"""Pipeline-parallel training for the flagship transformer.

The missing member of the parallelism matrix (dp/sp/tp/ep live in
models/transformer.py + models/sharding.py): layers split into P
contiguous stages over the ``pp`` mesh axis, driven by the 1F1B schedule
(parallel/pipeline.py — itself built on the reference's pt2pt ring,
SURVEY.md §2.2 "pairwise pt2pt: the core of PP").

Decomposition:

- **embedding** (embed + pos_embed): computed outside the pipeline on
  every rank (replicated math); its gradient comes back through the
  pipeline's input cotangents (``return_input_grads``).
- **stages**: the stacked layer params' leading ``n_layers`` axis is
  sharded over ``pp`` — each rank scans its ``L/P`` layers as one
  shape-preserving ``stage_fn``.
- **head** (ln_f_scale + lm_head): the last stage's loss head,
  differentiated via the pipeline's ``loss_params`` hook.

Gradients for the replicated pieces are psum'd over ``pp`` (only one
rank produces nonzero values — rank 0 for the embedding, rank P-1 for
the head — so the psum is a broadcast), exactly the §2.3 backend
property: collectives on device-resident shards, no host staging.

Composes with data parallelism: on a ("dp", "pp") mesh the batch is
dp-sharded outside, the pipeline runs per dp-slice, and gradients are
pmean'd over dp.

Composes with MoE: stages return their load-balance aux loss alongside
the activation and the 1F1B schedule threads it through
(``stage_aux_weight``) — the aux gradient rides the normal backward,
and the reported loss adds the psum'd aux term. Experts are
stage-local (dense routing per pp rank, no ep axis inside the
pipeline).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import optax

from hpc_patterns_tpu.models.transformer import (
    TransformerConfig,
    _layer,
    _rmsnorm,
    chunked_masked_causal_nll,
    init_params,
    masked_causal_nll,
)
from hpc_patterns_tpu.models.train import make_optimizer
from hpc_patterns_tpu.parallel.pipeline import pipeline_train_1f1b


def _embed(outer, tokens, cfg):
    dt = jnp.dtype(cfg.dtype)
    T = tokens.shape[-1]
    x = outer["embed"].astype(dt)[tokens]
    if cfg.pos_embed == "learned":
        x = x + outer["pos_embed"].astype(dt)[:T]
    return x


def _stage_fn(layers_shard, h, cfg):
    """One pipeline stage: scan this rank's L/P layers (shape-preserving,
    single-device math — mesh=None inside the pp rank). MoE configs
    return ``(h, aux)`` — the stage-local load-balance loss sum, which
    the 1F1B schedule threads through via ``stage_aux_weight`` (experts
    are stage-local here: dense routing per rank, no ep axis inside the
    pipeline)."""
    def body(carry, lp):
        x, aux = carry
        x, a = _layer(x, lp, cfg, mesh=None, act_spec=None)
        return (x, aux + a), None

    (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                           layers_shard)
    if cfg.n_experts:
        return h, aux
    return h


def _loss_head(lp, y, target_tokens, *, loss_chunk: int = 0):
    """Final-norm + LM head + the shared masked causal NLL
    (transformer.masked_causal_nll — identical loss semantics to
    transformer.loss_fn by construction). With ``loss_chunk`` the NLL is
    the online-logsumexp chunked form: the per-microbatch (b, T, vocab)
    logits never materialize, which is where the long-context memory
    wall bites hardest inside a pipeline stage (the 1F1B tick holds the
    stage's activations AND the loss head's intermediates live)."""
    x = _rmsnorm(y, lp["ln_f_scale"])
    if loss_chunk:
        return chunked_masked_causal_nll(
            x, lp["lm_head"].astype(y.dtype), target_tokens,
            chunk=loss_chunk,
        )
    logits = jnp.dot(x, lp["lm_head"].astype(y.dtype)).astype(jnp.float32)
    return masked_causal_nll(logits, target_tokens)


def pp_loss_and_grads(params, tokens, cfg: TransformerConfig, mesh,
                      *, microbatches: int, axis_pp: str = "pp",
                      axis_dp: str | None = None):
    """Mean causal-LM loss and full-parameter gradients via a 1F1B
    pipeline over ``axis_pp`` (optionally data-parallel over ``axis_dp``).

    ``params``: the standard init_params pytree (layers stacked on
    n_layers, which must divide by the pp axis size); ``tokens``:
    (batch, seq) int32, batch divisible by microbatches (× dp size).
    Loss and gradients are replicated on return (pipeline-internal
    validity masks are resolved by psum/pmean over the mesh axes).
    """
    M = microbatches
    pp = mesh.shape[axis_pp]
    L = cfg.n_layers
    if L % pp:
        raise ValueError(f"n_layers {L} must divide by pp={pp}")
    B = tokens.shape[0]
    dp = mesh.shape[axis_dp] if axis_dp else 1
    if B % (M * dp):
        raise ValueError(f"batch {B} must divide by microbatches*dp={M * dp}")

    outer = {"embed": params["embed"]}
    if cfg.pos_embed == "learned":
        outer["pos_embed"] = params["pos_embed"]
    head = {"ln_f_scale": params["ln_f_scale"], "lm_head": params["lm_head"]}

    def local(outer, layers_shard, head, tokens_local):
        toks = tokens_local.reshape(M, -1, tokens_local.shape[-1])
        x_mb = _embed(outer, toks, cfg)

        loss, layer_grads, extras = pipeline_train_1f1b(
            partial(_stage_fn, cfg=cfg),
            layers_shard,
            x_mb,
            toks,
            partial(_loss_head, loss_chunk=cfg.loss_chunk),
            axis_pp,
            loss_params=head,
            return_input_grads=True,
            stage_aux_weight=cfg.moe_aux_weight if cfg.n_experts else None,
        )

        # embedding backward: cotangents of the pipeline inputs (nonzero
        # on pp rank 0) pulled through the replicated embedding math
        _, embed_vjp = jax.vjp(lambda o: _embed(o, toks, cfg), outer)
        (outer_grads,) = embed_vjp(extras["input_grads"].astype(x_mb.dtype))

        # replicate the rank-local pieces: loss and head grads live on
        # the last pp rank, embedding grads on rank 0, so psum = broadcast
        loss = lax.psum(loss, axis_pp)
        if cfg.n_experts:
            # total load-balance loss: stage-local sums live per rank;
            # psum over pp = the sum over all layers, / M for the
            # per-microbatch mean (matching transformer.loss_fn, whose
            # aux is summed over layers on the whole batch)
            aux_mean = lax.psum(extras["aux_sum"], axis_pp) / M
            loss = loss + cfg.moe_aux_weight * aux_mean
        head_grads = jax.tree.map(lambda g: lax.psum(g, axis_pp),
                                  extras["loss_grads"])
        outer_grads = jax.tree.map(
            lambda g: lax.psum(
                jnp.where(lax.axis_index(axis_pp) == 0, g.astype(jnp.float32),
                          jnp.zeros_like(g, jnp.float32)),
                axis_pp,
            ),
            outer_grads,
        )
        grads_all = (outer_grads, layer_grads, head_grads)
        if axis_dp:
            loss = lax.pmean(loss, axis_dp)
            grads_all = jax.tree.map(lambda g: lax.pmean(g, axis_dp),
                                     grads_all)
        # grads are summed over microbatches; the loss head is per-
        # microbatch mean, so divide by M for the mean-loss gradient
        return loss[None], *jax.tree.map(lambda g: g / M, grads_all)

    layer_spec = P(axis_pp)   # leading n_layers axis -> L/P per rank
    tok_spec = P(axis_dp) if axis_dp else P()
    loss_r, outer_g, layer_g, head_g = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), layer_spec, P(), tok_spec),
        out_specs=(P(axis_pp) if not axis_dp else P((axis_dp, axis_pp)),
                   P(), layer_spec, P()),
        check_vma=False,  # validity masks + psum-broadcasts aren't VMA-provable
    )(outer, params["layers"], head, tokens)

    loss = loss_r[0]
    grads = {
        "embed": outer_g["embed"],
        "layers": layer_g,
        "ln_f_scale": head_g["ln_f_scale"],
        "lm_head": head_g["lm_head"],
    }
    if "pos_embed" in outer_g:
        grads["pos_embed"] = outer_g["pos_embed"]
    return loss, grads


def make_pp_train_step(cfg: TransformerConfig, mesh, *, microbatches: int,
                       axis_pp: str = "pp", axis_dp: str | None = None,
                       optimizer=None):
    """Jitted ``step(params, opt_state, tokens) -> (loss, params,
    opt_state)`` training the full model through the 1F1B pipeline."""
    optimizer = optimizer or make_optimizer()

    def step(params, opt_state, tokens):
        loss, grads = pp_loss_and_grads(
            params, tokens, cfg, mesh, microbatches=microbatches,
            axis_pp=axis_pp, axis_dp=axis_dp,
        )
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    return jax.jit(step, donate_argnums=(0, 1))


def init_pp_train_state(key, cfg: TransformerConfig, optimizer=None):
    """f32 params + opt state (replicated; the layer stack's leading axis
    is what the pp shard_map slices)."""
    optimizer = optimizer or make_optimizer()
    params = init_params(key, cfg)
    return params, optimizer.init(params)
