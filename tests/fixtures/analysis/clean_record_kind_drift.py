"""Known-clean: every written RunLog kind is either dispatched by a
consumer or declared write-only in FORENSIC_KINDS, and every dispatch
matches a live producer. Zero findings expected."""

FORENSIC_KINDS = ("engine_debug",)


def run_round(log, stats):
    log.emit(kind="engine_round", tok_s=stats["tok_s"])
    # forensic: raw per-round journal for post-mortem grep only
    log.emit(kind="engine_debug", raw=stats)


def summarize(records):
    rounds = [r for r in records if r.get("kind") == "engine_round"]
    return len(rounds)
