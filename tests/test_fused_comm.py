"""Parity suite for the device-initiated fused ring collectives
(``comm/fused.py``).

Every fused kernel runs under Pallas interpret mode on the virtual CPU
mesh (conftest) and is compared BYTE-EXACT against its host-driven
oracle: ``fused_allreduce`` against ``ring.ring_allreduce_chunked``
over the identical padded chunk layout (same combine order, so floats
match bitwise, not just to tolerance), ``allgather_matmul`` against
the gather-then-tiles reference, ``fused_permute`` against
``lax.ppermute``. The dtype axis (float32 / bfloat16 / int32), the
non-power-of-two and non-divisible shard shapes, and every ring size a
submesh of the 8-device mesh offers are all swept, because each is a
distinct way for chunk bookkeeping to go wrong silently.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hpc_patterns_tpu.analysis import runtime as analysis_runtime
from hpc_patterns_tpu.comm import Communicator, fused, ring
from hpc_patterns_tpu.topology import shard_map

WORLD = 8


@pytest.fixture(scope="module", autouse=True)
def strict_sems():
    """The strict-semaphore interpret shim over the WHOLE battery:
    every fused kernel traced by these tests has its DMA semaphore
    ledger balance-asserted at kernel exit (analysis/runtime.py) — so
    the bug class PR 8 caught by eyeball (double-waited send sems,
    undrained DMAs) fails here, in one test, not on silicon. No cache
    clear: every test builds FRESH jit wrappers, which always
    re-trace, so the kernel bodies run through the patched
    ``pallas_call`` regardless (a mid-suite ``jax.clear_caches()``
    would cost the rest of tier-1 its warm traces). Engagement is
    asserted by ``test_strict_shim_is_engaged``, a selected test —
    not at teardown, where a ``-k``-filtered run that traces no
    kernel would fail spuriously."""
    with analysis_runtime.strict_semaphores() as ledger:
        yield ledger


def test_strict_shim_is_engaged(strict_sems):
    """Proof the shim is live over this module: tracing one fused
    kernel must increment the ledger's checked-kernel count — an
    inert shim would silently void the whole battery's sync-protocol
    guarantee."""
    before = strict_sems.kernels_checked
    mesh = submesh(4)
    x = jnp.arange(4 * 2 * 8, dtype=jnp.float32).reshape(8, 8)
    out = shmap(lambda l: fused.fused_allreduce(l, "x"), mesh)(x)
    jax.block_until_ready(out)
    assert strict_sems.kernels_checked > before


@pytest.fixture(scope="module")
def comm():
    from hpc_patterns_tpu import topology

    return Communicator(topology.make_mesh({"x": WORLD}), "x")


def submesh(size: int) -> Mesh:
    return Mesh(np.asarray(jax.devices()[:size]), ("x",))


def shmap(fn, mesh, n_in=1, out_specs=P("x", None)):
    spec = P("x", None)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                             out_specs=out_specs))


def rand(rng, size, n, dtype):
    x = (rng.normal(size=(size, n)) * 8).astype(np.float32)
    if dtype == "int32":
        return x.astype(np.int32)
    return jnp.asarray(x).astype(dtype)


def host_ring_oracle(mesh, x, n):
    """The byte-exact host-driven oracle: pad the scatter axis to the
    SAME chunk layout the fused wrapper uses (fused.ring_layout), run
    the host two-phase ring, slice the pad back off. Identical chunk
    walk + combine order == identical bytes, every dtype."""
    size = mesh.shape["x"]
    _, _, _, n_pad = fused.ring_layout((1, n), size, interpret=True)
    xp = jnp.pad(jnp.asarray(x), ((0, 0), (0, n_pad - n)))
    out = shmap(
        lambda l: ring.ring_allreduce_chunked(l, "x", scatter_axis=1),
        mesh)(xp)
    return np.asarray(out)[:, :n]


class TestFusedAllreduceParity:
    # 40 = non-divisible by 8 and by 3; covers the pad-and-slice path
    # on most sizes and the divisible path on size 2/4/5
    @pytest.mark.parametrize("size", [2, 3, 4, 5, 6, 7, 8])
    def test_every_ring_size_matches_host_ring(self, size):
        mesh = submesh(size)
        x = rand(np.random.default_rng(size), size, 40, "float32")
        got = np.asarray(
            shmap(lambda l: fused.fused_allreduce(l, "x"), mesh)(x))
        np.testing.assert_array_equal(got, host_ring_oracle(mesh, x, 40))

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int32"])
    @pytest.mark.parametrize("n", [64, 65])
    def test_dtypes_and_shapes_match_host_ring(self, comm, dtype, n):
        # 64 divides the 8-ring exactly; 65 exercises padding
        x = rand(np.random.default_rng(1), WORLD, n, dtype)
        got = np.asarray(
            shmap(lambda l: fused.fused_allreduce(l, "x"), comm.mesh)(x))
        np.testing.assert_array_equal(
            got, host_ring_oracle(comm.mesh, x, n))

    def test_matches_collective_to_tolerance(self, comm):
        # the library collective reduces in a different association
        # order — allclose, not equal, is the right claim
        x = rand(np.random.default_rng(2), WORLD, 64, "float32")
        got = np.asarray(comm.allreduce(comm.shard(x), "fused"))
        ref = np.asarray(comm.allreduce(comm.shard(x), "collective"))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_communicator_route_is_the_kernel(self, comm):
        x = rand(np.random.default_rng(3), WORLD, 40, "float32")
        got = np.asarray(comm.allreduce(comm.shard(x), "fused"))
        np.testing.assert_array_equal(
            got, host_ring_oracle(comm.mesh, x, 40))

    def test_int32_sum_is_exact(self, comm):
        x = rand(np.random.default_rng(4), WORLD, 40, "int32")
        got = np.asarray(comm.allreduce(comm.shard(x), "fused"))
        want = x.sum(axis=0, dtype=np.int32)
        np.testing.assert_array_equal(got,
                                      np.broadcast_to(want, got.shape))


class TestAllreduceInto:
    def test_bias_and_epilogue_fused_exactly(self, comm):
        rng = np.random.default_rng(5)
        x = rand(rng, WORLD, 40, "float32")
        bias = rng.normal(size=(40,)).astype(np.float32)
        got = np.asarray(comm.allreduce_into(
            comm.shard(x), bias=bias, epilogue=jax.nn.relu,
            algorithm="fused"))
        want = np.maximum(host_ring_oracle(comm.mesh, x, 40) + bias, 0)
        np.testing.assert_array_equal(got, want)

    def test_widening_epilogue_keeps_dtype_on_both_routes(self, comm):
        # an epilogue computing in f32 must land back in the
        # collective's dtype on BOTH routes — the oracle-pair
        # contract. int32 input: the reduction is order-exact, so the
        # routes must agree to the byte even through the widen+round
        x = rand(np.random.default_rng(13), WORLD, 32, "int32")
        widen = lambda v: v.astype(jnp.float32) * 1.5  # noqa: E731
        got = comm.allreduce_into(comm.shard(x), epilogue=widen,
                                  algorithm="fused")
        ref = comm.allreduce_into(comm.shard(x), epilogue=widen,
                                  algorithm="collective")
        assert got.dtype == ref.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_host_route_agrees_to_tolerance(self, comm):
        rng = np.random.default_rng(6)
        x = rand(rng, WORLD, 64, "float32")
        bias = rng.normal(size=(64,)).astype(np.float32)
        got = np.asarray(comm.allreduce_into(
            comm.shard(x), bias=bias, algorithm="fused"))
        ref = np.asarray(comm.allreduce_into(
            comm.shard(x), bias=bias, algorithm="collective"))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


class TestAllgatherMatmul:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int32"])
    def test_matches_reference_bitwise(self, comm, dtype):
        rng = np.random.default_rng(7)
        x = np.asarray(rand(rng, WORLD, 3 * 16, dtype)).reshape(
            WORLD, 3, 16)
        w = np.asarray(rand(rng, WORLD, 16 * 8, dtype)).reshape(
            WORLD, 16, 8)
        got = np.asarray(comm.allgather_matmul(x, w, "fused"))
        want = np.asarray(comm.allgather_matmul(x, w, "collective"))
        assert got.shape == (WORLD, WORLD * 3, 8)
        np.testing.assert_array_equal(got, want)

    def test_reference_math(self, comm):
        # the host route itself against a plain numpy contraction
        rng = np.random.default_rng(8)
        x = rng.normal(size=(WORLD, 2, 16)).astype(np.float32)
        w = rng.normal(size=(WORLD, 16, 4)).astype(np.float32)
        out = np.asarray(comm.allgather_matmul(x, w, "collective"))
        gathered = x.reshape(WORLD * 2, 16)
        for r in range(WORLD):
            np.testing.assert_allclose(out[r], gathered @ w[r],
                                       rtol=1e-5, atol=1e-6)

    def test_rejects_bad_shapes(self, comm):
        with pytest.raises(ValueError, match="size, m, k"):
            comm.allgather_matmul(np.ones((WORLD, 4)),
                                  np.ones((WORLD, 4, 4)))
        with pytest.raises(ValueError, match="not in"):
            comm.allgather_matmul(np.ones((WORLD, 2, 4)),
                                  np.ones((WORLD, 4, 4)),
                                  algorithm="ring")


class TestFusedPermute:
    def test_ring_shift_matches_ppermute(self, comm):
        x = rand(np.random.default_rng(9), WORLD, 24, "float32")
        for shift in (1, -1, 3):
            got = np.asarray(shmap(
                lambda l: fused.fused_ring_shift(l, "x", shift),
                comm.mesh)(x))
            want = np.asarray(shmap(
                lambda l: ring.ring_shift(l, "x", shift),
                comm.mesh)(x))
            np.testing.assert_array_equal(got, want)

    def test_arbitrary_permutation(self, comm):
        # pairwise swap (the ping-pong pattern) through the fused route
        x = rand(np.random.default_rng(10), WORLD, 24, "float32")
        perm = [(i, i ^ 1) for i in range(WORLD)]
        ring.check_permutation(perm, WORLD)
        got = np.asarray(shmap(
            lambda l: fused.fused_permute(l, "x", perm), comm.mesh)(x))
        np.testing.assert_array_equal(
            got, np.asarray(x)[[r ^ 1 for r in range(WORLD)]])

    def test_high_rank_blocks_roundtrip(self, comm):
        # 4-D K/V-block shape, the ring-attention payload
        x = np.random.default_rng(11).normal(
            size=(WORLD, 2, 4, 3, 8)).astype(np.float32)
        got = np.asarray(jax.jit(shard_map(
            lambda l: fused.fused_ring_shift(l, "x", 1), mesh=comm.mesh,
            in_specs=P("x"), out_specs=P("x")))(x))
        np.testing.assert_array_equal(
            got, x[(np.arange(WORLD) - 1) % WORLD])

    def test_malformed_pairs_rejected(self, comm):
        with pytest.raises(ValueError, match="duplicate"):
            shmap(lambda l: fused.fused_permute(
                l, "x", [(i, 0) for i in range(WORLD)]), comm.mesh)(
                    np.ones((WORLD, 8), np.float32))


class TestRingAttentionFusedShift:
    def test_fused_shift_matches_ppermute_bitwise(self, comm):
        from hpc_patterns_tpu import parallel

        rng = np.random.default_rng(12)
        q, k, v = (rng.normal(size=(2, WORLD * 4, 2, 8)
                              ).astype(np.float32) for _ in range(3))
        spec = P(None, "x", None, None)

        def run(shift_impl):
            fn = jax.jit(shard_map(
                lambda a, b, c: parallel.ring_attention(
                    a, b, c, "x", causal=True, shift_impl=shift_impl),
                mesh=comm.mesh, in_specs=(spec,) * 3, out_specs=spec))
            return np.asarray(fn(q, k, v))

        np.testing.assert_array_equal(run("fused"), run("ppermute"))

    def test_rejects_unknown_shift_impl(self):
        from hpc_patterns_tpu import parallel

        with pytest.raises(ValueError, match="shift_impl"):
            parallel.ring_attention(
                jnp.ones((1, 8, 1, 4)), jnp.ones((1, 8, 1, 4)),
                jnp.ones((1, 8, 1, 4)), "x", shift_impl="nope")


class TestGuardsAndCaching:
    def test_fused_prod_refused(self):
        with pytest.raises(ValueError, match="prod"):
            fused.fused_allreduce(jnp.ones((2, 2)), "x", op="prod")

    def test_jit_allreduce_one_compile_per_key(self, comm):
        """The satellite claim: sweeping algorithms at one shape holds
        ONE traced closure per (shape, dtype, algorithm) — repeated
        calls return the same object and its jit cache stays at 1."""
        from hpc_patterns_tpu.harness.trace import jit_cache_size

        x = comm.shard(np.ones((WORLD, 32), np.float32))
        fns = {}
        for alg in ("fused", "collective", "ring", "ring_chunked"):
            f1 = comm.jit_allreduce(x, alg)
            f2 = comm.jit_allreduce(x, alg)
            assert f1 is f2, alg
            jax.block_until_ready(f1(x))
            jax.block_until_ready(f1(x))
            assert jit_cache_size(f1, strict=True) == 1, alg
            fns[alg] = f1
        assert len(set(map(id, fns.values()))) == 4
        # a different shape gets its own slot, old keys stay warm
        y = comm.shard(np.ones((WORLD, 16), np.float32))
        assert comm.jit_allreduce(y, "fused") is not fns["fused"]
        assert comm.jit_allreduce(x, "fused") is fns["fused"]


# every factorization the 8-device mesh offers, paired with each of
# its axes — the full (mesh, ring) product the multi-axis lift claims
MULTIAXIS_CASES = [
    pytest.param(axes, axis, id=f"{'x'.join(map(str, axes.values()))}-{axis}")
    for axes in ({"a": 2, "b": 4}, {"a": 4, "b": 2},
                 {"a": 2, "b": 2, "c": 2})
    for axis in axes
]


def multiaxis_host_oracle(mesh, axis, x, n):
    """:func:`host_ring_oracle` generalized to one axis of a
    multi-axis mesh: the host two-phase ring runs on the REAL mesh
    (XLA's discharge-free path has no single-axis restriction), padded
    to the identical fused chunk layout."""
    from jax.sharding import NamedSharding

    size = mesh.shape[axis]
    _, _, _, n_pad = fused.ring_layout((1, n), size, interpret=True)
    xp = jnp.pad(jnp.asarray(x), ((0, 0), (0, n_pad - n)))
    spec = P(axis, None)
    fn = jax.jit(shard_map(
        lambda l: ring.ring_allreduce_chunked(l, axis, scatter_axis=1),
        mesh=mesh, in_specs=spec, out_specs=spec))
    out = fn(jax.device_put(xp, NamedSharding(mesh, spec)))
    return np.asarray(out)[:, :n]


class TestMultiAxisFused:
    """The multi-axis lift: the fused kernels run over one axis of a
    2-D torus / multi-slice mesh via the flat-mesh route (neighbor ids
    from mesh coordinates — fused.RingGeometry), bitwise-equal to the
    host ring running natively on the multi-axis mesh."""

    @pytest.mark.parametrize("axes,axis", MULTIAXIS_CASES)
    def test_fused_allreduce_matches_host_ring(self, axes, axis):
        from hpc_patterns_tpu import topology

        mesh = topology.make_mesh(axes)
        c = Communicator(mesh, axis)
        x = rand(np.random.default_rng(c.size), c.size, 40, "float32")
        got = np.asarray(c.allreduce(c.shard(x), "fused"))
        np.testing.assert_array_equal(
            got, multiaxis_host_oracle(mesh, axis, x, 40))

    @pytest.mark.parametrize("axes,axis", MULTIAXIS_CASES)
    def test_fused_ring_shift_matches_host_shift(self, axes, axis):
        from jax.sharding import NamedSharding

        from hpc_patterns_tpu import topology

        mesh = topology.make_mesh(axes)
        g = fused.mesh_ring_geometry(mesh, axis)
        fm = fused.flat_mesh(mesh)
        x = rand(np.random.default_rng(7), g.size, 24, "float32")

        spec = P(fused.FLAT_AXIS, None)
        fn = jax.jit(shard_map(
            lambda l: fused.fused_ring_shift(l, fused.FLAT_AXIS,
                                             geometry=g),
            mesh=fm, in_specs=spec, out_specs=spec))
        xf = jax.device_put(
            jnp.take(jnp.asarray(x), jnp.asarray(g.positions()), axis=0),
            NamedSharding(fm, spec))
        full = np.asarray(fn(xf))

        rspec = P(axis, None)
        host = jax.jit(shard_map(
            lambda l: ring.ring_shift(l, axis, 1),
            mesh=mesh, in_specs=rspec, out_specs=rspec))
        want = np.asarray(host(jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, rspec))))

        np.testing.assert_array_equal(full[g.ring_ids()], want)
        # replica discipline: every flat rank sharing a ring position
        # computed the identical row, bit for bit
        pos = g.positions()
        for f in range(g.total):
            np.testing.assert_array_equal(
                full[f], full[pos[f] * g.stride])

    def test_allgather_matmul_multiaxis_matches_reference(self):
        from hpc_patterns_tpu import topology

        mesh = topology.make_mesh({"a": 2, "b": 4})
        c = Communicator(mesh, "b")
        rng = np.random.default_rng(3)
        x = rng.normal(size=(4, 2, 8)).astype(np.float32)
        w = rng.normal(size=(4, 8, 4)).astype(np.float32)
        got = np.asarray(c.allgather_matmul(x, w, "fused"))
        ref = np.asarray(c.allgather_matmul(x, w, "collective"))
        np.testing.assert_array_equal(got, ref)

    def test_jit_cache_one_compile_per_shape_dtype_axis(self):
        """The sweep-discipline pin: on ONE multi-axis mesh, a
        communicator per axis holds one compiled fused closure per
        (shape, dtype, axis) — repeat calls hit the same wrapper and
        its jit cache stays at 1, so an axis sweep never thrashes."""
        from hpc_patterns_tpu import topology
        from hpc_patterns_tpu.harness.trace import jit_cache_size

        mesh = topology.make_mesh({"a": 2, "b": 4})
        for axis in ("a", "b"):
            c = Communicator(mesh, axis)
            x = c.shard(np.ones((c.size, 32), np.float32))
            f1 = c.jit_allreduce(x, "fused")
            assert c.jit_allreduce(x, "fused") is f1, axis
            jax.block_until_ready(f1(x))
            jax.block_until_ready(f1(x))
            assert jit_cache_size(f1, strict=True) == 1, axis
            key = ((c.size, 32), "float32", axis, "fused")
            assert key in c._jit_allreduce_cache, axis


class TestScheduleFingerprints:
    def test_fused_route_fingerprinted_with_algorithm(self, comm,
                                                      tmp_path,
                                                      monkeypatch):
        """The verifier must not go blind on the fast path: an eager
        fused allreduce under an exported trace dir records the same
        (op, seq, shape, dtype, axis) chain entry as the host paths,
        plus the algorithm field that joined the fingerprint."""
        from hpc_patterns_tpu.analysis import runtime as art

        monkeypatch.setenv(art.ENV_TRACE_DIR, str(tmp_path))
        monkeypatch.setenv(art.ENV_PROCESS_ID, "0")
        art.reset_collective_schedule()
        x = comm.shard(np.ones((WORLD, 24), np.float32))
        comm.allreduce(x, "fused")
        comm.allreduce(x, "collective")
        sched = art.collective_schedule().snapshot()
        assert sched["n"] == 2
        e_fused, e_coll = sched["entries"]
        assert e_fused["op"] == "allreduce.fused"
        assert e_fused["algorithm"] == "fused"
        assert e_fused["shape"] == [WORLD, 24]
        assert e_fused["axis"] == "x"
        assert e_coll["algorithm"] == "collective"
        assert e_coll["seq"] == e_fused["seq"] + 1
        # and two chains that differ ONLY in algorithm diverge
        a = art.CollectiveSchedule()
        b = art.CollectiveSchedule()
        a.record("allreduce", 0, shape=(8, 4), dtype="float32",
                 axis="x", algorithm="fused")
        b.record("allreduce", 0, shape=(8, 4), dtype="float32",
                 axis="x", algorithm="collective")
        assert a.digest != b.digest
        art.reset_collective_schedule()
