"""Concurrency benchmark app — the rebuild of ``sycl_con`` / ``omp_con`` /
``omp_con_meta`` (C1–C3 in SURVEY.md).

Measures whether independent device commands (compute ``C``, host→device
``M2D``, device→host ``D2M``) overlap, exactly as the reference does
(sycl_con.cpp:163-297):

- positional mode + command list CLI (:184-232), with the reference's
  mode names accepted as aliases (``out_of_order``/``in_order`` →
  ``async``, ``host_threads`` → ``threads``, plus omp_con's ``nowait``);
- ``-1`` = autotune sentinels for sizes/tripcount (:179-232), resolved by
  the C12 autotuner (balance copies :243-255, tripcount :257-268);
- serial baseline → theoretical max speedup → concurrent run → verdict
  (:274-296), with both the SYCL speedup rule and the OMP absolute rule
  (omp_con.cpp:238-244) selectable via ``--rule`` — the one-binary-all-
  modes role of ``omp_con_meta``'s metadirectives;
- ``--n-queues`` spreads commands round-robin over devices
  (``Qs[i % n_queues]``, sycl_con.cpp:58-61,89), the queue-pool analog;
- ``--enable_profiling`` wraps the concurrent run in a ``jax.profiler``
  trace (run.sh:10-12's overhead re-check, now with real artifacts).
"""

from __future__ import annotations

import sys

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.concurrency import autotune, commands as cmds, engine
from hpc_patterns_tpu.harness import RunLog, concurrency_verdict
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import AUTO, base_parser
from hpc_patterns_tpu.harness.profiling import maybe_trace

DEFAULT_COPY_ELEMENTS = 1 << 22  # 16 MiB float32; ref default is
# max_mem_alloc_size (sycl_con.cpp:168-172), far past useful on TPU hosts


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument(
        "mode",
        nargs="?",
        default="async",
        help="dispatch mode: serial | async | threads "
        "(aliases: out_of_order, in_order, nowait, host_threads)",
    )
    p.add_argument(
        "commands",
        nargs="*",
        default=["C", "M2D"],
        help="command list, e.g. C M2D (default) — sycl_con.cpp positional list",
    )
    p.add_argument("--tripcount", type=int, default=AUTO,
                   help="compute trips; -1 = autotune to mean copy time")
    p.add_argument("--copy-elements", type=int, default=AUTO,
                   help="copy size in float32 elements; -1 = default + balance")
    p.add_argument("--compute-elements", type=int, default=8 * 128,
                   help="compute buffer elements (one VPU tile by default)")
    p.add_argument("--n-queues", type=int, default=1,
                   help="devices to round-robin commands over (queue pool analog)")
    p.add_argument("--rule", default="sycl", choices=["sycl", "omp"],
                   help="verdict rule: sycl speedup (sycl_con) or omp absolute (omp_con)")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "dispatch", "onchip"],
                   help="overlap mechanism: 'dispatch' races jit calls "
                        "across devices/streams (the queue-pool analog); "
                        "'onchip' runs the whole experiment inside ONE "
                        "Pallas kernel (HBM<->VMEM DMA vs VPU compute) — "
                        "where single-chip TPU concurrency actually "
                        "lives; 'auto' picks onchip on a TPU backend "
                        "(n_queues<=1, supported pair)")
    p.add_argument("--enable_profiling", action="store_true",
                   help="jax.profiler trace around the concurrent run")
    p.add_argument("--trace-dir", default=None, help="profiler output dir")
    return p


def build_commands(args, devices) -> tuple[list[cmds.Command], dict]:
    kinds = [k.upper() for k in args.commands]
    for k in kinds:
        if k not in ("C", "M2D", "D2M"):
            raise SystemExit(f"unknown command {k!r} (want C, M2D, or D2M)")

    m2d_elems = d2m_elems = (
        DEFAULT_COPY_ELEMENTS if args.copy_elements == AUTO else args.copy_elements
    )
    tune_info = {}
    if args.copy_elements == AUTO and "M2D" in kinds and "D2M" in kinds:
        m2d_elems, d2m_elems, info = autotune.balance_copy_sizes(
            m2d_elems, d2m_elems, devices[0]
        )
        tune_info["balance"] = info

    tripcount = args.tripcount
    if tripcount == AUTO and "C" in kinds:
        copy_cmds = []
        if "M2D" in kinds:
            copy_cmds.append(cmds.CopyM2DCommand(m2d_elems, devices[0]))
        if "D2M" in kinds:
            copy_cmds.append(cmds.CopyD2MCommand(d2m_elems, devices[0]))
        if copy_cmds:
            tripcount, info = autotune.tune_tripcount_to_copies(
                copy_cmds,
                compute_elements=args.compute_elements,
                device=devices[0],
            )
            tune_info["tripcount"] = info
        else:
            tripcount = 1000
    elif tripcount == AUTO:
        tripcount = 1000

    built = []
    for i, k in enumerate(kinds):
        dev = devices[i % max(1, args.n_queues) % len(devices)]
        if k == "C":
            built.append(cmds.ComputeCommand(args.compute_elements, tripcount, dev))
        elif k == "M2D":
            built.append(cmds.CopyM2DCommand(m2d_elems, dev))
        else:
            built.append(cmds.CopyD2MCommand(d2m_elems, dev))
    return built, tune_info


# on-chip engine: command pair -> ((command, baseline mode) per command,
# serial mode, overlap mode). Resources: C occupies the (sequential)
# TensorCore, copies share HBM bandwidth — the verdict floor is
# resource-aware.
_ONCHIP_PAIRS = {
    ("C", "M2D"): (
        (("M2D", "dma"), ("C", "compute")), "serial", "overlap"),
    ("C", "D2M"): (
        (("D2M", "dma_out"), ("C", "compute")), "serial_out", "overlap_out"),
    ("D2M", "M2D"): (
        (("M2D", "dma"), ("D2M", "dma_out")), "pair_serial", "pair_overlap"),
    ("C", "C"): (
        (("C", "compute"), ("C", "compute")), "compute2", "compute2"),
}
_RESOURCE = {"C": "core", "M2D": "hbm", "D2M": "hbm"}
_ONCHIP_CHUNKS = 16


def _onchip_supported(args, mode) -> bool:
    kinds = tuple(sorted(k.upper() for k in args.commands))
    import jax

    return (
        kinds in _ONCHIP_PAIRS
        and mode in ("serial", "async")
        and args.n_queues <= 1
        and jax.default_backend() == "tpu"
    )


def _record_overlap_metrics(engine_name, names, serial_s, concurrent_s,
                            verdict) -> None:
    """Overlap outcome gauges (no-op when --metrics is off): the
    serial/concurrent pair and the achieved speedup, keyed by
    ``<engine>.<mode>`` and the command pair so a sweep over modes
    accumulates the full matrix instead of overwriting one key."""
    m = metricslib.get_metrics()
    if not m.enabled:
        return
    pair = "+".join(names)
    m.gauge(f"concurrency.{engine_name}.{pair}.serial_s").set(serial_s)
    m.gauge(f"concurrency.{engine_name}.{pair}.concurrent_s").set(
        concurrent_s)
    if verdict.speedup is not None:
        m.gauge(f"concurrency.{engine_name}.{pair}.speedup").set(
            verdict.speedup)


def run_onchip(args, log, mode) -> int:
    """C1's experiment as ONE Pallas kernel: the copy commands are
    HBM↔VMEM DMA streams, the compute command is the busy-wait chain,
    and overlap is double-buffering inside the kernel — the TPU-native
    location of single-device copy/compute concurrency (async dispatch
    between jit calls serializes on one TensorCore, so the reference's
    queue-race formulation physically cannot overlap there)."""
    import jax

    from hpc_patterns_tpu.concurrency import pipeline

    kinds = tuple(sorted(k.upper() for k in args.commands))
    baselines, serial_mode, overlap_mode = _ONCHIP_PAIRS[kinds]
    (name_a, base_a), (name_b, base_b) = baselines
    names = [name_a, name_b]

    elems = (
        _ONCHIP_CHUNKS * 2048 * 128
        if args.copy_elements == AUTO else args.copy_elements
    )
    rows = max(8, (elems // (_ONCHIP_CHUNKS * 128) + 7) // 8 * 8)
    x = jax.block_until_ready(pipeline.make_hbm_array(_ONCHIP_CHUNKS, rows))
    per_pass = lambda m, t: pipeline.per_pass_seconds(x, m, t, repetitions=5)

    # C12 balance: tripcount so the chain matches the copy baseline
    # (shared pipeline.balance_tripcount); pure-copy and pure-compute
    # pairs skip it
    trips = args.tripcount if args.tripcount != AUTO else 64
    t_a = per_pass(base_a, trips)
    t_b = per_pass(base_b, trips)
    if args.tripcount == AUTO and "C" in kinds and base_a != base_b:
        trips, t_b = pipeline.balance_tripcount(per_pass, t_a, base_b, trips)
        log.emit(kind="autotune", which="onchip_tripcount", tripcount=trips,
                 t_copy_us=t_a * 1e6, t_compute_us=t_b * 1e6)
        log.print(f"autotune[onchip_tripcount]: trips={trips} "
                  f"copy {t_a * 1e6:.2f} us vs compute {t_b * 1e6:.2f} us")

    per_times = [t_a, t_b]
    for name, t in zip(names, per_times):
        log.print(f"serial {name}: {t * 1e6:.3f} us/pass")

    if mode == "serial":
        log.emit(kind="result", name="concurrency[onchip:serial]",
                 success=True, commands=names,
                 per_command_us=[t * 1e6 for t in per_times])
        log.print("SUCCESS")
        return 0

    # C C maps serial_mode == overlap_mode == "compute2" (two chains on
    # the one core at the SAME per-chain tripcount as the baselines —
    # per-trip cost is nonlinear in tripcount, so one chain at 2x trips
    # is not a valid stand-in); speedup ~1.0 against the resource floor
    t_serial = per_pass(serial_mode, trips)
    t_concurrent = (
        t_serial if overlap_mode == serial_mode
        else per_pass(overlap_mode, trips)
    )
    log.print(f"measured serial total: {t_serial * 1e6:.3f} us/pass")

    with maybe_trace(args.enable_profiling, args.trace_dir) as trace_dir:
        if trace_dir:
            # one traced run so the profiler artifact shows the kernel
            jax.block_until_ready(pipeline.overlap_run(
                x, mode=overlap_mode, tripcount=trips, passes=100))
            log.print(f"profiler trace: {trace_dir}")

    resources = [_RESOURCE[k] for k in names]
    verdict = concurrency_verdict(
        per_times, t_concurrent, rule=args.rule, resources=resources
    )
    _record_overlap_metrics(f"onchip.{mode}", names, t_serial,
                            t_concurrent, verdict)
    log.result(
        f"concurrency[onchip:{'+'.join(names)}]",
        verdict,
        commands=names,
        mode=mode,
        engine="onchip",
        rule=args.rule,
        resources=resources,
        tripcount=trips,
        serial_us=t_serial * 1e6,
        concurrent_us=t_concurrent * 1e6,
        per_command_us=[t * 1e6 for t in per_times],
    )
    return verdict.exit_code


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    mode = engine.canonical_mode(args.mode)
    if args.engine == "onchip" or (
        args.engine == "auto" and _onchip_supported(args, mode)
    ):
        if args.engine == "onchip" and not _onchip_supported(args, mode):
            log.print("ERROR: --engine onchip needs a real TPU backend, "
                      "mode serial/async (aliases included), n_queues<=1, "
                      f"and a supported command pair {sorted(_ONCHIP_PAIRS)}")
            log.print("FAILURE")
            return 1
        return run_onchip(args, log, mode)
    devices = topology.get_devices(args.backend)
    command_list, tune_info = build_commands(args, devices)
    names = [c.name for c in command_list]
    for key, info in tune_info.items():
        log.emit(kind="autotune", which=key, **info)
        log.print(f"autotune[{key}]: {info}")

    serial = engine.bench(
        "serial", command_list, repetitions=args.repetitions, warmup=args.warmup
    )
    per_times = [t.min_s for t in serial.per_command]
    for name, t in zip(names, per_times):
        log.print(f"serial {name}: {t * 1e3:.3f} ms")
    log.print(f"best serial total: {serial.best_serial_total_s * 1e3:.3f} ms")

    if mode == "serial":
        log.emit(kind="result", name="concurrency[serial]", success=True,
                 commands=names, per_command_ms=[t * 1e3 for t in per_times])
        log.print("SUCCESS")
        return 0

    with maybe_trace(args.enable_profiling, args.trace_dir) as trace_dir:
        concurrent = engine.bench(
            mode, command_list, repetitions=args.repetitions, warmup=args.warmup
        )
    if trace_dir:
        log.print(f"profiler trace: {trace_dir}")

    verdict = concurrency_verdict(
        per_times, concurrent.total.min_s, rule=args.rule
    )
    _record_overlap_metrics(f"dispatch.{mode}", names,
                            serial.best_serial_total_s,
                            concurrent.total.min_s, verdict)
    log.result(
        f"concurrency[{mode}:{'+'.join(names)}]",
        verdict,
        commands=names,
        mode=mode,
        rule=args.rule,
        serial_total_ms=serial.best_serial_total_s * 1e3,
        concurrent_total_ms=concurrent.total.min_s * 1e3,
        per_command_ms=[t * 1e3 for t in per_times],
        trace_dir=trace_dir,
    )
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
