"""Cross-rank trace collection: clock-aligned merge + skew rollups.

Rung 4 of the observability ladder. Rungs 1–3 (metrics histograms,
flight recorder + Chrome-trace export, regression gate) see exactly one
process — but the reference's miniapps only ever run under ``mpirun
-np 4``, and for communication patterns the interesting signal IS
cross-rank: collective skew, stragglers, and the rank-MAX timing rule
the suite already uses (PAPERS.md: stream-aware message passing and
GPU-communication analyses both work from per-rank stream timelines).

The pipeline:

1. **Per-rank capture** — each child of ``apps/launch.py`` running with
   ``--trace`` writes its recorder snapshot (the ``kind=trace`` payload,
   stamped with ``process`` identity and clock anchors) to the
   launcher-provided ``HPCPAT_TRACE_DIR`` as ``rank<id>.trace.json``
   (apps/common.run_instrumented → trace.write_rank_snapshot).
2. **Clock-aligned merge** (this module) — per-rank clock offsets are
   estimated from each snapshot's two monotonic↔wall anchor pairs
   (drift-bounded by their disagreement), then refined by barrier-echo
   sync anchors when every rank carries them (all ranks exit a global
   barrier within its release-propagation window — micro-seconds on one
   host, network-RTT across hosts — far tighter than NTP wall-clock
   skew). The per-rank rings merge into ONE Chrome-trace/Perfetto JSON
   with one ``pid`` lane per rank, and Perfetto flow events link the N
   per-rank slices of the same collective — matched by slice name +
   sequence index (``comm/communicator.py``'s per-communicator counter,
   ``harness/timing.py``'s repetition index) — so allreduce skew is
   visible as a fan of arrows.
3. **Cross-rank rollups** — per-collective skew (max−min start,
   max−min duration), per-rank busy/bubble fractions over the device
   track, and a straggler table (which rank finished last, how often),
   printed by the CLI and carried as one ``kind=trace_merged`` RunLog
   record that ``harness.report`` renders.

Usage::

    python -m hpc_patterns_tpu.harness.collect rankdir/ -o merged.json
    python -m hpc_patterns_tpu.apps.launch -np 2 --trace-out merged.json \
        -- python -m hpc_patterns_tpu.apps.allreduce_app -p 8 --trace

Exit 0 on a merge (even with nothing matched — the lanes still help);
2 on unreadable input / no snapshots.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Iterable

from hpc_patterns_tpu.harness import trace as tracelib

# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_rank_snapshots(paths: Iterable[str | Path]) -> list[dict[str, Any]]:
    """Flight-recorder snapshots from ``paths``: directories are
    globbed for the per-rank handoff files (``rank*.trace.json``),
    ``.json`` files are read as one snapshot object, and anything else
    is treated as a runlog JSONL whose ``kind=trace`` records are the
    snapshots (so a merged view can also be built from N per-rank
    ``--log`` files). Unparseable lines are skipped, same tolerance as
    harness.report."""
    snaps: list[dict[str, Any]] = []
    for path in paths:
        path = Path(path)
        if path.is_dir():
            for f in sorted(path.glob("rank*.trace.json")):
                snaps.extend(_read_snapshot_file(f))
        else:
            snaps.extend(_read_snapshot_file(path))
    return snaps


def _read_snapshot_file(path: Path) -> list[dict[str, Any]]:
    try:
        obj = json.loads(path.read_text())
        if isinstance(obj, dict) and "events" in obj:
            obj.setdefault("_source", str(path))
            return [obj]
        return []
    except json.JSONDecodeError:
        # not one JSON object: a runlog JSONL — trace.py owns that
        # parsing contract (kind=trace filter, skip-unparseable
        # tolerance, _source annotation)
        return tracelib.load_trace_snapshots([path])


def rank_of(snap: dict[str, Any], default: int = 0) -> int:
    return int(snap.get("process", {}).get("process_id", default))


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------


def anchor_pairs(snap: dict[str, Any]) -> list[tuple[float, float]]:
    """(mono, wall) anchor pairs of a snapshot: construction time
    always; snapshot time when present (older records carry one)."""
    c = snap.get("clock", {})
    pairs = [(float(c["mono0"]), float(c["wall0"]))]
    if "mono1" in c and "wall1" in c:
        pairs.append((float(c["mono1"]), float(c["wall1"])))
    return pairs


def wall_offset(snap: dict[str, Any]) -> tuple[float, float]:
    """(offset, drift_bound): ``wall ≈ mono + offset`` for this rank's
    clocks. With two anchor pairs the offset is their mean and the
    bound half their disagreement (clock drift over the run, plus the
    scheduling noise of taking the anchors)."""
    offs = [w - m for m, w in anchor_pairs(snap)]
    mid = sum(offs) / len(offs)
    return mid, (max(offs) - min(offs)) / 2.0


def _sync_keyed(snap: dict[str, Any]) -> dict[tuple[str, int], float]:
    """Sync anchors keyed by (name, occurrence index) — the k-th
    barrier of a given name is the same global event on every rank."""
    counts: dict[str, int] = {}
    out: dict[tuple[str, int], float] = {}
    for a in snap.get("sync", []):
        name = str(a.get("name", "sync"))
        i = counts.get(name, 0)
        counts[name] = i + 1
        out[(name, i)] = float(a["mono"])
    return out


def estimate_alignment(
        snaps: list[dict[str, Any]]) -> dict[str, Any]:
    """Per-rank clock offsets onto one shared timeline (public form:
    one snapshot per rank, keyed by the snapshot's process id).

    Base estimate: each rank's wall anchors (``offset = wall − mono``),
    valid to NTP skew across hosts and exact on one host. Refinement:
    when every rank carries the same sync anchors (name + index), those
    instants are treated as simultaneous — each rank's offset is
    corrected so its anchors land on the earliest rank's (the earliest
    barrier exit is closest to the true release) — shrinking alignment
    error from wall-clock skew to barrier-exit spread.

    Returns ``{"offsets": {rank: offset_s}, "method": "wall"|"sync",
    "drift_bound_s", "wall_disagreement_s", "residual_s"}`` —
    ``wall_disagreement_s`` is how far the wall estimate was off per
    the sync anchors (the error a wall-only merge would have carried),
    ``residual_s`` the spread of corrections across multiple anchors
    (0 with one; the floor on post-refinement error)."""
    return _align_lanes({rank_of(s): s for s in snaps})


def _align_lanes(reps: dict[int, dict[str, Any]]) -> dict[str, Any]:
    """:func:`estimate_alignment` keyed by merge lane: ``reps`` maps
    lane id → its representative snapshot."""
    offsets: dict[int, float] = {}
    drift = 0.0
    keyed: dict[int, dict[tuple[str, int], float]] = {}
    for lane, snap in reps.items():
        off, d = wall_offset(snap)
        offsets[lane] = off
        drift = max(drift, d)
        keyed[lane] = _sync_keyed(snap)
    align = {"offsets": offsets, "method": "wall",
             "drift_bound_s": drift, "wall_disagreement_s": 0.0,
             "residual_s": drift}
    if len(keyed) < 2:
        return align
    common = set.intersection(*(set(k) for k in keyed.values()))
    if not common:
        return align
    corrections: dict[int, list[float]] = {r: [] for r in keyed}
    disagreement = 0.0
    for key in sorted(common):
        aligned = {r: keyed[r][key] + offsets[r] for r in keyed}
        ref = min(aligned.values())
        disagreement = max(disagreement,
                           max(aligned.values()) - ref)
        for r, v in aligned.items():
            corrections[r].append(v - ref)
    residual = 0.0
    for r, cs in corrections.items():
        offsets[r] -= sum(cs) / len(cs)
        residual = max(residual, (max(cs) - min(cs)) / 2.0)
    align.update(method="sync", wall_disagreement_s=disagreement,
                 residual_s=residual)
    return align


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------


def annotate(snaps: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Attach merge metadata to each snapshot: ``_pid`` (the Chrome
    process lane), ``_pname`` (lane label), and ``_offset`` (seconds
    added to its monotonic stamps to land on the shared timeline), plus
    the alignment verdict on every snapshot under ``_align`` (same
    object).

    One lane per (source file, process id): snapshots of the same
    process in the same log share a lane (they share a clock — e.g. an
    app emitting several sub-run records), while records from DIFFERENT
    files or ranks never collapse onto one pid — distinct lanes get the
    rank id where ranks are distinct, and are re-numbered in input
    order where they collide (two unrelated single-process logs both
    claiming rank 0)."""
    lanes: dict[tuple[Any, int], int] = {}
    used: set[int] = set()
    reps: dict[int, dict[str, Any]] = {}
    out = []
    for i, snap in enumerate(snaps):
        r = rank_of(snap)
        key = (snap.get("_source", i), r)
        if key in lanes:
            pid = lanes[key]
        else:
            pid = r
            while pid in used:
                pid += 1
            used.add(pid)
            lanes[key] = pid
            reps[pid] = snap
        out.append((pid, snap))
    align = _align_lanes(reps)
    annotated = []
    for pid, snap in out:
        proc = snap.get("process", {})
        n = int(proc.get("num_processes", 1) or 1)
        r = rank_of(snap)
        name = f"rank {r}/{n}"
        if proc.get("slice_id"):
            name += f" (slice {proc['slice_id']})"
        src = snap.get("_source")
        if src and n == 1:
            name = f"{Path(src).name}"
        snap = dict(snap)
        snap["_pid"] = pid
        snap["_pname"] = name
        snap["_offset"] = align["offsets"].get(pid, 0.0)
        snap["_align"] = align
        annotated.append(snap)
    return annotated


def _device_windows(annotated: list[dict[str, Any]]):
    """Sequence-stamped device X slices per snapshot, on the shared
    timeline: ``{(name, seq): [window, ...]}`` where a window is
    ``{"rank", "pid", "tid", "start", "dur"}``. These are the
    collective spans the flow fan and the skew rollups run over."""
    groups: dict[tuple[str, int], list[dict[str, Any]]] = {}
    for snap in annotated:
        off = snap["_offset"]
        for ev in snap.get("events", []):
            ph, cat, name, ts, tid, dur, args = ev
            if ph != "X" or cat != "device" or not isinstance(args, dict):
                continue
            seq = args.get("seq")
            if not isinstance(seq, int):
                continue
            groups.setdefault((name, seq), []).append({
                "rank": rank_of(snap), "pid": snap["_pid"],
                "tid": int(tid), "start": float(ts) + off,
                "dur": float(dur or 0.0),
            })
    return groups


def _schedule_check(annotated: list[dict[str, Any]]) -> dict[str, Any]:
    """Cross-rank collective schedule verification — the merge-time
    half of the shardlint story (analysis/runtime.py records, this
    cross-checks). Each snapshot carries its rank's hash chain over
    ``(op, seq, shape, dtype, axis)`` fingerprints; equal final
    digests prove the SPMD schedules matched, and on mismatch the
    retained entry windows localize the FIRST divergent collective
    per rank — the "rank 2 is at allreduce#17, rank 0 at
    sendrecv_ring#17" a deadlock debug needs first.

    Returns the ``schedule`` field of the trace_merged rollup:
    ``verdict`` is ``consistent`` / ``divergent`` / ``single_rank``
    (one chain: nothing to cross-check) / ``not_recorded``; a
    divergent verdict carries ``first_divergence`` with the index and
    each rank's ``(op, seq)`` there (or ``ended_at`` for a rank whose
    chain stopped short)."""
    chains: dict[int, dict[str, Any]] = {}
    for snap in annotated:
        c = snap.get("collectives")
        if not isinstance(c, dict) or not int(c.get("n", 0) or 0):
            continue
        pid = snap["_pid"]
        cur = chains.get(pid)
        # several snapshots of one process: the longest chain is the
        # final state (the chain only grows within a run)
        if cur is None or int(c["n"]) > int(cur["n"]):
            chains[pid] = c
    if not chains:
        return {"verdict": "not_recorded", "n_ranks_recorded": 0}
    base = {
        "n_ranks_recorded": len(chains),
        "n_collectives": max(int(c["n"]) for c in chains.values()),
    }
    if len(chains) == 1:
        return {"verdict": "single_rank", **base}
    ns = {int(c["n"]) for c in chains.values()}
    digests = {c.get("digest", "") for c in chains.values()}
    if len(ns) == 1 and len(digests) == 1:
        return {"verdict": "consistent", **base,
                "digest": next(iter(digests))}
    # localize: walk absolute indices; at the first index where the
    # per-rank entry digests disagree (or a chain has ended), name each
    # rank's position. Indices evicted from some chain's window are
    # skipped (unjudgeable); chains here are far below the window in
    # practice. Keys are merge LANES (same ids as the rollup's
    # ``ranks``/``stragglers`` tables): ranks are guaranteed-distinct
    # lane ids, while two unrelated single-process logs may both claim
    # process_id 0 and must not collapse onto one report key.
    maps: dict[int, tuple[dict[int, dict[str, Any]], int]] = {}
    for pid, c in sorted(chains.items()):
        maps[pid] = ({int(e["i"]): e for e in c.get("entries", [])},
                     int(c["n"]))
    hi = max(n for _, n in maps.values())
    first = None
    for i in range(hi):
        seen: dict[int, str | None] = {}
        evicted = False
        for pid, (entries, n) in maps.items():
            if i >= n:
                seen[pid] = None  # this rank never issued collective #i
            elif i in entries:
                seen[pid] = entries[i]["digest"]
            else:
                evicted = True
                break
        if evicted:
            continue
        if len(set(seen.values())) > 1:
            first = i
            break
    divergence = None
    if first is not None:
        ranks_at: dict[str, dict[str, Any]] = {}
        for pid, (entries, n) in sorted(maps.items()):
            e = entries.get(first)
            if e is None or first >= n:
                ranks_at[str(pid)] = {"ended_at": n}
            else:
                ranks_at[str(pid)] = {"op": e["op"], "seq": e["seq"]}
        divergence = {"index": first, "ranks": ranks_at}
    return {"verdict": "divergent", **base,
            "first_divergence": divergence}


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals — busy time
    must not double-count overlapped windows on different subtracks."""
    total = 0.0
    end = float("-inf")
    for s, e in sorted(intervals):
        if e <= end:
            continue
        total += e - max(s, end)
        end = e
    return total


def merge(snaps: list[dict[str, Any]]) -> dict[str, Any]:
    """The full cross-rank merge: ``{"chrome": <Perfetto JSON>,
    "rollup": <kind=trace_merged payload>}``.

    The Chrome JSON has one ``pid`` lane per rank (process_name +
    process_sort_index metadata), every rank's events re-based onto the
    shared clock, and flow events (``s``/``t``/``f`` with a shared id)
    threading the per-rank slices of each matched collective — load it
    in Perfetto and a skewed allreduce shows as a fan of arrows from
    the early ranks to the straggler."""
    annotated = annotate(snaps)
    align = annotated[0]["_align"] if annotated else {
        "offsets": {}, "method": "wall", "drift_bound_s": 0.0,
        "wall_disagreement_s": 0.0, "residual_s": 0.0}
    # shared origin: earliest event start across every rank
    t0 = None
    for snap in annotated:
        off = snap["_offset"]
        base = float(snap["clock"]["mono0"]) + off
        t0 = base if t0 is None else min(t0, base)
        for ev in snap.get("events", []):
            t0 = min(t0, float(ev[3]) + off)
    t0 = t0 or 0.0

    meta: list[dict[str, Any]] = []
    events: list[dict[str, Any]] = []
    for snap in annotated:
        pid, off = snap["_pid"], snap["_offset"]
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": snap["_pname"]}})
        meta.append({"name": "process_sort_index", "ph": "M",
                     "pid": pid, "args": {"sort_index": pid}})
        tids = set()
        for ev in snap.get("events", []):
            ph, cat, name, ts, tid, dur, args = ev
            tids.add(int(tid))
            rec: dict[str, Any] = {
                "name": name, "cat": cat, "ph": ph,
                "ts": (float(ts) + off - t0) * 1e6,
                "pid": pid, "tid": int(tid),
            }
            if ph == "X":
                rec["dur"] = (dur or 0.0) * 1e6
            if ph == "i":
                rec["s"] = "t"
            if ph == "C":
                rec["args"] = {k: v for k, v in (args or {}).items()}
            elif args:
                rec["args"] = {k: str(v) for k, v in args.items()}
            events.append(rec)
        for tid in sorted(tids):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid,
                         "args": {"name": tracelib._track_label(tid)}})

    # the matched subset is computed ONCE: flows and the rollup tables
    # must agree on what counts as "the same collective seen by >= 2
    # ranks" by construction, not by parallel re-derivation
    groups = _device_windows(annotated)
    matched = {key: wins for key, wins in sorted(groups.items())
               if len({w["pid"] for w in wins}) >= 2}
    n_unmatched = len(groups) - len(matched)
    flow_id = 0
    for (name, _seq), wins in matched.items():
        flow_id += 1
        # bind each flow point mid-slice (an edge stamp is ambiguous
        # between adjacent slices) and order the chain by the binding
        # points — Chrome flow ts must be non-decreasing along the id
        wins = sorted(wins, key=lambda w: w["start"] + w["dur"] / 2.0)
        for i, w in enumerate(wins):
            ph = "s" if i == 0 else ("f" if i == len(wins) - 1 else "t")
            rec = {"name": name, "cat": "collective", "ph": ph,
                   "id": flow_id, "pid": w["pid"], "tid": w["tid"],
                   "ts": (w["start"] + w["dur"] / 2.0 - t0) * 1e6}
            if ph == "f":
                rec["bp"] = "e"
            events.append(rec)

    # round 18 request-forensics lanes (harness/reqtrace.py): each
    # request's lifecycle segments already merged above as cat=request
    # X slices on its own TID_REQUEST lane; here every `migrating`
    # segment carrying the plane's migration seq is threaded by a flow
    # chain into the matched plane.kv_migration device windows of the
    # same seq — reading a p99 in Perfetto, the arrow leads from the
    # request's wait into the transfer that caused it
    n_req_lanes = set()
    n_mig_links = 0
    for snap in annotated:
        off = snap["_offset"]
        for ev in snap.get("events", []):
            ph, cat, name, ts, tid, dur, args = ev
            if ph != "X" or cat != "request":
                continue
            n_req_lanes.add((snap["_pid"], int(tid)))
            if name != "migrating" or not isinstance(args, dict) \
                    or not isinstance(args.get("seq"), int):
                continue
            wins = groups.get(("plane.kv_migration", args["seq"]))
            if not wins:
                continue
            n_mig_links += 1
            flow_id += 1
            chain = sorted(
                [{"pid": snap["_pid"], "tid": int(tid),
                  "start": float(ts) + off,
                  "dur": float(dur or 0.0)}] + wins,
                key=lambda w: w["start"] + w["dur"] / 2.0)
            for i, w in enumerate(chain):
                fph = "s" if i == 0 else (
                    "f" if i == len(chain) - 1 else "t")
                rec = {"name": "plane.kv_migration", "cat": "request",
                       "ph": fph, "id": flow_id, "pid": w["pid"],
                       "tid": w["tid"],
                       "ts": (w["start"] + w["dur"] / 2.0 - t0) * 1e6}
                if fph == "f":
                    rec["bp"] = "e"
                events.append(rec)

    rollup = _rollup(annotated, matched, align, n_unmatched)
    rollup["requests"] = {"n_lanes": len(n_req_lanes),
                          "n_migration_links": n_mig_links}
    chrome = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    return {"chrome": chrome, "rollup": rollup}


def _rollup(annotated, matched, align, n_unmatched):
    """The cross-rank numbers: per-collective skew, straggler counts,
    per-rank busy/bubble — the ``kind=trace_merged`` record payload.
    ``matched`` is merge()'s matched-group subset (>= 2 ranks each)."""
    ranks = sorted({snap["_pid"] for snap in annotated})
    skew: dict[str, dict[str, Any]] = {}
    last_counts: dict[int, int] = {r: 0 for r in ranks}
    n_matched = len(matched)
    for (name, _seq), wins in matched.items():
        starts = [w["start"] for w in wins]
        durs = [w["dur"] for w in wins]
        s = skew.setdefault(name, {
            "n": 0, "max_start_skew_s": 0.0, "sum_start_skew_s": 0.0,
            "max_dur_skew_s": 0.0})
        start_skew = max(starts) - min(starts)
        s["n"] += 1
        s["max_start_skew_s"] = max(s["max_start_skew_s"], start_skew)
        s["sum_start_skew_s"] += start_skew
        s["max_dur_skew_s"] = max(s["max_dur_skew_s"],
                                  max(durs) - min(durs))
        last = max(wins, key=lambda w: w["start"] + w["dur"])
        last_counts[last["pid"]] = last_counts.get(last["pid"], 0) + 1
    for s in skew.values():
        s["mean_start_skew_s"] = s.pop("sum_start_skew_s") / s["n"]

    # busy/bubble per lane: several snapshots of one process aggregate
    # into that lane's single fraction
    lane_stamps: dict[int, list[float]] = {}
    lane_intervals: dict[int, list[tuple[float, float]]] = {}
    total_events = 0
    for snap in annotated:
        off = snap["_offset"]
        pid = snap["_pid"]
        stamps = lane_stamps.setdefault(pid, [])
        intervals = lane_intervals.setdefault(pid, [])
        for ev in snap.get("events", []):
            total_events += 1
            stamps.append(float(ev[3]) + off)
            if ev[0] == "X" and ev[1] == "device":
                s0 = float(ev[3]) + off
                intervals.append((s0, s0 + float(ev[5] or 0.0)))
    busy: dict[str, dict[str, float]] = {}
    for pid, stamps in lane_stamps.items():
        if not stamps:
            continue
        intervals = lane_intervals[pid]
        window = max(max(stamps), max((e for _, e in intervals),
                                      default=max(stamps))) - min(stamps)
        busy_s = _union_seconds(intervals)
        frac = busy_s / window if window > 0 else 0.0
        busy[str(pid)] = {
            "busy_frac": frac, "bubble_frac": 1.0 - frac,
            "window_s": window,
        }

    num_processes = max(
        (int(s.get("process", {}).get("num_processes", 1) or 1)
         for s in annotated), default=0)
    return {
        "num_processes": num_processes,
        "ranks": ranks,
        "n_ranks": len(ranks),
        "n_events": total_events,
        "n_matched": n_matched,
        "n_unmatched": n_unmatched,
        "align": {
            "method": align["method"],
            "offsets_s": {str(r): align["offsets"].get(r, 0.0)
                          for r in sorted(align["offsets"])},
            "drift_bound_s": align["drift_bound_s"],
            "wall_disagreement_s": align["wall_disagreement_s"],
            "residual_s": align["residual_s"],
        },
        "skew": skew,
        "schedule": _schedule_check(annotated),
        "stragglers": {str(r): {"last": last_counts.get(r, 0),
                                "of": n_matched}
                       for r in ranks},
        "busy": busy,
    }


# ---------------------------------------------------------------------------
# rendering + CLI
# ---------------------------------------------------------------------------


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f} s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f} ms"
    return f"{v * 1e6:.1f} us"


def format_rollup(rollup: dict[str, Any]) -> str:
    """The human skew/straggler summary the launcher and the CLI
    print; ``harness.report`` renders a one-line digest of the same
    record."""
    lines = []
    a = rollup["align"]
    lines.append(
        f"merged {rollup['n_ranks']} rank(s) "
        f"({rollup['n_events']} events; clock align: {a['method']}"
        + (f", residual ≤ {_fmt_s(a['residual_s'])}"
           if a["method"] == "sync" else
           f", drift ≤ {_fmt_s(a['drift_bound_s'])}")
        + f"); {rollup['n_matched']} collective(s) matched across ranks"
        + (f", {rollup['n_unmatched']} single-rank"
           if rollup["n_unmatched"] else ""))
    reqs = rollup.get("requests") or {}
    if reqs.get("n_lanes"):
        lines.append(
            f"request lanes: {reqs['n_lanes']} request(s), "
            f"{reqs['n_migration_links']} migration flow link(s) "
            "(harness/explain.py attributes the tails)")
    sched = rollup.get("schedule") or {}
    verdict = sched.get("verdict")
    if verdict == "consistent":
        lines.append(
            f"collective schedules consistent across "
            f"{sched['n_ranks_recorded']} rank(s): "
            f"{sched['n_collectives']} collective(s), "
            f"digest {sched['digest']}")
    elif verdict == "divergent":
        fd = sched.get("first_divergence")
        if fd:
            at = ", ".join(
                (f"rank {r} is at {info['op']}#{info['seq']}"
                 if "op" in info
                 else f"rank {r} ended after {info['ended_at']}")
                for r, info in sorted(fd["ranks"].items(),
                                      key=lambda kv: int(kv[0])))
            lines.append(
                f"COLLECTIVE SCHEDULE DIVERGENCE at #{fd['index']}: "
                f"{at}")
        else:
            lines.append(
                "COLLECTIVE SCHEDULE DIVERGENCE (first divergent "
                "collective evicted from every chain window)")
    if rollup["skew"]:
        lines.append("")
        lines.append(f"{'collective':<36} {'n':>4} {'max start skew':>15} "
                     f"{'mean start skew':>16} {'max dur skew':>13}")
        for name, s in sorted(rollup["skew"].items()):
            lines.append(
                f"{name:<36} {s['n']:>4} "
                f"{_fmt_s(s['max_start_skew_s']):>15} "
                f"{_fmt_s(s['mean_start_skew_s']):>16} "
                f"{_fmt_s(s['max_dur_skew_s']):>13}")
    strag = [(r, v) for r, v in sorted(rollup["stragglers"].items(),
                                       key=lambda kv: int(kv[0]))
             if v["of"]]
    if strag:
        lines.append("")
        lines.append(f"{'rank':<6} {'finished last':>14} "
                     f"{'busy':>8} {'bubble':>8}")
        for r, v in strag:
            b = rollup["busy"].get(r, {})
            lines.append(
                f"r{r:<5} {v['last']:>7}/{v['of']:<6} "
                f"{b.get('busy_frac', 0.0):>7.1%} "
                f"{b.get('bubble_frac', 0.0):>7.1%}")
        worst = max(strag, key=lambda kv: kv[1]["last"])
        if worst[1]["last"]:
            lines.append(
                f"straggler: rank {worst[0]} finished last in "
                f"{worst[1]['last']}/{worst[1]['of']} matched "
                "collective(s)")
    return "\n".join(lines)


#: schema version of the ``--rollup-out`` artifact — bump on any
#: breaking change to the rollup key layout so downstream fitters
#: (harness/autofit.py) can refuse a layout they don't understand
ROLLUP_VERSION = 1
ROLLUP_KIND = "trace_rollup"


def dumps_rollup(rollup: dict[str, Any]) -> str:
    """The stable serialized form of the ``--rollup-out`` artifact:
    the trace_merged payload wrapped in a version/kind envelope,
    sorted keys, trailing newline — byte-identical for identical
    rollups, so a fitted config derived from it is reproducible."""
    doc = {"version": ROLLUP_VERSION, "kind": ROLLUP_KIND,
           **{k: v for k, v in rollup.items()
              if not k.startswith("_")}}
    return json.dumps(doc, sort_keys=True, indent=2, default=str) + "\n"


def write_rollup(rollup: dict[str, Any], path: str | Path) -> Path:
    """Write the versioned rollup JSON and record its location in the
    rollup itself (``rollup_out``), so the ``kind=trace_merged`` runlog
    record — and harness.report's digest line — name the artifact."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    rollup["rollup_out"] = str(path)
    path.write_text(dumps_rollup(rollup))
    return path


def collect_to_file(inputs: Iterable[str | Path],
                    out: str | Path) -> dict[str, Any] | None:
    """Load, merge, and write the Perfetto JSON to ``out``. Returns the
    rollup (None when no snapshots were found) — the one call the
    launcher makes at exit."""
    snaps = load_rank_snapshots(inputs)
    if not snaps:
        return None
    merged = merge(snaps)
    out = Path(out)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w") as f:
        json.dump(merged["chrome"], f)
    rollup = merged["rollup"]
    rollup["out"] = str(out)
    return rollup


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Merge per-rank flight-recorder snapshots into one "
                    "clock-aligned Perfetto timeline with cross-rank "
                    "skew/straggler rollups")
    p.add_argument("inputs", nargs="+",
                   help="rank directory (HPCPAT_TRACE_DIR), per-rank "
                        "rank*.trace.json files, or runlog JSONL files "
                        "with kind=trace records")
    p.add_argument("-o", "--out", default=None,
                   help="merged Chrome-trace JSON path (default: "
                        "<first input>/merged.trace.json for a "
                        "directory, <first input>.merged.json otherwise)")
    p.add_argument("--log", default=None,
                   help="append the kind=trace_merged rollup record to "
                        "this runlog JSONL (harness.report renders it)")
    p.add_argument("--rollup-out", default=None, metavar="PATH",
                   help="also write the cross-rank rollup as a stable "
                        "versioned JSON artifact (kind=trace_rollup, "
                        f"version {ROLLUP_VERSION}; sorted keys, "
                        "reproducible bytes) — the file "
                        "harness/autofit.py consumes for placement "
                        "fitting, named in harness.report's digest "
                        "line")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    first = Path(args.inputs[0])
    if args.out:
        out = Path(args.out)
    elif first.is_dir():
        out = first / "merged.trace.json"
    else:
        out = first.with_suffix(".merged.json")
    try:
        rollup = collect_to_file(args.inputs, out)
    except OSError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    if rollup is None:
        print("ERROR: no trace snapshots in input (per-rank "
              "rank*.trace.json files are written by traced children "
              "of apps/launch.py --trace-out; kind=trace records by "
              "--trace --log runs)", file=sys.stderr)
        return 2
    if args.rollup_out:
        # BEFORE the --log emit: the trace_merged record must carry
        # the artifact's location for report's digest line
        try:
            write_rollup(rollup, args.rollup_out)
        except OSError as e:
            print(f"ERROR: cannot write --rollup-out: {e}",
                  file=sys.stderr)
            return 2
        print(f"rollup artifact: {args.rollup_out} "
              f"(kind={ROLLUP_KIND} v{ROLLUP_VERSION})")
    print(format_rollup(rollup))
    print(f"{out}: open in Perfetto (ui.perfetto.dev) or "
          "chrome://tracing — one pid lane per rank, flow arrows link "
          "each collective's ranks")
    if args.log:
        from hpc_patterns_tpu.harness.runlog import RunLog

        RunLog(args.log, truncate=False).emit(kind="trace_merged",
                                              **rollup)
    return 0


if __name__ == "__main__":
    sys.exit(main())
