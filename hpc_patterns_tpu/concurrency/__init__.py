"""Concurrency suite — TPU rebuild of the reference's ``concurency/``
(C1–C4 + C12 in SURVEY.md §2.1).

The reference measures whether N independent device commands — compute
kernels (``C``), host→device copies (``M2D``), device→host copies
(``D2M``) — actually overlap on one GPU, comparing an out-of-order queue
and an in-order queue pool against serial execution
(sycl_con.cpp:35-131), plus OpenMP ``nowait`` tasks and host-thread
fan-out (omp_con.cpp:64-125).

TPU mapping (SURVEY.md §7 step 3):

- ``C``   → a Pallas busy-wait FMA kernel (:mod:`~.kernels`, ≙
  ``busy_wait``, sycl_con.cpp:26-33)
- ``M2D`` → host→HBM transfer; ``D2M`` → HBM→host transfer
  (:mod:`~.commands`), via JAX memory-kind jits on TPU or
  ``device_put``/``copy_to_host_async`` elsewhere
- out-of-order queue / ``nowait`` → JAX **async dispatch**: submits
  return immediately, the runtime overlaps DMA with compute
- in-order queue pool → round-robin over multiple devices
- ``host_threads`` → a thread per command (:func:`~.engine.bench`)

The verdict rules and timing protocol are the shared harness
(:mod:`hpc_patterns_tpu.harness`); the autotuner (C12) lives in
:mod:`~.autotune`.
"""

from hpc_patterns_tpu.concurrency.commands import (  # noqa: F401
    Command,
    ComputeCommand,
    CopyD2MCommand,
    CopyM2DCommand,
    make_command,
)
from hpc_patterns_tpu.concurrency.engine import MODES, BenchResult, bench  # noqa: F401
from hpc_patterns_tpu.concurrency.kernels import busy_wait  # noqa: F401
