"""Serve app: continuous batching over the paged KV cache, validated.

Completes the lifecycle triad's serving leg as a CLI: a stream of
requests with varied prompt lengths and budgets served through
models/serving.ContinuousBatcher (page free-list, admission as pages
free, per-row completion), then EVERY sequence validated token-exact
against its standalone ``paged_generate`` — the reference's
benchmark-IS-the-test discipline (SURVEY.md §4: the binary measures
its own claim and exits SUCCESS/FAILURE). Reports tokens/s and, with
``--static-compare``, the static-batching baseline wall clock.
"""

from __future__ import annotations

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import base_parser
from hpc_patterns_tpu.models import TransformerConfig, init_params


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--slots", type=int, default=2,
                   help="concurrent rows in the pool")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--chunk", type=int, default=4,
                   help="decode steps per jitted dispatch (admission "
                        "granularity)")
    p.add_argument("--prompt-len", type=int, default=12)
    p.add_argument("--budget", type=int, default=12,
                   help="max new tokens per request (actual budgets "
                        "vary 1/4..1x)")
    p.add_argument("--pool-pages", type=int, default=0,
                   help="shared arena size (0 = slots * pages needed "
                        "for prompt+budget)")
    p.add_argument("--eos-id", type=int, default=-1,
                   help=">= 0: end rows early at this token")
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--pos-embed", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--kv-cache-dtype", default="compute",
                   choices=["compute", "int8"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="serve a trained checkpoint (train_app "
                        "--checkpoint-dir); default: fresh init")
    p.add_argument("--draft-pair", default=None, metavar="DIR",
                   help="serve an aligned draft/target pair "
                        "(benchmarks/make_draft_pair.py): speculative "
                        "rounds inside the engine — rows advance "
                        "1..gamma+1 tokens per dispatch (overrides the "
                        "model-dim flags with the pair's configs)")
    p.add_argument("--gamma", type=int, default=4,
                   help="draft proposals per round with --draft-pair")
    p.add_argument("--static-compare", action="store_true",
                   help="also time static batching (batches of "
                        "--slots padded to the batch max budget)")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    topology.init_distributed_from_env()
    from hpc_patterns_tpu.models.decode import paged_generate
    from hpc_patterns_tpu.models.serving import ContinuousBatcher

    need = args.prompt_len + args.budget
    draft_params = draft_cfg = None
    if args.draft_pair and args.checkpoint_dir:
        log.print("ERROR: --draft-pair serves the pair's own target "
                  "checkpoint; --checkpoint-dir would be silently "
                  "ignored — pass one or the other")
        log.print("FAILURE")
        return 1
    try:
        if args.draft_pair:
            import json
            import os

            from hpc_patterns_tpu.utils.checkpoint import restore_params

            with open(os.path.join(args.draft_pair, "META.json")) as f:
                meta = json.load(f)
            cfg = TransformerConfig(**{**meta["target_cfg"],
                                       "max_seq": need})
            draft_cfg = TransformerConfig(**{**meta["draft_cfg"],
                                             "max_seq": need})
            params, _ = restore_params(
                os.path.join(args.draft_pair, "target"))
            draft_params, _ = restore_params(
                os.path.join(args.draft_pair, "draft"))
            log.print(f"aligned pair from {args.draft_pair} "
                      f"(gamma={args.gamma})")
        else:
            cfg = TransformerConfig(
                vocab=args.vocab, d_model=args.d_model,
                n_heads=args.n_heads, n_layers=args.n_layers,
                d_ff=4 * args.d_model, max_seq=need,
                n_kv_heads=args.n_kv_heads, pos_embed=args.pos_embed,
                kv_cache_dtype=args.kv_cache_dtype,
            )
    except (ValueError, FileNotFoundError, KeyError) as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    if args.requests < 1 or args.slots < 1 or args.budget < 1:
        log.print("ERROR: --requests/--slots/--budget must be >= 1")
        log.print("FAILURE")
        return 1
    if not args.draft_pair:
        params = init_params(jax.random.PRNGKey(0), cfg)
        if args.checkpoint_dir:
            from hpc_patterns_tpu.utils.checkpoint import restore_params

            try:
                params, step = restore_params(args.checkpoint_dir)
                log.print(
                    f"restored step {step} from {args.checkpoint_dir}")
            except (FileNotFoundError, ValueError, KeyError) as e:
                log.print(f"ERROR: cannot restore "
                          f"{args.checkpoint_dir}: {e}")
                log.print("FAILURE")
                return 1

    # the engine owns the sizing rule (incl. speculative slack)
    pages_per_seq = ContinuousBatcher.pages_needed(
        args.prompt_len, args.budget, args.page_size,
        gamma=args.gamma if draft_params is not None else None)
    pool_pages = args.pool_pages or args.slots * pages_per_seq
    rng = np.random.RandomState(7)
    reqs = []
    for _ in range(args.requests):
        prompt = rng.randint(0, cfg.vocab,
                             size=args.prompt_len).astype(np.int32)
        budget = int(rng.choice([max(1, args.budget // 4),
                                 max(1, args.budget // 2), args.budget]))
        reqs.append((prompt, budget))
    total_budget = sum(b for _, b in reqs)

    def serve():
        # constructor/submit ValueErrors (bad gamma, vocab mismatch,
        # oversize request) keep the clean ERROR/FAILURE contract too,
        # not just run()'s RuntimeError
        try:
            eng = ContinuousBatcher(
                params, cfg, slots=args.slots, pool_pages=pool_pages,
                pages_per_seq=pages_per_seq, page_size=args.page_size,
                chunk=args.chunk,
                eos_id=args.eos_id if args.eos_id >= 0 else None,
                draft_params=draft_params, draft_cfg=draft_cfg,
                gamma=args.gamma, emit=log.emit,
            )
            ids = [eng.submit(p, b) for p, b in reqs]
            got = eng.run()
        except (ValueError, RuntimeError) as e:
            return None, str(e)
        return {i: got[sid] for i, sid in enumerate(ids)}, None

    # warmup (compiles) — keep its records out of the registry: its
    # TTFT would be compile-dominated and its counters would double
    # every request (the warmup-vs-timed discipline of harness.timing)
    m = metricslib.get_metrics()
    prev_enabled = m.enabled
    m.enabled = False
    try:
        out, err = serve()
    finally:
        m.enabled = prev_enabled
    if err is not None:
        log.print(f"ERROR: {err}")
        log.print("FAILURE")
        return 1
    t0 = time.perf_counter()
    with metricslib.span("serve.measure"):
        out, _ = serve()
    dt = time.perf_counter() - t0
    served = sum(len(v) for v in out.values())
    metricslib.get_metrics().gauge("serve.tokens_per_s").set(served / dt)

    # the oracle: every sequence token-exact vs standalone paged decode
    # (truncated at eos when enabled — same rule the engine applies)
    exact = True
    for i, (prompt, budget) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None, :], cfg, budget,
            page_size=args.page_size))[0]
        if args.eos_id >= 0 and np.any(want == args.eos_id):
            want = want[:int(np.argmax(want == args.eos_id)) + 1]
        if not np.array_equal(out[i], want):
            exact = False
            log.print(f"MISMATCH seq {i}: engine {out[i][:8]}... vs "
                      f"standalone {want[:8]}...")
    ok = exact and served > 0
    log.emit(kind="result", name="serve", success=ok,
             requests=args.requests, slots=args.slots,
             pool_pages=pool_pages, page_size=args.page_size,
             chunk=args.chunk, served_tokens=served,
             tokens_per_s=served / dt, oracle_exact=exact)
    log.print(f"serve[{args.slots} slots, pool {pool_pages}p x "
              f"{args.page_size}] {args.requests} reqs, {served} tokens "
              f"(budget {total_budget}): {dt:.3f}s, "
              f"{served / dt:,.1f} tok/s, oracle "
              f"{'exact' if exact else 'MISMATCH'}")

    if args.static_compare:
        def run_static():
            o = {}
            for i in range(0, args.requests, args.slots):
                batch = reqs[i:i + args.slots]
                prompts = jnp.asarray(np.stack([p for p, _ in batch]))
                run_len = max(b for _, b in batch)
                toks = np.asarray(paged_generate(
                    params, prompts, cfg, run_len,
                    page_size=args.page_size))
                for j, (_, b) in enumerate(batch):
                    o[i + j] = toks[j, :b]
            return o

        run_static()  # warmup
        t0 = time.perf_counter()
        run_static()
        ts = time.perf_counter() - t0
        log.print(f"static batching: {ts:.3f}s "
                  f"({served / ts:,.1f} tok/s) — engine/static "
                  f"{ts / dt:.2f}x")

    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
