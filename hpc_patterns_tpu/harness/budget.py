"""Per-segment SLO budgets: alert when ONE lifecycle segment alone
blows the target.

The attainment rollup (harness/slo.py) says *whether* a class missed
its TTFT/TPOT target; the attribution digest (harness/explain.py)
says *where* the tail's time went. This module closes the gap between
them: an :class:`SLOBudget` declares how much of each target a single
segment is ALLOWED to eat (``admit_wait <= 0.3 * ttft_slo_s``), and a
pure evaluator walks the finalized ``reqtrace`` segment tilings per
priority class and emits one breach record per over-budget
``(class, axis, segment)`` — so "p99 missed" becomes "prefetch_wait
spent 62ms of its 34ms decode allowance on 3 of 5 requests" before
anyone opens a trace.

Axes mirror slo.py's two latencies:

- **ttft**: segment time inside ``[t_submit, t_first]`` vs
  ``share * ttft_slo_s``;
- **tpot**: segment time inside ``[t_first, t_finish]`` (the decode
  phase) vs ``share * tpot_slo_s * (tokens - 1)`` — the whole-phase
  allowance implied by the per-token target, so a single long stall
  and death-by-a-thousand-pauses are judged by the same yardstick.

The evaluator is pure (snapshot in, records out). :func:`publish`
does the side effects: ``kind=slo_budget`` through a RunLog emit
(rendered by harness/report.py as the per-class breach table) and a
``budget.breach.<segment>`` counter per breached segment. The
launched serving plane (serving_plane/service.py) publishes
automatically when request tracing is on and SLO targets are set;
``--explain`` surfaces print :func:`format_budget`'s loud section.
docs/observability.md#segment-slo-budgets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import reqtrace

#: record kind of one breach row (consumed by harness/report.py)
BUDGET_KIND = "slo_budget"


@dataclass(frozen=True)
class SLOBudget:
    """Per-segment shares of the TTFT/TPOT targets. A segment absent
    from a map is unbudgeted (never breaches); shares may sum past
    1.0 — each is an independent alarm line, not a partition."""

    ttft_shares: Mapping[str, float] = field(default_factory=dict)
    tpot_shares: Mapping[str, float] = field(default_factory=dict)


#: conservative default: scheduling may eat half the TTFT target,
#: admission a third; any single decode-phase stall mechanism may eat
#: a third of the decode allowance; unclaimed time is alarmed tight
#: on both axes (untracked time hiding a stall is itself a finding)
DEFAULT_BUDGET = SLOBudget(
    ttft_shares={"queued": 0.5, "admit_wait": 0.3, "preempted": 0.3,
                 "untracked": 0.15},
    tpot_shares={"preempted": 0.35, "swapped_out": 0.35,
                 "prefetch_wait": 0.35, "migrating": 0.35,
                 "untracked": 0.15},
)


def _segment_time(tiled: Iterable, lo: float, hi: float
                  ) -> dict[str, float]:
    """Per-kind seconds of one request's canonical tiling inside
    ``[lo, hi]`` (the same intersection explain's windows use)."""
    out: dict[str, float] = {}
    for kind, s0, s1, _meta in tiled:
        ov = min(float(s1), hi) - max(float(s0), lo)
        if ov > 0:
            out[kind] = out.get(kind, 0.0) + ov
    return out


def evaluate(snapshot: Mapping[str, Any],
             targets: Mapping[int, Any],
             budget: SLOBudget = DEFAULT_BUDGET) -> list[dict[str, Any]]:
    """Walk one ``kind=reqtrace`` snapshot against per-class SLO
    targets (``{priority: slo.SLOTarget}``, the engine's ``slo=``
    map) and return one record per breached ``(class, axis,
    segment)`` — empty list when every segment stayed inside its
    allowance. Pure: no emission, no counters (see :func:`publish`)."""
    # (priority, axis, segment) -> running aggregate
    agg: dict[tuple[int, str, str], dict[str, Any]] = {}

    def _check(prio: int, axis: str, seg: str, share: float,
               spent: float, allowance: float, sid: int) -> None:
        key = (prio, axis, seg)
        a = agg.setdefault(key, {
            "kind": BUDGET_KIND, "priority": prio, "axis": axis,
            "segment": seg, "share": float(share), "allowance_s": 0.0,
            "n": 0, "breached": 0, "worst_s": 0.0,
            "worst_seq_id": None,
        })
        a["n"] += 1
        if spent > allowance:
            a["breached"] += 1
        if spent >= a["worst_s"]:
            a["worst_s"] = float(spent)
            a["worst_seq_id"] = sid
            # report the allowance of the worst offender: on the tpot
            # axis it scales with the request's own token count
            a["allowance_s"] = float(allowance)

    for sid_str, entry in (snapshot.get("requests") or {}).items():
        sid = int(sid_str)
        prio = int(entry.get("priority") or 0)
        tgt = targets.get(prio)
        if tgt is None:
            continue
        t_submit = entry.get("t_submit")
        t_first = entry.get("t_first")
        t_finish = entry.get("t_finish")
        if t_submit is None or t_finish is None:
            continue  # still in flight: no finalized window to judge
        tiled, _ = reqtrace.finalize(entry.get("segments") or (),
                                     t_submit, t_finish)
        ttft_slo = getattr(tgt, "ttft_slo_s", None)
        if ttft_slo is None:
            ttft_slo = getattr(tgt, "ttft_s", None)
        tpot_slo = getattr(tgt, "tpot_slo_s", None)
        if tpot_slo is None:
            tpot_slo = getattr(tgt, "tpot_s", None)
        if ttft_slo is not None and t_first is not None:
            spent = _segment_time(tiled, float(t_submit),
                                  float(t_first))
            for seg, share in budget.ttft_shares.items():
                _check(prio, "ttft", seg, share,
                       spent.get(seg, 0.0), share * float(ttft_slo),
                       sid)
        tokens = int(entry.get("tokens") or 0)
        if tpot_slo is not None and t_first is not None and tokens >= 2:
            spent = _segment_time(tiled, float(t_first),
                                  float(t_finish))
            decode_allow = float(tpot_slo) * (tokens - 1)
            for seg, share in budget.tpot_shares.items():
                _check(prio, "tpot", seg, share,
                       spent.get(seg, 0.0), share * decode_allow, sid)

    return sorted((a for a in agg.values() if a["breached"]),
                  key=lambda a: (a["priority"], a["axis"],
                                 -a["worst_s"]))


def breached_segments(breaches: Iterable[Mapping[str, Any]]
                      ) -> set[str]:
    return {str(b["segment"]) for b in breaches}


def publish(breaches: Iterable[Mapping[str, Any]],
            emit: Callable[..., Any] | None = None) -> None:
    """The side-effect half: one ``kind=slo_budget`` record per breach
    through ``emit`` (a RunLog.emit) and a ``budget.breach.<segment>``
    counter bump per breached request."""
    m = metricslib.get_metrics()
    for b in breaches:
        if emit is not None:
            emit(**dict(b))
        if m.enabled:
            m.counter(f"budget.breach.{b['segment']}").inc(
                int(b["breached"]))


def _ms(v: float) -> str:
    return f"{v * 1e3:.0f}ms"


def format_budget(breaches: list[dict[str, Any]]) -> str:
    """The loud ``--explain`` section: name the over-budget segment
    with its spend vs allowance, or say plainly that every segment
    stayed inside."""
    if not breaches:
        return "slo budgets: all segments within allowance"
    lines = ["SLO BUDGET BREACHES:"]
    for b in breaches:
        lines.append(
            f"  class {b['priority']} {b['axis']}: {b['segment']} "
            f"spent {_ms(b['worst_s'])} of {_ms(b['allowance_s'])} "
            f"allowance ({b['share']:.0%} of target) — "
            f"{b['breached']}/{b['n']} request(s), worst seq "
            f"{b['worst_seq_id']}")
    return "\n".join(lines)
