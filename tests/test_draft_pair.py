"""Aligned draft/target pair pipeline (benchmarks/make_draft_pair.py):
truncation+distillation must measurably beat the round-4 random-draft
baseline on acceptance diagnostics, and the saved pair must serve
through speculative_generate."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent


class TestDraftPair:
    @pytest.fixture(scope="class")
    def pair_dir(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("pair")
        proc = subprocess.run(
            [sys.executable, str(REPO / "benchmarks/make_draft_pair.py"),
             f"--out={out}", "--steps=25", "--distill-steps=25"],
            capture_output=True, text=True, cwd=REPO, timeout=900,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        return out

    def test_alignment_beats_random_baseline(self, pair_dir):
        meta = json.loads((pair_dir / "META.json").read_text())
        acc = meta["acceptance"]
        # even a 25-step CPU pair separates clearly from independence
        assert acc["aligned_greedy"] > acc["random_greedy"] + 0.05
        assert acc["aligned_minpq"] > acc["random_minpq"] + 0.05

    def test_pair_serves_speculatively_and_exact(self, pair_dir):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.decode import generate
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate,
        )
        from hpc_patterns_tpu.utils.checkpoint import restore_params

        meta = json.loads((pair_dir / "META.json").read_text())
        cfg = TransformerConfig(**meta["target_cfg"])
        dcfg = TransformerConfig(**meta["draft_cfg"])
        params, _ = restore_params(pair_dir / "target")
        dparams, _ = restore_params(pair_dir / "draft")
        prompt = jnp.arange(8, dtype=jnp.int32)[None, :] % cfg.vocab
        want = np.asarray(generate(params, prompt, cfg, 12))
        got = np.asarray(speculative_generate(
            params, cfg, dparams, dcfg, prompt, 12, gamma=3))
        np.testing.assert_array_equal(got, want)
