"""Known-bad: the fused (device-initiated) collective entry points in
divergence-shaped and unchecked-permutation-shaped code. The ring runs
inside a Pallas kernel, but every rank must still ENTER the kernel in
lockstep — rank-guarding a fused collective is the same deadlock shape
as rank-guarding an MPI call, and an unchecked pair list reaching
``fused_permute`` strands a rank on a DMA that never arrives."""

from jax import lax

from hpc_patterns_tpu.comm import fused


def rank_guarded_fused(x, axis):
    me = lax.axis_index(axis)
    if me == 0:  # EXPECT: collective-divergence
        return fused.fused_allreduce(x, axis)
    return x


def fused_branch_mismatch(x, w, axis):
    me = lax.axis_index(axis)
    if me % 2:  # EXPECT: collective-divergence
        y = fused.allgather_matmul(x, w, axis)
    else:
        y = fused.allreduce_into(x, axis)
    return y


def inline_pairs_fused(x, size):
    return fused.fused_permute(x, "x", [(i, i ^ 1) for i in range(size)])  # EXPECT: unchecked-permutation


def unchecked_name_fused(x, size):
    pairs = [(i, (i + 3) % size) for i in range(size)]
    return fused.fused_permute(x, "x", pairs)  # EXPECT: unchecked-permutation
