"""Known-clean: sibling paths that agree on collective order, and an
algorithm switch (different ops entirely — a uniform config choice,
not a reordering of one shared multiset)."""

from hpc_patterns_tpu.comm import collectives, ring


def same_order_both_arms(comm, x, big):
    if x.shape[0] > big:
        g = comm.all_gather(x * 2)
        s = comm.reduce_scatter(x * 2)
    else:
        g = comm.all_gather(x)
        s = comm.reduce_scatter(x)
    return g, s


def algorithm_switch(x, use_library):
    # WHICH op runs changes, not the order of a shared multiset
    if use_library:
        return collectives.allreduce(x, "x", "sum")
    return ring.ring_allreduce(x, "x")
