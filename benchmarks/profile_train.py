"""Step profile: trace N training steps and print the op_profile
category breakdown (the table RESULTS.md quotes).

Builds the same step as benchmarks/bench_train.py (same args), runs a
warmup, traces a few steps with jax.profiler, and parses the trace via
xprof's op_profile converter into (category, % of device time, MXU
utilization) rows.

Usage: python benchmarks/profile_train.py [--seq=N] [--steps=8] [...]
"""

import glob
import json
import sys
import tempfile

import jax
from jax import lax

from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_batch,
    make_optimizer,
)
from hpc_patterns_tpu.models.transformer import loss_fn
from functools import partial
import optax


def arg(name, default, cast):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def _print_tree(prog, min_pct=0.5, top_children=3):
    """Category rows of one program node (this xprof's op_profile JSON:
    byProgramExcludeIdle -> program -> category -> op)."""
    total = prog.get("metrics", {}).get("rawTime", 1) or 1
    cats = sorted(prog.get("children", []),
                  key=lambda c: -c.get("metrics", {}).get("rawTime", 0))
    print(f"{'category / top ops':48s} {'%time':>6s} {'mxu%':>6s} "
          f"{'membw%':>7s}")
    for c in cats:
        m = c.get("metrics", {})
        pct = 100.0 * m.get("rawTime", 0) / total
        if pct < min_pct:
            continue
        bw = (m.get("bandwidthUtils") or [0])[0] * 100.0
        print(f"{c.get('name', '?')[:48]:48s} {pct:6.1f} "
              f"{m.get('flops', 0) * 100:6.1f} {bw:7.1f}")
        ops = sorted(c.get("children", []),
                     key=lambda x: -x.get("metrics", {}).get("rawTime", 0))
        for cc in ops[:top_children]:
            cm = cc.get("metrics", {})
            cbw = (cm.get("bandwidthUtils") or [0])[0] * 100.0
            print(f"  {cc.get('name', '?')[:46]:46s} "
                  f"{100.0 * cm.get('rawTime', 0) / total:6.1f} "
                  f"{cm.get('flops', 0) * 100:6.1f} {cbw:7.1f}")


def main():
    on_tpu = jax.default_backend() == "tpu"
    cfg = TransformerConfig(
        vocab=arg("vocab", 32768 if on_tpu else 256, int),
        d_model=arg("d", 1024 if on_tpu else 64, int),
        n_heads=arg("heads", 8 if on_tpu else 4, int),
        n_layers=arg("layers", 8 if on_tpu else 2, int),
        d_ff=arg("ff", 4096 if on_tpu else 128, int),
        max_seq=arg("seq", 2048 if on_tpu else 64, int),
        dtype="bfloat16",
        attention=arg("attn", "flash" if on_tpu else "full", str),
        remat=bool(arg("remat", 1, int)),
        n_kv_heads=arg("kv", 0, int),
        loss_chunk=arg("chunk", 0, int),
        remat_policy=arg("rp", "split", str),
        pos_embed=arg("pos", "learned", str),
        mlp_impl=arg("mlp", "dense", str),
    )
    batch = arg("batch", 8 if on_tpu else 2, int)
    steps = arg("steps", 8, int)
    optimizer = make_optimizer()
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg,
                                         optimizer=optimizer)
    tokens = make_batch(jax.random.PRNGKey(1), cfg, batch, cfg.max_seq)

    @partial(jax.jit, static_argnums=(2,))
    def run_t(carry, tokens, n):
        def one_step(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(partial(loss_fn, cfg=cfg))(
                params, tokens
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        _, losses = lax.scan(one_step, carry, None, length=n)
        return losses[-1]

    # warmup/compile outside the trace
    jax.block_until_ready(run_t((params, opt_state), tokens, steps))
    logdir = tempfile.mkdtemp(prefix="hpcpat_prof_")
    with jax.profiler.trace(logdir):
        jax.block_until_ready(run_t((params, opt_state), tokens, steps))

    xspace = sorted(glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True))
    if not xspace:
        print(f"no xplane under {logdir}")
        return
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xspace[-1]], "op_profile", params={}
    )
    prof = json.loads(data) if isinstance(data, (str, bytes)) else data
    progs = prof.get("byProgramExcludeIdle", {}).get("children", [])
    if not progs:
        print(f"no programs in op_profile (trace dir {logdir})")
        return
    prog = max(progs, key=lambda p: p.get("metrics", {}).get("rawTime", 0))
    m = prog.get("metrics", {})
    bw = (m.get("bandwidthUtils") or [0])[0] * 100.0
    print(f"config: T={cfg.max_seq} B={batch} kv={cfg.n_kv_heads} "
          f"remat={cfg.remat}/{cfg.remat_policy} chunk={cfg.loss_chunk} "
          f"pos={cfg.pos_embed} mlp={cfg.mlp_impl} steps={steps}")
    print(f"program {prog.get('name', '?')}: flops-util "
          f"{m.get('flops', 0) * 100:.1f}%  hbm-bw {bw:.1f}%  "
          f"(trace dir {logdir})")
    _print_tree(prog)


if __name__ == "__main__":
    main()
