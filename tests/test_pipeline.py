"""On-chip DMA/compute pipeline tests (the Pallas side of C1).

Timing claims are TPU-only (bench.py); here the interpreter validates the
kernel *semantics*: all computing variants produce the identical checksum
(the reference's self-validation idea, SURVEY.md §4.2), scalars are
runtime (no recompiles), and the amortized-timing protocol is sane.
"""

import numpy as np
import pytest

from hpc_patterns_tpu.concurrency import pipeline
from hpc_patterns_tpu.harness.timing import amortized_seconds


@pytest.fixture(scope="module")
def hbm():
    return pipeline.make_hbm_array(4, 8, seed=1)


class TestOverlapKernel:
    def test_overlap_matches_serial_checksum(self, hbm):
        a = pipeline.overlap_run(hbm, mode="overlap", tripcount=3, passes=2)
        b = pipeline.overlap_run(hbm, mode="serial", tripcount=3, passes=2)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_checksum_depends_on_data(self, hbm):
        other = pipeline.make_hbm_array(4, 8, seed=2)
        a = pipeline.overlap_run(hbm, mode="serial", tripcount=3)
        b = pipeline.overlap_run(other, mode="serial", tripcount=3)
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_tripcount_changes_result(self, hbm):
        a = pipeline.overlap_run(hbm, mode="serial", tripcount=1)
        b = pipeline.overlap_run(hbm, mode="serial", tripcount=4)
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_dma_and_compute_modes_run(self, hbm):
        for mode in ("dma", "compute", "compute2"):
            out = pipeline.overlap_run(hbm, mode=mode, tripcount=2)
            assert np.asarray(out).shape == (8, 128)

    def test_out_direction_checksum_parity(self, hbm):
        # overlap_out's writeback flies under compute; the chain result
        # must be identical to the strictly-serialized walk
        a = pipeline.overlap_run(hbm, mode="overlap_out", tripcount=3, passes=2)
        b = pipeline.overlap_run(hbm, mode="serial_out", tripcount=3, passes=2)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pair_modes_checksum_parity(self, hbm):
        # the copy-through pipeline must read the same chunks as the
        # strictly-serialized in/out walk
        a = pipeline.overlap_run(hbm, mode="pair_overlap", passes=2)
        b = pipeline.overlap_run(hbm, mode="pair_serial", passes=2)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dma_out_mode_runs(self, hbm):
        out = pipeline.overlap_run(hbm, mode="dma_out", tripcount=1)
        assert np.asarray(out).shape == (8, 128)

    def test_bad_mode_and_shape(self, hbm):
        with pytest.raises(ValueError, match="mode"):
            pipeline.overlap_run(hbm, mode="warp")
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="128"):
            pipeline.overlap_run(jnp.zeros((2, 8, 64)), mode="serial")


class TestAmortizedTiming:
    def test_differencing_recovers_per_iter_cost(self):
        import time

        def fake_run(iters):
            time.sleep(0.002 * iters + 0.01)  # per-iter cost + fixed latency
            return np.zeros(1)

        per = amortized_seconds(fake_run, iters=10, repetitions=2, warmup=0)
        assert 0.001 < per < 0.004  # ~2 ms, latency term cancelled

    def test_rejects_single_iter(self):
        with pytest.raises(ValueError):
            amortized_seconds(lambda n: np.zeros(1), iters=1)

    def test_negative_difference_clamps_to_zero(self):
        def noisy(iters):
            return np.zeros(1)

        assert amortized_seconds(noisy, iters=4, repetitions=1, warmup=0) >= 0.0
