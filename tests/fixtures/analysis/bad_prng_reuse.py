"""Known-bad: one key, two draws — linear reuse and loop reuse."""

import jax


def double_sample(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # EXPECT: prng-key-reuse
    return a + b


def split_then_reuse_parent(key):
    sub1, sub2 = jax.random.split(key)
    noise = jax.random.normal(key, (2,))  # EXPECT: prng-key-reuse
    return sub1, sub2, noise


def loop_reuse(key, n):
    outs = []
    for _ in range(n):
        outs.append(jax.random.normal(key, (2,)))  # EXPECT: prng-key-reuse
    return outs
