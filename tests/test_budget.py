"""Tier-1 pins for segment SLO budgets (harness/budget.py).

The evaluator is a pure function of one reqtrace snapshot and the
per-class targets, so every axis rule is pinned on hand-built
tilings with known spends; the publish half is pinned against a
captured emit stream and the metrics registry; the end-to-end claim
— seeded chaos breaches the budget bucket it was injected into and
NO other — is pinned through the real engine in
tests/test_bench_serving.py (run_slo_budget asserts it in-run).
"""

import pytest

from hpc_patterns_tpu.harness import budget as budgetlib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import slo


def entry(*, segments, t_submit=0.0, t_first=1.0, t_finish=3.0,
          tokens=3, priority=0):
    return {"priority": priority, "t_submit": t_submit,
            "t_first": t_first, "t_finish": t_finish,
            "tokens": tokens, "outcome": "ok", "preemptions": 0,
            "segments": segments}


def snap(entries):
    return {"n": len(entries), "coverage_frac": 1.0,
            "requests": {str(i): e for i, e in enumerate(entries)}}


TARGETS = {0: slo.SLOTarget(ttft_s=1.0, tpot_s=0.1)}


class TestEvaluate:
    def test_ttft_axis_judges_the_submit_to_first_window(self):
        # queued eats 0.9s of a 1.0s TTFT target: past the 0.5 share
        # allowance (0.5s), inside every other budget line
        e = entry(segments=[["queued", 0.0, 0.9, None],
                            ["prefill", 0.9, 1.0, None],
                            ["decode", 1.0, 3.0, None]])
        breaches = budgetlib.evaluate(snap([e]), TARGETS)
        assert budgetlib.breached_segments(breaches) == {"queued"}
        (b,) = breaches
        assert (b["axis"], b["priority"]) == ("ttft", 0)
        assert b["worst_s"] == pytest.approx(0.9)
        assert b["allowance_s"] == pytest.approx(0.5)
        assert b["kind"] == budgetlib.BUDGET_KIND

    def test_tpot_axis_scales_allowance_with_token_count(self):
        # prefetch_wait eats 1.0s of the decode phase; the allowance
        # is share * tpot * (tokens-1) = 0.35 * 0.1 * 2 = 70ms
        e = entry(segments=[["prefill", 0.0, 1.0, None],
                            ["decode", 1.0, 1.5, None],
                            ["prefetch_wait", 1.5, 2.5, None],
                            ["decode", 2.5, 3.0, None]])
        breaches = budgetlib.evaluate(snap([e]), TARGETS)
        assert budgetlib.breached_segments(breaches) \
            == {"prefetch_wait"}
        (b,) = breaches
        assert b["axis"] == "tpot"
        assert b["worst_s"] == pytest.approx(1.0)
        assert b["allowance_s"] == pytest.approx(0.07)

    def test_single_token_response_skips_the_tpot_axis(self):
        # tokens < 2: no inter-token interval exists, so even a huge
        # decode-phase stall has no per-token yardstick to breach
        e = entry(segments=[["prefill", 0.0, 1.0, None],
                            ["prefetch_wait", 1.0, 3.0, None]],
                  tokens=1)
        assert budgetlib.evaluate(snap([e]), TARGETS) == []

    def test_within_allowance_is_silent(self):
        e = entry(segments=[["queued", 0.0, 0.3, None],
                            ["prefill", 0.3, 1.0, None],
                            ["decode", 1.0, 3.0, None]])
        assert budgetlib.evaluate(snap([e]), TARGETS) == []

    def test_unbudgeted_segment_and_untargeted_class_never_breach(self):
        # decode has no budget line; priority 7 has no SLO target
        e1 = entry(segments=[["decode", 0.0, 3.0, None]])
        e2 = entry(segments=[["queued", 0.0, 3.0, None]], priority=7)
        assert budgetlib.evaluate(snap([e1, e2]), TARGETS) == []

    def test_inflight_request_has_no_finalized_window(self):
        e = entry(segments=[["queued", 0.0, None, None]],
                  t_first=None, t_finish=None)
        assert budgetlib.evaluate(snap([e]), TARGETS) == []

    def test_untracked_gap_is_itself_budgeted(self):
        # a bare 0.9s hole before t_first: finalize tiles it as
        # untracked, and the tight 0.15 share alarms on it
        e = entry(segments=[["prefill", 0.9, 1.0, None],
                            ["decode", 1.0, 3.0, None]])
        breaches = budgetlib.evaluate(snap([e]), TARGETS)
        assert budgetlib.breached_segments(breaches) == {"untracked"}

    def test_aggregates_per_class_and_tracks_the_worst(self):
        mild = entry(segments=[["queued", 0.0, 0.6, None],
                               ["decode", 0.6, 3.0, None]])
        bad = entry(segments=[["queued", 0.0, 0.9, None],
                              ["decode", 0.9, 3.0, None]])
        ok = entry(segments=[["queued", 0.0, 0.2, None],
                             ["decode", 0.2, 3.0, None]])
        (b,) = budgetlib.evaluate(snap([mild, bad, ok]), TARGETS)
        assert (b["n"], b["breached"]) == (3, 2)
        assert b["worst_s"] == pytest.approx(0.9)
        assert b["worst_seq_id"] == 1

    def test_custom_budget_and_slo_duck_typing(self):
        # a zero-allowance custom budget breaches on any spend; the
        # evaluator reads ttft_slo_s-style attrs when present
        class Tgt:
            ttft_slo_s = 1.0
            tpot_slo_s = 0.1

        tight = budgetlib.SLOBudget(ttft_shares={"prefill": 0.01})
        e = entry(segments=[["prefill", 0.0, 1.0, None],
                            ["decode", 1.0, 3.0, None]])
        breaches = budgetlib.evaluate(snap([e]), {0: Tgt()}, tight)
        assert budgetlib.breached_segments(breaches) == {"prefill"}

    def test_breaches_sort_by_class_axis_and_severity(self):
        big = entry(segments=[["queued", 0.0, 0.95, None],
                              ["prefetch_wait", 1.0, 3.0, None]])
        rows = budgetlib.evaluate(snap([big]), TARGETS)
        assert [(b["axis"], b["segment"]) for b in rows] == [
            ("tpot", "prefetch_wait"), ("ttft", "queued")]


class TestPublishAndFormat:
    def test_publish_emits_records_and_bumps_counters(self):
        e = entry(segments=[["queued", 0.0, 0.9, None],
                            ["decode", 0.9, 3.0, None]])
        breaches = budgetlib.evaluate(snap([e]), TARGETS)
        emitted = []
        metricslib.configure(enabled=True)
        try:
            budgetlib.publish(breaches,
                              emit=lambda **kw: emitted.append(kw))
            m = metricslib.get_metrics().snapshot()
        finally:
            metricslib.configure(enabled=False)
        assert [r["kind"] for r in emitted] == ["slo_budget"]
        assert emitted[0]["segment"] == "queued"
        assert m["counters"]["budget.breach.queued"] == 1

    def test_publish_without_emit_or_metrics_is_a_noop(self):
        e = entry(segments=[["queued", 0.0, 0.9, None],
                            ["decode", 0.9, 3.0, None]])
        budgetlib.publish(budgetlib.evaluate(snap([e]), TARGETS))

    def test_format_names_the_breach_or_says_all_clear(self):
        assert "within allowance" in budgetlib.format_budget([])
        e = entry(segments=[["queued", 0.0, 0.9, None],
                            ["decode", 0.9, 3.0, None]])
        text = budgetlib.format_budget(
            budgetlib.evaluate(snap([e]), TARGETS))
        assert "SLO BUDGET BREACHES" in text
        assert "queued" in text and "900ms" in text and "500ms" in text
