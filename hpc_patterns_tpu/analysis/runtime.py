"""Runtime complements to the static rules: donation poisoning and the
collective schedule verifier.

Two helpers live here, each the belt-and-braces RUNTIME check behind a
static rule family:

**Donation poisoning** (:func:`poison_donated`, behind
``donation-alias``). The hazard (round 6's "poisoned cache"): on CPU a
freshly-built executable often does NOT honor a donation, so a
zero-copy host view of a donated input keeps reading stable values and
the bug passes every test — until a cache-loaded (or TPU) executable
honors the donation and mutates the view in place, corrupting whatever
bookkeeping was built on it. ``poison_donated`` removes the luck: it
wraps a jitted function and, after each call completes, overwrites
every donated input buffer that the executable did NOT alias into an
output with a sentinel byte pattern. Wiring: ``tests/conftest.py``
installs the wrappers around the serving engine's jitted entry points
for ``tests/test_serving.py`` (always) and for the whole suite under
``HPC_PATTERNS_POISON_DONATED=1``.

**Collective schedule verification** (:class:`CollectiveSchedule`,
behind ``collective-divergence``/``collective-order``). The hazard is
the reference suite's silent MPI deadlock: SPMD ranks disagreeing on
which collective comes next hang with no error. Statically the
shardlint rules forbid the divergence-shaped code; at runtime every
eager ``Communicator`` collective (and every recorder-traced
``harness.timing.measure`` repetition) is fingerprinted into a
per-rank hash chain over ``(op, seq, shape, dtype, axis)``. The
running digest is stamped into flight-recorder snapshots
(``harness/trace.py``) and cross-checked at merge time
(``harness/collect.py``): equal digests PROVE the rank schedules
matched; on mismatch the merge names the first divergent
``(rank, op, seq)``. Under ``apps/launch.py`` the chain additionally
persists a tiny per-rank progress file on every record, so a TIMED-OUT
rank's position is readable post-mortem — a hang reads as "rank 2 is
at allreduce#17, rank 0 at sendrecv_ring#17" instead of a dead tunnel.

This module is import-light on purpose (stdlib only; jax is imported
inside the poison helpers): the schedule verifier must be usable from
jax-free launcher children and from ``harness/trace.py``, whose
disabled path stays jax-free at import time.
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import json
import os
import threading
from collections import deque

#: sentinel byte: 0xAB patterns decode to huge-magnitude garbage in
#: every dtype we serve (int32 -1414812757, implausible floats), so a
#: poisoned read corrupts comparisons instead of looking plausible
SENTINEL_BYTE = 0xAB

#: env names mirroring ``topology.ENV_TRACE_DIR`` / ``ENV_PROCESS_ID``
#: — duplicated as literals so this module stays importable without
#: jax (topology imports jax at module scope); tests assert the pair
#: stays in sync with topology's constants.
ENV_TRACE_DIR = "HPCPAT_TRACE_DIR"
ENV_PROCESS_ID = "HPCPAT_PROCESS_ID"

#: chain entries retained per process (the digest always covers the
#: FULL history; the window only bounds what a snapshot can name)
SCHEDULE_WINDOW = 4096


# ---------------------------------------------------------------------------
# collective schedule verifier
# ---------------------------------------------------------------------------


class CollectiveSchedule:
    """Per-rank hash chain over collective fingerprints.

    ``record(op, seq, ...)`` folds one fingerprint into the running
    digest: ``digest_k = H(digest_{k-1} | op | seq | shape | dtype |
    axis)``. Two ranks of an SPMD program that issued the identical
    collective sequence therefore hold the identical digest — one
    string comparison at merge time proves N whole schedules matched —
    while the retained entry window lets a mismatch be localized to
    the first divergent ``(op, seq)``.
    """

    def __init__(self, *, window: int = SCHEDULE_WINDOW):
        self._lock = threading.Lock()
        self.window = window
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.n = 0
            self.digest = ""
            self.entries: deque = deque(maxlen=self.window)

    def record(self, op: str, seq: int, *, shape=None, dtype=None,
               axis=None, algorithm=None) -> dict:
        # ``algorithm`` joined the fingerprint with the fused-collective
        # route (PR 8): a rank running the host-driven path while its
        # peers run the in-kernel ring is a schedule divergence even
        # when (op, seq, shape) agree — the wire protocols differ.
        fp = (f"{op}|{int(seq)}|{tuple(shape) if shape is not None else ()}"
              f"|{dtype or ''}|{axis or ''}|{algorithm or ''}")
        with self._lock:
            digest = hashlib.sha256(
                f"{self.digest}\x1f{fp}".encode()).hexdigest()[:16]
            entry = {
                "i": self.n, "op": str(op), "seq": int(seq),
                "shape": list(shape) if shape is not None else None,
                "dtype": str(dtype) if dtype is not None else None,
                "axis": str(axis) if axis is not None else None,
                "algorithm": (str(algorithm) if algorithm is not None
                              else None),
                "digest": digest,
            }
            self.digest = digest
            self.entries.append(entry)
            self.n += 1
        return entry

    @property
    def last(self) -> dict | None:
        return self.entries[-1] if self.entries else None

    def snapshot(self) -> dict:
        """JSON-able chain state — the ``collectives`` field of a
        flight-recorder snapshot (``harness/trace.py``), cross-checked
        rank-against-rank by ``harness/collect.py``."""
        with self._lock:
            return {
                "n": self.n,
                "digest": self.digest,
                "window": self.window,
                "entries": [dict(e) for e in self.entries],
            }


_schedule = CollectiveSchedule()


def collective_schedule() -> CollectiveSchedule:
    """The process-wide chain (one per rank in a launch)."""
    return _schedule


def reset_collective_schedule() -> None:
    """Fresh chain — ``harness.trace.configure`` calls this so every
    instrumented run's chain starts at the same genesis on every rank."""
    _schedule.reset()


def _progress_path(trace_dir: str, process_id: int) -> str:
    return os.path.join(trace_dir, f"rank{process_id:05d}.sched.json")


def record_collective(op: str, seq: int, *, shape=None, dtype=None,
                      axis=None, algorithm=None) -> dict:
    """Fingerprint one collective into the process chain.

    Called at ISSUE time (before the wait): ``comm/communicator.py``
    records every eager collective — host-driven AND fused-kernel
    routes, with ``algorithm`` in the fingerprint so the fast path is
    never invisible to the verifier — and ``harness/timing.py`` every
    traced timed repetition. Under a launcher (``HPCPAT_TRACE_DIR``
    exported by ``apps/launch.py --trace-out``) each record also
    persists the chain head to ``rank<id>.sched.json`` — that write is
    what makes a HUNG rank diagnosable: the rank never reaches its
    trace-snapshot handoff, but the collective it is stuck in is
    already on disk for the launcher's timeout report."""
    entry = _schedule.record(op, seq, shape=shape, dtype=dtype, axis=axis,
                             algorithm=algorithm)
    trace_dir = os.environ.get(ENV_TRACE_DIR)
    if trace_dir:
        try:
            pid = int(os.environ.get(ENV_PROCESS_ID) or 0)
        except ValueError:
            pid = 0
        # payload built from THIS call's entry (not a re-read of the
        # shared chain head): concurrent recorders each write a
        # self-consistent (last, n, digest) triple
        payload = {
            "process_id": pid,
            "n": entry["i"] + 1,
            "digest": entry["digest"],
            "last": {"i": entry["i"], "op": entry["op"],
                     "seq": entry["seq"]},
        }
        path = _progress_path(trace_dir, pid)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            # write-then-rename: a rank killed mid-write (the timeout
            # path's proc.kill()) must not leave a truncated file —
            # the straggler whose position the hang report exists to
            # print is exactly the rank most likely to die mid-write
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            pass  # forensics are best-effort; never fail the collective
    return entry


# ---------------------------------------------------------------------------
# donation poisoning
# ---------------------------------------------------------------------------


def _buffer_ptrs(leaf) -> list[tuple[int, int]]:
    """(pointer, nbytes) per addressable shard; [] when the backend
    hides them (the helper is then inert, never wrong)."""
    out = []
    try:
        for shard in leaf.addressable_shards:
            db = shard.data
            out.append((db.unsafe_buffer_pointer(), db.nbytes))
    except Exception:  # noqa: BLE001 - best-effort probe
        return []
    return out


def poison_donated(fn, donate_argnums, *, sentinel: int = SENTINEL_BYTE):
    """Wrap jitted ``fn`` so donated inputs die loudly after each call.

    After ``fn(*args)`` completes (outputs blocked on), every jax leaf
    of each ``args[i]`` for ``i in donate_argnums`` is overwritten with
    ``sentinel`` bytes — unless the executable aliased that buffer into
    an output (donation honored: poisoning would corrupt the result;
    the aliasing itself already invalidates stale host views) or jax
    deleted it. The wrapper forwards ``__wrapped__``, so
    ``harness.trace.jit_cache_size`` / ``compile_watch`` (and through
    them ``serving.prefill_cache_size``) keep probing the real jit.

    ``wrapper.poison_count`` accumulates poisoned buffers — tests
    assert on it to prove the hook engaged rather than silently
    no-op'ing.
    """
    donate_argnums = tuple(donate_argnums)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        import jax

        out = fn(*args, **kwargs)
        leaves_out = jax.tree_util.tree_leaves(out)
        for leaf in leaves_out:
            jax.block_until_ready(leaf)
        out_ptrs = {
            ptr
            for leaf in leaves_out
            if isinstance(leaf, jax.Array)
            for ptr, _ in _buffer_ptrs(leaf)
        }
        for i in donate_argnums:
            if i >= len(args):
                continue
            for leaf in jax.tree_util.tree_leaves(args[i]):
                if not isinstance(leaf, jax.Array):
                    continue
                try:
                    if leaf.is_deleted():
                        continue
                except Exception:  # noqa: BLE001
                    continue
                for ptr, nbytes in _buffer_ptrs(leaf):
                    if ptr in out_ptrs or nbytes == 0:
                        continue
                    ctypes.memset(ptr, sentinel, nbytes)
                    wrapper.poison_count += 1
        return out

    wrapper.poison_count = 0
    # functools.wraps already set __wrapped__ = fn; make the contract
    # explicit since the trace probe depends on it
    wrapper.__wrapped__ = fn
    return wrapper


#: the serving engine's donating jit entry points and their donated
#: positions — MUST mirror the donate_argnums in models/serving.py
#: (tests/test_analysis.py asserts they stay in sync)
SERVING_POISON_TARGETS: dict[str, tuple[int, ...]] = {
    "_chunk_step": (1, 2, 3, 4, 5),
    "_spec_chunk": (2, 3, 4, 5, 6, 7),
    "_prefill_one": (3,),
    "_admit_row": (0, 1, 2, 3, 4),
    # the serving plane's KV-handoff install scatter (round 10): the
    # pool is donated — an aliased host view of it would be the exact
    # PR 2 bug class resurfacing on the migration path
    "_install_pages": (0,),
    # the prefix-sharing tail prefill (round 12): donates the pool like
    # _prefill_one — an aliased view of a SHARED page would corrupt
    # every reader at once, so the poison harness must cover it
    "_tail_prefill_one": (3,),
}


def install_serving_poison():
    """Swap the serving module's jitted entry points for poisoned
    wrappers; returns an ``uninstall()`` restoring the originals.
    Import stays local so merely importing this module never drags the
    models package in."""
    from hpc_patterns_tpu.models import serving

    originals = {}
    for name, argnums in SERVING_POISON_TARGETS.items():
        originals[name] = getattr(serving, name)
        setattr(serving, name, poison_donated(originals[name], argnums))

    def uninstall():
        for name, fn in originals.items():
            setattr(serving, name, fn)

    return uninstall
