"""Shared app scaffolding: device/mesh resolution and reporting units.

The reference duplicates this in every main() (device pick at
allreduce-mpi-sycl.cpp:135-152, world-size guard at :95-97, reporting at
:185-206); apps here share one implementation.
"""

from __future__ import annotations

import os
from typing import Callable

import jax

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.comm import Communicator


def run_instrumented(run_fn: Callable[[object], int], args) -> int:
    """The shared ``--metrics``/``--trace`` session every app main()
    runs through: install a fresh process-wide metrics registry AND
    flight recorder from the flags (both no-ops without their flag —
    the disabled fast path), run the app, and on ANY exit path append
    the closing snapshot records to ``--log``: one ``kind=metrics``
    (aggregated by `python -m hpc_patterns_tpu.harness.report`) and one
    ``kind=trace`` (exported to Chrome-trace JSON by `python -m
    hpc_patterns_tpu.harness.trace`). Appending (never truncating)
    keeps the app's own records: the snapshots are the log's closing
    records, like run.sh's trailing grep summary.

    Distributed handoff: a traced run under apps/launch.py additionally
    writes its recorder snapshot to the launcher-provided
    ``HPCPAT_TRACE_DIR`` as ``rank<id>.trace.json`` (independent of
    ``--log`` — the launcher, not the child, owns the merged artifact),
    where the launcher collects every rank's ring for the clock-aligned
    merge (harness/collect.py)."""
    from hpc_patterns_tpu.harness import metrics, trace
    from hpc_patterns_tpu.harness.runlog import RunLog

    # mirror_traces stays off here: profiling.maybe_trace toggles it
    # (and restores it) around the actual traced region, so spans only
    # pay for TraceAnnotation while a trace is live
    m = metrics.configure(enabled=getattr(args, "metrics", False))
    trace_kw = {}
    if getattr(args, "trace_capacity", None):
        trace_kw["capacity"] = args.trace_capacity
    rec = trace.configure(enabled=getattr(args, "trace", False),
                          **trace_kw)
    try:
        return run_fn(args)
    finally:
        # ONE snapshot serves both sinks: the --log record and the
        # per-rank handoff file must carry identical events and clock
        # anchors (the offline re-merge from --log files and the
        # launcher's merge would otherwise disagree)
        trace_dir = os.environ.get(topology.ENV_TRACE_DIR)
        rec_snap = (rec.snapshot()
                    if rec.enabled and (getattr(args, "log", None)
                                        or trace_dir) else None)
        if getattr(args, "log", None) and (m.enabled or rec.enabled):
            log = RunLog(args.log, truncate=False)
            if m.enabled:
                log.emit(kind="metrics", **m.snapshot())
            if rec.enabled:
                log.emit(kind="trace", **rec_snap)
        if rec.enabled and trace_dir:
            trace.write_rank_snapshot(rec, trace_dir, snapshot=rec_snap)


def _trace_recorder():
    """The active flight recorder, or None — lazy so apps that never
    enable tracing don't pay the harness import here."""
    from hpc_patterns_tpu.harness import trace as tracelib

    return tracelib.active()


def make_communicator(
    backend: str | None, world: int, *, even: bool = False, axis: str = "x"
) -> Communicator:
    """Build the app's communicator: all (or ``world``) devices of the
    chosen backend on a 1-D mesh.

    ``world=-1`` (auto) uses every device — the miniapps' mpirun -np
    choice made explicit. ``even=True`` reproduces the reference's
    even-rank-count precondition (allreduce-mpi-sycl.cpp:95-97) by
    dropping the odd device out, rather than failing, because a 1-chip
    dev box is the common case here.

    Joins a launcher rendezvous first when one is in the environment
    (apps/launch.py ≙ mpirun; init is the MPI_Init analog), so the
    device list is the GLOBAL multi-process view. A traced
    multi-process run then records a sync anchor off a global barrier
    (all ranks exit within the release-propagation window), which the
    cross-rank merge uses to align per-rank clocks tighter than wall
    time — every rank runs the same command line, so either all ranks
    reach the barrier or none does (the SPMD invariant).
    """
    topology.init_distributed_from_env()
    rec = _trace_recorder()
    if rec is not None and jax.process_count() > 1:
        # barrier = a cross-process allgather: no process receives the
        # gathered value before every process contributed, so the
        # returns cluster inside the release-propagation window. The
        # same primitive reduce_across_processes uses — NOT
        # sync_global_devices, whose jitted psum the CPU backend
        # rejects for multiprocess computations on jax 0.4.x.
        import numpy as np
        from jax.experimental import multihost_utils

        multihost_utils.process_allgather(np.float64(0.0))
        rec.mark_sync("make_communicator")
    devices = topology.get_devices(backend)
    if world == -1:
        world = len(devices)
    if world > len(devices):
        raise topology.TopologyError(
            f"world {world} > {len(devices)} visible devices"
        )
    if even and world % 2 and world > 1:
        world -= 1
    mesh = topology.make_mesh({axis: world}, devices[:world])
    return Communicator(mesh, axis)


def allreduce_bus_bandwidth_gbps(nbytes: int, seconds: float, world: int) -> float:
    """Bus bandwidth for an allreduce: algbw · 2(size−1)/size.

    The standard ring-limit normalization, so numbers are comparable
    across world sizes — the BASELINE.json "allreduce GB/s" metric.
    Degenerates to 0 for world=1 (no wire traffic).
    """
    if seconds <= 0:
        return float("inf")
    return (nbytes / seconds / 1e9) * (2 * (world - 1) / world)


def local_rows(global_array) -> list[tuple[int, "jax.Array"]]:
    """(rank, row) pairs this process can address, for a (size, ...) array
    sharded one row per rank. In multi-process runs each process
    validates only its own ranks' buffers — exactly the reference's
    per-rank validation (allreduce-mpi-sycl.cpp:192-206); single-process
    it is every row."""
    rows = []
    for shard in global_array.addressable_shards:
        lead = shard.index[0] if shard.index else slice(0, 1)
        start = lead.start or 0
        data = shard.data
        for i in range(data.shape[0]):
            rows.append((start + i, data[i]))
    return sorted(rows, key=lambda rv: rv[0])


def reduce_across_processes(value: float, op=None) -> float:
    """Reduce a host scalar across processes (default max — the
    reference's MPI_Allreduce(MAX) timing convention). Single-process:
    identity. The one allgather-and-reduce implementation shared by the
    app verdicts; harness.timing.max_across_processes is its
    harness-layer twin."""
    import numpy as np

    if jax.process_count() == 1:
        return float(value)
    from jax.experimental import multihost_utils

    op = np.max if op is None else op
    return float(op(multihost_utils.process_allgather(np.float64(value))))


def all_processes_agree(ok: bool) -> bool:
    """Cross-process AND of a local verdict (the reference MAX-reduces
    times and each rank asserts its own buffer; a distributed SUCCESS
    needs every rank's assert to hold). Single-process: identity."""
    return reduce_across_processes(0.0 if ok else 1.0) == 0.0


def supports_memory_kind(kind: str) -> bool:
    """Whether the backend exposes the given JAX memory kind (TPU has
    pinned_host + device; CPU meshes typically only the default).
    Delegates to the single probe home (memory/kinds.py)."""
    from hpc_patterns_tpu.memory import kinds as kindslib

    return kindslib.supports_memory_kind(kind)
