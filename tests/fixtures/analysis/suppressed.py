"""Suppression semantics: trailing and standalone forms silence a
named rule; bare or unknown ``disable`` is itself a finding and the
underlying hazard stays live."""

import jax
import numpy as np


def accepted_one_shot(x):
    return jax.jit(lambda v: v + 1)(x)  # jaxlint: disable=recompile-hazard — fixture: accepted one-shot


def _dispatch_chunk(engine):
    # jaxlint: disable=host-sync-in-dispatch — fixture: standalone
    # form, justification continuing over a second comment line
    return np.asarray(engine.pos)


def bare_disable(x):
    return jax.jit(lambda v: v)(x)  # jaxlint: disable   (EXPECT: bad-suppression, recompile-hazard)


def unknown_rule(x):
    return jax.jit(lambda v: v - 1)(x)  # jaxlint: disable=no-such-rule  (EXPECT: bad-suppression, recompile-hazard)
