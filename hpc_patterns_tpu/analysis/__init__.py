"""jaxlint: static hazard analysis for the JAX patterns this repo has
been burned by — donation aliasing, dispatch-path host syncs, per-call
re-jits, PRNG key reuse, and tracer leaks.

Run it over the package (CI mode exits nonzero on any unsuppressed
finding)::

    python -m hpc_patterns_tpu.analysis --ci

The motivating incident is PR 2's "poisoned cache": a zero-copy
``np.asarray`` host view of a buffer that a donated jit arg later
mutated in place (``serving._dispatch_chunk``). The flight recorder
(harness/trace.py) can show that bug only *after* it burns a chip
session; the ``donation-alias`` rule catches it at review time. The
recorder shows you the bubble; jaxlint stops the next one.

Public surface:

- :func:`run_paths` / :class:`Report` / :class:`Finding` — the engine
  (hpc_patterns_tpu.analysis.core; rules in .rules self-register);
- :func:`dispatch_critical` — no-op marker decorator: the
  ``host-sync-in-dispatch`` rule treats any function carrying it as
  dispatch-critical, in addition to the configured name list;
- :func:`poison_donated` (hpc_patterns_tpu.analysis.runtime) — the
  RUNTIME complement: wraps a jitted fn and clobbers donated inputs
  after each call, so an aliasing bug the analyzer missed fails loudly
  in tests instead of silently on a chip.
"""

from __future__ import annotations

from hpc_patterns_tpu.analysis.core import (  # noqa: F401
    AnalysisConfig,
    DEFAULT_DISPATCH_CRITICAL,
    Finding,
    Report,
    analyze_file,
    registered_rules,
    run_paths,
)


def dispatch_critical(fn):
    """Marker decorator: this function is on a dispatch-critical path
    (its job is to ENQUEUE device work, never to wait for it). Purely
    declarative — the wrapped function is returned unchanged — but the
    ``host-sync-in-dispatch`` rule audits every function carrying it,
    so the marker turns a design intention into a checked invariant."""
    return fn
