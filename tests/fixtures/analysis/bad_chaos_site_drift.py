"""Known-bad: chaos site/kind names that drifted from the
declarations. A typo'd site name injects nothing (the soak silently
stops covering the collective path), a recorded injection claims a
fault kind KINDS never declared, and a spec string's kind prefix
dies at parse time in the one run least equipped to debug it."""

KINDS = ("straggler", "drop", "stall")
SITES = ("collective", "host_transfer")


def soak(chaos, i):
    # "colective": the typo'd site matches no maybe_inject caller
    if chaos.maybe_inject("colective", i):  # EXPECT: chaos-site-drift
        return True
    chaos.record_injection("collective", i, "meteor")  # EXPECT: chaos-site-drift
    return False


def configure_soak(chaos):
    chaos.configure("stal:at=3,delay_ms=5")  # EXPECT: chaos-site-drift
