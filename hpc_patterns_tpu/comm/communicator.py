"""Array-level communicator: mesh axis ≙ MPI communicator.

The reference's miniapp mains wire device buffers to MPI calls per rank
(allreduce-mpi-sycl.cpp:88-207). Here one process drives all local TPU
devices, so the per-rank view is created by ``shard_map``: a
:class:`Communicator` binds a mesh axis and exposes collectives over
global ``jax.Array``\\ s whose leading dimension is sharded on that axis —
row r of the global array is rank r's buffer, exactly the miniapp's
``VA/VB/VC`` per-rank layout.

Every operation jit-compiles a ``shard_map`` closure (cached per shape/
dtype/algorithm); on TPU the collectives run on HBM shards over ICI with
no host staging.
"""

from __future__ import annotations

import os
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpc_patterns_tpu.analysis import runtime as analysis_runtime
from hpc_patterns_tpu.comm import collectives, fused, ring
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.topology import shard_map

Algorithm = Literal["collective", "ring", "ring_chunked", "fused"]


def _ready_in_span(result, op: str = "collective", seq: int | None = None,
                   axis: str | None = None, algorithm: str | None = None):
    """Block before an open span exits so it measures collective
    completion, not async dispatch — the shard_map call returns an
    unready array. Only when a span actually records (metrics, trace
    mirroring, or the flight recorder); the disabled path stays fully
    async. With a recorder, the dispatch→completion window also lands
    as a ``comm.<op>`` slice on the device track, separating wire time
    from the host time around it; ``seq`` (the per-communicator
    collective counter) rides in the slice args so the cross-rank merge
    (harness/collect.py) can match the N ranks' windows of the SAME
    collective and measure its skew.

    Every eager collective is ALSO fingerprinted into the per-rank
    schedule hash chain (analysis/runtime.py) before the wait —
    whenever anything can consume the chain: a live flight recorder
    (the chain rides trace snapshots to the cross-rank merge) or a
    launcher-exported ``HPCPAT_TRACE_DIR`` (the per-record progress
    file is what names which collective a hung rank is stuck in, so
    it must engage even when the child wasn't run with ``--trace``).
    Reading ``.shape``/``.dtype`` off the unready array does not
    block, and with neither consumer present nothing is recorded —
    the disabled path stays fully async and byte-identical."""
    m = metricslib.get_metrics()
    rec = tracelib.active()
    if seq is not None and (
            rec is not None
            or analysis_runtime.ENV_TRACE_DIR in os.environ):
        analysis_runtime.record_collective(
            op, seq, shape=getattr(result, "shape", None),
            dtype=str(getattr(result, "dtype", "")) or None, axis=axis,
            algorithm=algorithm)
    if not (m.enabled or m.mirror_traces or rec is not None):
        return result
    if rec is not None:
        attrs = None if seq is None else {"seq": seq}
        t_disp = rec.mark_dispatch(f"comm.{op}", args=attrs)
        # jaxlint: disable=host-sync-in-dispatch — measures completion,
        # not dispatch (PR 1 review decision); only reached with a
        # recorder/metrics active, the disabled path stays fully async
        jax.block_until_ready(result)
        rec.mark_complete(f"comm.{op}", t_disp, args=attrs)
    else:
        # jaxlint: disable=host-sync-in-dispatch — same contract as
        # above: the recording span must not exit before the wire time
        # it claims to measure has elapsed
        jax.block_until_ready(result)
    return result


def _inject_chaos(seq: int) -> None:
    """Chaos injection, straggler site — called by every collective
    method BEFORE the shard_map closure is even built, so the injected
    delay precedes the dispatch itself: the straggler's device work for
    collective ``seq`` genuinely starts late (the other ranks stretch
    waiting for it), and the skew evidence in the cross-rank merge is
    the real perturbation, not an artifact of marker placement. One
    cached-config read when no chaos is active."""
    if chaoslib.active() is not None:
        chaoslib.maybe_inject("collective", seq)


def record_collective_bandwidth(op: str, nbytes: int, seconds: float,
                                **attrs) -> None:
    """Per-collective bandwidth gauge + latency histogram in the
    process-wide metrics registry (no-op when disabled): the
    observability layer's view of the BASELINE bandwidth metrics, so a
    sweep's ``kind=metrics`` snapshot carries the same numbers the
    per-point ``kind=result`` records do. ``attrs`` become gauges too
    (e.g. ``busbw_gbps=...`` for the ring-normalized form)."""
    m = metricslib.get_metrics()
    if not m.enabled or seconds <= 0:
        return
    m.gauge(f"comm.{op}.bandwidth_gbps").set(nbytes / seconds / 1e9)
    m.histogram(f"comm.{op}.s").observe(seconds)
    for key, value in attrs.items():
        m.gauge(f"comm.{op}.{key}").set(value)

# allreduce algorithm table: library collective vs hand-built rings vs
# the device-initiated fused ring — the comparison the reference exists
# to make (SURVEY.md §2.3(b)), extended one rung down the stack.
_ALLREDUCE = {
    "collective": lambda x, axis: collectives.allreduce(x, axis, "sum"),
    "ring": ring.ring_allreduce,
    # chunk over the trailing (data) axis — the leading axis is the
    # 1-row rank dimension inside shard_map
    "ring_chunked": lambda x, axis: ring.ring_allreduce_chunked(
        x, axis, scatter_axis=x.ndim - 1
    ),
    # the ring schedule run INSIDE a Pallas kernel (remote DMA per
    # step); byte-exact vs ring_chunked over the padded layout —
    # comm/fused.py. Sum only: _check_op guards the _pprod fallback.
    "fused": lambda x, axis: fused.fused_allreduce(x, axis),
}


class Communicator:
    """Collectives over one named axis of a mesh.

    ``Communicator(mesh, "x")`` plays the role of ``MPI_COMM_WORLD`` in
    the miniapps; ``size`` is ``MPI_Comm_size``. Arrays passed in must
    have a leading dimension equal to ``size`` (one row per rank); they
    are sharded onto the axis automatically if not already.
    """

    def __init__(self, mesh: Mesh, axis: str = "x"):
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        # jitted rank_filled initializers by (n, dtype): sweeps call it
        # once per point, and a fresh jax.jit per call re-traces every
        # time (jaxlint: recompile-hazard)
        self._rank_filled_cache: dict = {}
        # jitted allreduce closures by (shape, dtype, ALGORITHM):
        # benchmark sweeps race algorithms at one shape, and a cache
        # missing the algorithm key would thrash one slot per point
        # (each jit_allreduce call re-tracing the loser)
        self._jit_allreduce_cache: dict = {}
        # allgather_matmul closures, same keying discipline — the
        # fused-vs-collective bench times the eager method per rep
        self._agmm_cache: dict = {}
        # per-communicator collective counter: every eager collective
        # call takes the next value, and since all ranks of an SPMD
        # program issue the identical collective sequence, (span name,
        # seq) identifies THE SAME collective across ranks — what the
        # cross-rank trace merge fans its skew arrows over. Incremented
        # unconditionally (one integer add; the disabled trace path
        # stays byte-identical in recorded output).
        self._seq = 0

    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def row_sharding(self, ndim: int, memory_kind: str | None = None) -> NamedSharding:
        """Sharding that puts row r on rank r (leading dim over the axis).

        ``memory_kind`` maps the reference's USM allocator axis
        (``-H/-D``, allreduce-mpi-sycl.cpp:104-131) onto JAX memory
        kinds: ``"pinned_host"`` ≙ host USM, ``"device"``/None ≙ device
        USM (HBM)."""
        spec = P(self.axis, *([None] * (ndim - 1)))
        if memory_kind is None:
            return NamedSharding(self.mesh, spec)
        return NamedSharding(self.mesh, spec, memory_kind=memory_kind)

    def shard(self, x, memory_kind: str | None = None) -> jax.Array:
        """Place a (size, ...) array with one row per rank — the analog of
        each rank allocating + initializing its device buffer
        (allreduce-mpi-sycl.cpp:154-164)."""
        x = jnp.asarray(x)
        if x.shape[0] != self.size:
            raise ValueError(
                f"leading dim {x.shape[0]} != communicator size {self.size}"
            )
        return jax.device_put(x, self.row_sharding(x.ndim, memory_kind))

    def _shmap(self, fn, x, out_specs=None):
        spec = P(self.axis, *([None] * (jnp.ndim(x) - 1)))
        out = out_specs if out_specs is not None else spec
        mapped = shard_map(fn, mesh=self.mesh, in_specs=spec, out_specs=out)
        return jax.jit(mapped)

    # -- collectives over (size, n) arrays --------------------------------

    def _fused_route(self):
        """``(mesh, axis, geometry)`` the fused kernels run over. The
        kernels bind LOGICAL neighbor ids under ONE named axis (jax's
        dma-discharge rule and the logical id space are both
        single-axis), so a 1-D mesh runs as-is (geometry ``None`` —
        the identity ring) and a multi-axis mesh runs over its FLAT
        1-axis view with ring neighbors computed from mesh coordinates
        (:func:`fused.mesh_ring_geometry` — stride = product of the
        axis sizes to the right). Ranks sharing a ring position are
        replicas: each reduces its own copy, bitwise-identically, and
        :meth:`_fused_shmap` folds one representative row back per
        position."""
        if len(self.mesh.axis_names) == 1:
            return self.mesh, self.axis, None
        return (fused.flat_mesh(self.mesh), fused.FLAT_AXIS,
                fused.mesh_ring_geometry(self.mesh, self.axis))

    def _fused_shmap(self, mk_per_rank, *xs):
        """One jitted closure around a fused kernel over operands
        ``xs`` (each with the leading rank dim). ``mk_per_rank`` gets
        ``(axis, geometry)`` and returns the rank-local function —
        single-axis meshes shard_map it directly, multi-axis meshes
        take-expand every operand onto the flat mesh (row ``f`` =
        ring-position row ``pos(f)``), run the kernel, and fold the
        representative rows back, all inside the same jit (one compile
        per cache key, same as the 1-D route)."""
        mesh, axis, g = self._fused_route()
        per_rank = mk_per_rank(axis, g)
        specs = tuple(P(axis, *([None] * (jnp.ndim(v) - 1)))
                      for v in xs)
        mapped = shard_map(
            per_rank, mesh=mesh,
            in_specs=specs if len(specs) > 1 else specs[0],
            out_specs=specs[0])
        if g is None:
            return jax.jit(mapped)
        idx = jnp.asarray(g.positions())
        sel = jnp.asarray(g.ring_ids())
        shardings = tuple(NamedSharding(mesh, s) for s in specs)

        def run(*vals):
            expanded = [
                jax.device_put(jnp.take(v, idx, axis=0), s)
                for v, s in zip(vals, shardings)]
            return jnp.take(mapped(*expanded), sel, axis=0)

        return jax.jit(run)

    def allreduce(self, x, algorithm: Algorithm = "collective") -> jax.Array:
        """Elementwise sum across ranks; every row of the result holds the
        sum (MPI_Allreduce semantics, allreduce-mpi-sycl.cpp:61-67 for
        ``"collective"``; the :173-182 hand ring for ``"ring"``;
        two-phase bandwidth-optimal ring for ``"ring_chunked"``; the
        same two-phase ring as device-initiated in-kernel remote DMA
        for ``"fused"`` — comm/fused.py, docs/comm.md; on a multi-axis
        mesh the fused route runs over the flat view with
        coordinate-computed neighbors, :meth:`_fused_route`)."""
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.allreduce", algorithm=algorithm):
            if algorithm == "fused":
                result = self.jit_allreduce(x, algorithm)(x)
            else:
                impl = _ALLREDUCE[algorithm]
                result = self._shmap(
                    lambda local: impl(local, self.axis), x)(x)
            return _ready_in_span(
                result,
                op=f"allreduce.{algorithm}", seq=seq, axis=self.axis,
                algorithm=algorithm)

    def jit_allreduce(self, x, algorithm: Algorithm = "collective"):
        """The compiled allreduce closure for ``x``'s shape — what a
        benchmark should time (compile excluded per SURVEY.md §7(d)).
        Cached per (shape, dtype, axis, algorithm): an algorithm sweep
        at one shape gets one traced closure per algorithm instead of
        re-tracing whichever it asked for last (the axis key is
        redundant per instance — the communicator binds one axis — but
        pins the multi-axis sweep discipline the fused-route tests
        assert)."""
        key = (jnp.shape(x), str(jnp.result_type(x)), self.axis,
               algorithm)
        fn = self._jit_allreduce_cache.get(key)
        if fn is None:
            if algorithm == "fused":
                fn = self._fused_shmap(
                    lambda axis, g: (lambda local: fused.fused_allreduce(
                        local, axis, geometry=g)), x)
            else:
                impl = _ALLREDUCE[algorithm]
                fn = self._shmap(lambda local: impl(local, self.axis), x)
            self._jit_allreduce_cache[key] = fn
        return fn

    def pingpong(self, x) -> jax.Array:
        """Pairwise even/odd exchange: row r swaps with row r^1 — the
        pt2pt ping-pong config of BASELINE.json."""
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.pingpong"):
            return _ready_in_span(self.jit_pingpong(x)(x),
                                  op="pingpong", seq=seq,
                                  axis=self.axis)

    def jit_pingpong(self, x):
        """Compiled pairwise-exchange closure (for timing loops)."""
        return self._shmap(lambda l: ring.pairwise_exchange(l, self.axis), x)

    def sendrecv_ring(self, x, shift: int = 1) -> jax.Array:
        """One ring hop: row r moves to row (r+shift) % size
        (SendRecvRing, allreduce-mpi-sycl.cpp:43-59)."""
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.sendrecv_ring", shift=shift):
            return _ready_in_span(self._shmap(
                lambda l: ring.ring_shift(l, self.axis, shift), x)(x),
                op="sendrecv_ring", seq=seq, axis=self.axis)

    def all_gather(self, x) -> jax.Array:
        """Every rank receives every row: (size, n) -> (size, size, n)."""
        fn = lambda l: collectives.all_gather(l, self.axis, tiled=False).squeeze(1)[None]
        spec = P(self.axis, None, *([None] * (jnp.ndim(x) - 1)))
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.all_gather"):
            return _ready_in_span(self._shmap(fn, x, out_specs=spec)(x),
                                  op="all_gather", seq=seq,
                                  axis=self.axis)

    def reduce_scatter(self, x) -> jax.Array:
        """(size, size*n) rows -> (size, n): rank r gets chunk r of the sum."""
        fn = lambda l: collectives.reduce_scatter(l, self.axis, scatter_axis=jnp.ndim(x) - 1)
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.reduce_scatter"):
            return _ready_in_span(self._shmap(
                fn, x,
                out_specs=P(self.axis, *([None] * (jnp.ndim(x) - 1))))(x),
                op="reduce_scatter", seq=seq, axis=self.axis)

    def all_to_all(self, x) -> jax.Array:
        """Row r's chunk c goes to row c's chunk r (MPI_Alltoall)."""
        fn = lambda l: collectives.all_to_all(
            l, self.axis, split_axis=jnp.ndim(x) - 1, concat_axis=jnp.ndim(x) - 1
        )
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.all_to_all"):
            return _ready_in_span(self._shmap(fn, x)(x),
                                  op="all_to_all", seq=seq,
                                  axis=self.axis)

    # -- fused collective+consumer ops (comm/fused.py) --------------------

    def allgather_matmul(self, x, w,
                         algorithm: str = "fused") -> jax.Array:
        """``all_gather(x) @ w`` with per-rank weight panels: ``x`` is
        (size, m, k) — row r is rank r's activation block — and ``w``
        is (size, k, n) — row r is rank r's panel; the result row r is
        ``gathered_x @ w[r]`` of shape (size*m, n).

        ``algorithm="fused"`` runs the gather ring inside one Pallas
        kernel, each arriving shard feeding a matmul tile while the
        next shard is on the wire; ``"collective"`` is the host-driven
        oracle (XLA all-gather completes, then the tiles compute) with
        identical per-tile accumulation, so the two are bitwise-equal
        — the parity the fused suite asserts."""
        if algorithm not in ("fused", "collective"):
            raise ValueError(
                f"allgather_matmul algorithm {algorithm!r} not in "
                "('fused', 'collective')")
        if jnp.ndim(x) != 3 or jnp.ndim(w) != 3:
            raise ValueError(
                f"want x (size, m, k) and w (size, k, n), got "
                f"{jnp.shape(x)} and {jnp.shape(w)}")
        key = (jnp.shape(x), str(jnp.result_type(x)), jnp.shape(w),
               str(jnp.result_type(w)), self.axis, algorithm)
        fn = self._agmm_cache.get(key)
        if fn is None:
            if algorithm == "fused":
                fn = self._fused_shmap(
                    lambda axis, g: (
                        lambda xl, wl: fused.allgather_matmul(
                            xl[0], wl[0], axis, geometry=g)[None]),
                    x, w)
            else:
                def per_rank(xl, wl):
                    return fused.allgather_matmul_reference(
                        xl[0], wl[0], self.axis)[None]

                spec = P(self.axis, None, None)
                fn = jax.jit(shard_map(per_rank, mesh=self.mesh,
                                       in_specs=(spec, spec),
                                       out_specs=spec))
            self._agmm_cache[key] = fn
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.allgather_matmul",
                             algorithm=algorithm):
            return _ready_in_span(
                fn(self.shard(x), self.shard(w)),
                op=f"allgather_matmul.{algorithm}", seq=seq,
                axis=self.axis, algorithm=algorithm)

    def allreduce_into(self, x, bias=None, epilogue=None,
                       algorithm: str = "fused") -> jax.Array:
        """Allreduce(sum) with its consumer fused in: every row of the
        result holds ``epilogue(sum_ranks(x) + bias)``. On the
        ``"fused"`` route the bias add/epilogue are applied to each
        reduced chunk AS ITS DMA LANDS (no separate pass);
        ``"collective"`` is the host-driven oracle (psum, then the
        epilogue as ordinary XLA ops). ``epilogue`` must be
        elementwise — chunkwise application is what makes the fused
        route exact."""
        if algorithm not in ("fused", "collective"):
            raise ValueError(
                f"allreduce_into algorithm {algorithm!r} not in "
                "('fused', 'collective')")
        row_bias = None
        if bias is not None:
            row_bias = jnp.asarray(bias, jnp.result_type(x))

        def per_rank_collective(local):
            out = collectives.allreduce(local, self.axis, "sum")
            if row_bias is not None:
                out = out + row_bias
            if epilogue is not None:
                out = epilogue(out)
            # same dtype contract as the fused route (whose chunk
            # writes land in the collective's dtype): a widening
            # epilogue must not make the two routes diverge
            return out.astype(local.dtype)

        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.allreduce_into", algorithm=algorithm):
            if algorithm == "fused":
                fn = self._fused_shmap(
                    lambda axis, g: (lambda local: fused.allreduce_into(
                        local, axis, bias=row_bias, epilogue=epilogue,
                        geometry=g)), x)
                result = fn(x)
            else:
                result = self._shmap(per_rank_collective, x)(x)
            return _ready_in_span(
                result,
                op=f"allreduce_into.{algorithm}", seq=seq,
                axis=self.axis, algorithm=algorithm)

    # -- miniapp-style buffer init ---------------------------------------

    def rank_filled(self, n: int, dtype="float32") -> jax.Array:
        """The miniapp's ``Initialize``: rank r's buffer filled with r
        (allreduce-mpi-sycl.cpp:33-41), so the allreduce oracle is
        ``size*(size-1)/2`` (:192-204). Built shard-wise (no host
        materialization of the global array)."""

        fill = self._rank_filled_cache.get((n, str(dtype)))
        if fill is None:

            def init(_):
                r = ring.axis_index(self.axis)
                return jnp.full((1, n), r, dtype=dtype)

            spec = P(self.axis, None)
            fill = jax.jit(
                shard_map(init, mesh=self.mesh, in_specs=spec,
                          out_specs=spec)
            )
            self._rank_filled_cache[(n, str(dtype))] = fill
        token = self.shard(np.zeros((self.size, 1), np.int8))
        return fill(token)

    def expected_allreduce_value(self) -> float:
        """The analytic oracle: Σ ranks = size(size-1)/2."""
        return self.size * (self.size - 1) / 2
