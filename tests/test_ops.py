"""Pallas op tests: flash attention vs the dense oracle (interpret mode
on the CPU mesh; real-TPU correctness/perf are exercised by bench/driver
runs)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.ops import flash_attention
from hpc_patterns_tpu.parallel.ring_attention import full_attention


def _qkv(key, B=2, T=128, H=4, D=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_oracle(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )

    def test_uneven_blocks_rejected(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), T=96)
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k, v, block_q=64, block_k=64)

    def test_bad_rank(self):
        with pytest.raises(ValueError, match="head_dim"):
            flash_attention(jnp.zeros((2, 2)), jnp.zeros((2, 2)), jnp.zeros((2, 2)))

    def test_block_larger_than_seq_clamps(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), T=64)
        got = flash_attention(q, k, v, causal=True)  # default blocks 128 > 64
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grad_matches_oracle(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(3), B=1, T=64, H=2, D=16)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=causal,
                                   block_q=32, block_k=32).sum()

        def loss_dense(q, k, v):
            return full_attention(q, k, v, causal=causal).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=2e-5)

    def test_model_flash_matches_full(self):
        from hpc_patterns_tpu.models import TransformerConfig, forward, init_params

        base = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    max_seq=32, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), TransformerConfig(**base))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64, "int32")
        a = forward(params, tokens, TransformerConfig(**base))
        b = forward(params, tokens, TransformerConfig(**base, attention="flash"))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_flash_on_mesh_rejected(self, mesh_dp_sp_tp):
        from hpc_patterns_tpu.models import TransformerConfig, forward, init_params

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=8, n_layers=1,
                                d_ff=64, max_seq=32, attention="flash")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64, "int32")
        with pytest.raises(ValueError, match="single-device"):
            forward(params, tokens, cfg, mesh_dp_sp_tp)
