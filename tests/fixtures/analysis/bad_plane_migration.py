"""Known-bad: serving-plane KV-handoff hazards, minimized.

Two shapes the round-10 plane made possible: (1) a handoff issued
under rank-dependent control flow — the donor migrates while the
other rank does not, so the two sides' ``(kv_migration, seq)`` chains
diverge and the receiver waits on a bundle that never comes (the
merge-time verifier names it; shardlint catches it before the run);
(2) a host readback inside a migration dispatch function — the
transfer exists to hide behind the in-flight decode chunk, and a
sync there exposes exactly the latency it should be hiding.

Lines carrying ``EXPECT: <rule>`` markers are the golden findings
tests/test_analysis.py asserts, line-exact.
"""

import os

import numpy as np

from hpc_patterns_tpu.serving_plane.migration import migrate_pages


def rank_branched_handoff(bundle, x, device):
    if int(os.environ.get("HPCPAT_PROCESS_ID") or 0) == 0:  # EXPECT: collective-divergence
        out = migrate_pages(bundle, device)
    else:
        out = x
    return out


def _dispatch_migration(engine, slot, device):
    pos_now = np.asarray(engine.pos)  # EXPECT: host-sync-in-dispatch
    bundle = engine.export_migration(slot)
    bundle.pos = int(pos_now[slot])
    return migrate_pages(bundle, device)
