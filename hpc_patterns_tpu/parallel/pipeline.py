"""Pipeline parallelism: microbatch schedules over the pt2pt ring.

The reference's pairwise blocking Send/Recv between ring neighbors is
"the core of PP" (SURVEY.md §2.2): a pipeline stage boundary is exactly
one neighbor handoff per tick. This module turns that primitive
(comm.ring.ring_shift — deadlock-free ppermute, vs the reference's
even/odd ordering trick, allreduce-mpi-sycl.cpp:50-58) into two
schedules:

- :func:`pipeline_forward` — GPipe-style forward fill-drain: rank r runs
  stage r; microbatch m enters at tick m, reaches stage r at tick m+r,
  exits after M + P - 1 ticks.
- :func:`pipeline_train_1f1b` — the 1F1B training schedule: each stage
  runs its warmup forwards, then alternates one-forward-one-backward, so
  at most P - r microbatch activations are ever stashed on stage r
  (vs all M under GPipe) — the input stash here is sized min(P, M) and
  ring-indexed, the real 1F1B memory bound. Backward is recompute-based
  (``jax.vjp`` of the stage on the stashed input), the standard PP
  memory/FLOPs trade.

SPMD subtlety: inside ``shard_map`` every rank executes the same program,
so "is my buffer valid at this tick" is data (a mask), not control flow —
inactive (fill/drain bubble) ticks compute on garbage and mask the
result, the standard XLA-friendly formulation (static tick loop, no
data-dependent branching — SURVEY.md's XLA-semantics ground rule).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.comm import ring


def _varying(tree, axis: str):
    """Mark fresh (axis-invariant) arrays as varying over the shard_map
    axis, so they can carry through a lax.scan whose body mixes them
    with genuinely per-rank values (ring hops, rank-masked updates) —
    scan requires carry-in and carry-out VMA types to match."""
    if not hasattr(lax, "pcast"):
        # pre-vma jax (0.4.x): shard_map's check is check_rep and scan
        # carries no varying-axes types — nothing to mark
        return tree
    return jax.tree.map(lambda a: lax.pcast(a, (axis,), to="varying"), tree)


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    axis: str,
):
    """Run ``stage_fn(stage_params, x)`` as a P-stage pipeline over the
    mesh axis (rank-local; run inside ``shard_map``).

    ``stage_params``: this rank's stage parameters (stage r on rank r).
    ``x_microbatches``: (M, ...) microbatches — read on rank 0 (the
    pipeline entry); other ranks may pass zeros of the same shape.
    Returns (M, ...) outputs, valid on the LAST rank (rank size-1); other
    ranks return zeros — fetch the last-rank shard, or close the ring
    with one more hop if replication is wanted.
    """
    size = ring.axis_size(axis)
    me = ring.axis_index(axis)
    M = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    # shape contract checked once up front (the handoff buffer is reused
    # every tick, so stages must be shape/dtype-preserving — project
    # in/out inside stage_fn)
    y_shape = jax.eval_shape(
        stage_fn, stage_params,
        jax.ShapeDtypeStruct(mb_shape, x_microbatches.dtype),
    )
    if not hasattr(y_shape, "shape"):
        raise ValueError(
            "stage_fn must return a single activation array; got a "
            f"{type(y_shape).__name__} — aux-returning (MoE) stages are "
            "only supported by pipeline_train_1f1b, which threads the "
            "aux through the backward"
        )
    if y_shape.shape != mb_shape or y_shape.dtype != x_microbatches.dtype:
        raise ValueError(
            f"stage_fn must preserve microbatch shape/dtype: "
            f"{mb_shape}/{x_microbatches.dtype} -> "
            f"{y_shape.shape}/{y_shape.dtype}"
        )

    buf = jnp.zeros(mb_shape, x_microbatches.dtype)  # incoming activation

    def tick_body(carry, tick):
        buf, outs = carry
        # entry rank injects microbatch `tick` during the fill window
        cur = jnp.where(me == 0, x_microbatches[jnp.clip(tick, 0, M - 1)],
                        buf)
        # stage r is active for microbatch (tick - r) in [0, M)
        active = jnp.logical_and(tick - me >= 0, tick - me < M)
        y = stage_fn(stage_params, cur)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its finished microbatch
        out_idx = jnp.clip(tick - (size - 1), 0, M - 1)
        bank = jnp.logical_and(active, me == size - 1)
        outs = outs.at[out_idx].set(jnp.where(bank, y, outs[out_idx]))
        # neighbor handoff (the SendRecvRing hop); last->0 wraps but rank 0
        # overwrites with its injection, so the wrap is harmless
        buf = ring.ring_shift(y, axis, 1)
        return (buf, outs), None

    outs = jnp.zeros((M, *mb_shape), x_microbatches.dtype)
    # scan, not a Python loop: the stage traces ONCE however long the
    # pipeline runs (compile cost independent of M and P)
    (buf, outs), _ = lax.scan(
        tick_body, _varying((buf, outs), axis), jnp.arange(M + size - 1)
    )
    return outs


def schedule_1f1b(P: int, M: int):
    """The 1F1B tick table (pure Python — testable without devices).

    Unit fwd/bwd costs. Returns ``(fwd, bwd)`` dicts mapping
    ``(stage, microbatch) -> tick``:

    - forward:  warmup ``t_f(r, m) = m + r`` for the first ``P - r``
      microbatches (streamed back-to-back), then steady-state
      ``t_f(r, m) = 2m + r`` — each forward follows the backward of
      microbatch ``m - (P - r)`` (the one-forward-one-backward
      alternation; earlier stages idle between warmup and their first
      backward, which is the 1F1B bubble).
    - backward: ``t_b(r, m) = 2P - 1 - r + 2m`` — microbatch m's
      backward leaves the last stage right after its forward and walks
      back one stage per tick.

    Properties (asserted by tests): per stage, no two ops share a tick;
    an activation is produced >= 1 tick before its consumer needs it;
    the number of stashed activations on stage r never exceeds
    ``min(P - r, M)`` — the 1F1B memory bound.
    """
    fwd = {}
    bwd = {}
    for r in range(P):
        for m in range(M):
            fwd[(r, m)] = m + r if m <= P - 1 - r else 2 * m + r
            bwd[(r, m)] = 2 * P - 1 - r + 2 * m
    return fwd, bwd


def pipeline_train_1f1b(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    targets,
    loss_fn: Callable,
    axis: str,
    *,
    loss_params=None,
    return_input_grads: bool = False,
    stage_aux_weight: float | None = None,
):
    """One 1F1B pipeline training pass (rank-local; run inside
    ``shard_map``): forward every microbatch through the P stages,
    seed each backward with d(loss)/dy on the last stage, and return
    this stage's accumulated parameter gradients.

    ``stage_fn(params, x) -> y`` must preserve the microbatch shape
    (project in/out inside); ``loss_fn(y, target) -> scalar`` is applied
    per microbatch on the LAST stage. ``x_microbatches``: (M, ...) read
    on rank 0; ``targets``: (M, ...) read on rank P-1 (other ranks pass
    same-shaped arrays). Returns ``(mean_loss, grads)`` where mean_loss
    is valid on the last rank (zeros elsewhere) and ``grads`` matches
    ``stage_params`` (this stage's gradient, summed over microbatches —
    divide by M upstream for a mean-loss gradient if desired; here the
    seed is grad of ``loss_fn`` itself per microbatch, accumulated).

    ``loss_params`` (optional): a pytree the last stage's loss head
    differentiates through — ``loss_fn(loss_params, y, target)`` — e.g.
    the LM head + final norm of a pipelined transformer; their gradient
    is returned too (nonzero on the last rank; psum over the axis to
    replicate). ``return_input_grads``: also return d(loss)/d(x_m) as an
    (M, ...) f32 array (nonzero on rank 0) — the hook for differentiating
    whatever produced the pipeline inputs (e.g. the embedding).
    ``stage_aux_weight`` (optional): when set, ``stage_fn`` returns
    ``(y, aux)`` with ``aux`` a scalar per-microbatch auxiliary loss
    (e.g. the MoE load-balance loss). The aux values are accumulated
    over this rank's forwards into ``extras["aux_sum"]`` (unweighted;
    psum over the axis and divide by M upstream), and each backward
    seeds the aux output's cotangent with ``stage_aux_weight``, so the
    returned parameter/input gradients include the weighted aux term —
    the auxiliary loss rides the existing 1F1B backward, no extra pass.

    With any option the return becomes ``(mean_loss, grads, extras)``
    with ``extras = {"loss_grads": ..., "input_grads": ..., "aux_sum":
    ...}`` (the requested keys only); plain calls keep the 2-tuple.

    Scheduling follows :func:`schedule_1f1b`; the input stash and the
    activation/cotangent mailboxes are ring-indexed with ``min(P, M)``
    slots — the 1F1B in-flight bound (GPipe would need all M).
    """
    P = ring.axis_size(axis)
    me = ring.axis_index(axis)
    M = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    S = min(P, M)  # stash slots: the 1F1B in-flight bound
    f32 = jnp.float32

    in_stash = jnp.zeros((S, *mb_shape), x_microbatches.dtype)
    fwd_mail = jnp.zeros((S, *mb_shape), x_microbatches.dtype)
    bwd_mail = jnp.zeros((S, *mb_shape), f32)
    grads = jax.tree.map(lambda p: jnp.zeros(p.shape, f32), stage_params)
    loss_grads = (None if loss_params is None else jax.tree.map(
        lambda p: jnp.zeros(p.shape, f32), loss_params))
    in_grads = (jnp.zeros((M, *mb_shape), f32)
                if return_input_grads else None)
    loss_sum = jnp.zeros((), f32)
    has_aux = stage_aux_weight is not None
    aux_sum = jnp.zeros((), f32) if has_aux else None

    def eval_stage(params, x):
        """Uniform (y, aux) stage evaluation (aux = 0 when unused)."""
        if has_aux:
            y, aux = stage_fn(params, x)
            return y, aux.astype(f32)
        return stage_fn(params, x), jnp.zeros((), f32)

    def fwd_microbatch_at(t):
        """(m, valid) for this rank's forward at tick t (traced me)."""
        warm = t - me  # warmup: t_f = m + r
        warm_ok = jnp.logical_and(warm >= 0, warm <= P - 1 - me)
        steady = (t - me) // 2  # steady: t_f = 2m + r
        steady_ok = jnp.logical_and(
            (t - me) % 2 == 0, steady > P - 1 - me
        )
        m = jnp.where(warm_ok, warm, steady)
        ok = jnp.logical_and(
            jnp.logical_or(warm_ok, steady_ok),
            jnp.logical_and(m >= 0, m < M),
        )
        return m, ok

    def bwd_microbatch_at(t):
        num = t - (2 * P - 1 - me)
        m = num // 2
        ok = jnp.logical_and(
            jnp.logical_and(num >= 0, num % 2 == 0),
            m < M,
        )
        return m, ok

    def masked_bank(mail, m, ok, payload):
        slot = m % S
        cur = mail[slot]
        return mail.at[slot].set(
            jnp.where(ok, payload.astype(mail.dtype), cur)
        )

    def tick_body(carry, t, *, has_fwd, has_bwd):
        # one 1F1B tick. ``has_fwd``/``has_bwd`` are STATIC phase flags
        # (fixed per scan segment below): before tick P no rank can run
        # a backward (first is t_b(P-1, 0) = P), after tick 2M+P-3 no
        # rank forwards (last is t_f(P-1, M-1)) — the corresponding unit
        # is skipped entirely instead of emitting fully-masked compute.
        (in_stash, fwd_mail, bwd_mail, grads, loss_grads, in_grads,
         loss_sum, aux_sum) = carry
        is_last = me == P - 1

        if has_fwd:
            m_f, f_ok = fwd_microbatch_at(t)
            x_f = jnp.where(
                me == 0, x_microbatches[jnp.clip(m_f, 0, M - 1)],
                fwd_mail[m_f % S],
            )
            in_stash = masked_bank(in_stash, m_f, f_ok, x_f)
        if has_bwd:
            m_b, b_ok = bwd_microbatch_at(t)
            x_b = in_stash[m_b % S]

        if not has_bwd:
            # fwd-only tick: plain stage evaluation, no pullback, no loss
            y, aux = eval_stage(stage_params, x_f)
        else:
            # ONE stage evaluation serves both units: per stage, forward
            # and backward never share a tick (schedule invariant), so
            # select the input and run a single vjp — y is the forward's
            # output on f_ok ticks, the recomputed activation on b_ok
            x_sel = jnp.where(b_ok, x_b, x_f) if has_fwd else x_b
            (y, aux), pullback = jax.vjp(eval_stage, stage_params, x_sel)

            tgt = targets[jnp.clip(m_b, 0, M - 1)]
            if loss_params is None:
                loss_m, dloss = jax.value_and_grad(loss_fn)(
                    y.astype(f32), tgt
                )
            else:
                loss_m, (dlp, dloss) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1)
                )(loss_params, y.astype(f32), tgt)
                lp_mask = jnp.logical_and(b_ok, is_last).astype(f32)
                loss_grads = jax.tree.map(
                    lambda g, d: g + lp_mask * d.astype(f32), loss_grads, dlp
                )
            dy = jnp.where(is_last, dloss, bwd_mail[m_b % S]).astype(y.dtype)
            # aux cotangent: the weighted auxiliary loss enters this
            # microbatch's backward here. Without aux the cotangent must
            # stay a plain (axis-invariant) zero to match eval_stage's
            # constant-zero aux output VMA type
            daux = (
                jnp.where(b_ok, jnp.float32(stage_aux_weight), 0.0)
                if has_aux else jnp.zeros((), f32)
            )
            dparams, dx = pullback((dy, daux))
            b_mask = b_ok.astype(f32)
            grads = jax.tree.map(
                lambda g, d: g + b_mask * d.astype(f32), grads, dparams
            )
            if return_input_grads:
                take = jnp.logical_and(b_ok, me == 0)
                idx = jnp.clip(m_b, 0, M - 1)
                in_grads = in_grads.at[idx].set(
                    jnp.where(take, dx.astype(f32), in_grads[idx])
                )
            loss_sum = loss_sum + jnp.where(
                jnp.logical_and(b_ok, is_last), loss_m, 0.0
            )
        if has_aux and has_fwd:
            # aux belongs to the FORWARD microbatch (f_ok and b_ok never
            # coincide on one stage, so a backward tick's recomputed aux
            # is not double-counted)
            aux_sum = aux_sum + jnp.where(f_ok, aux, 0.0)

        # ---- neighbor handoffs (masked payloads; only phases that can
        # carry data hop): the activation hops forward, the cotangent
        # hops backward, each tagged with its microbatch index
        if has_fwd:
            y_send = jnp.where(f_ok, y, jnp.zeros_like(y))
            y_recv = ring.ring_shift(y_send, axis, 1)
            mf_recv = ring.ring_shift(
                jnp.stack([m_f, f_ok.astype(m_f.dtype)]), axis, 1
            )
            fwd_mail = masked_bank(
                fwd_mail, mf_recv[0],
                jnp.logical_and(mf_recv[1] == 1, me != 0), y_recv,
            )
        if has_bwd:
            dx_send = jnp.where(b_ok, dx.astype(f32),
                                jnp.zeros(mb_shape, f32))
            dx_recv = ring.ring_shift(dx_send, axis, -1)
            mb_recv = ring.ring_shift(
                jnp.stack([m_b, b_ok.astype(m_b.dtype)]), axis, -1
            )
            bwd_mail = masked_bank(
                bwd_mail, mb_recv[0],
                jnp.logical_and(mb_recv[1] == 1, me != P - 1), dx_recv,
            )
        return (in_stash, fwd_mail, bwd_mail, grads, loss_grads, in_grads,
                loss_sum, aux_sum), None

    # three lax.scan segments with static phase flags — the stage traces
    # a constant number of times (one plain eval + two vjps) however
    # large M and P are, vs one trace per tick under a Python loop:
    #   [0, P)            fwd only (fill; no backward can exist yet)
    #   [P, 2M+P-2)       mixed 1F1B steady state (empty when M == 1)
    #   [2M+P-2, n_ticks) bwd only (drain; no forward remains)
    n_ticks = 2 * M + 2 * P - 3 + 1
    carry = _varying(
        (in_stash, fwd_mail, bwd_mail, grads, loss_grads, in_grads,
         loss_sum, aux_sum),
        axis,
    )
    segments = (
        (0, P, True, False),
        (P, max(2 * M + P - 2, P), True, True),
        (max(2 * M + P - 2, P), n_ticks, False, True),
    )
    for t0, t1, hf, hb in segments:
        if t1 > t0:
            carry, _ = lax.scan(
                functools.partial(tick_body, has_fwd=hf, has_bwd=hb),
                carry, jnp.arange(t0, t1),
            )
    (in_stash, fwd_mail, bwd_mail, grads, loss_grads, in_grads,
     loss_sum, aux_sum) = carry

    mean_loss = jnp.where(me == P - 1, loss_sum / M, 0.0)
    extras = {}
    if loss_params is not None:
        extras["loss_grads"] = loss_grads
    if return_input_grads:
        extras["input_grads"] = in_grads
    if has_aux:
        extras["aux_sum"] = aux_sum
    if extras:
        return mean_loss, grads, extras
    return mean_loss, grads
