"""Process launcher: the ``mpirun -np N`` analog (SURVEY.md §4, C11).

The reference registers every miniapp as ``mpirun -np 4 ./app`` under
CTest (aurora.mpich.miniapps/src/CMakeLists.txt:39-50). Here the same
role is played by N local processes joined through JAX's distributed
runtime: each child gets a shared coordinator address plus its process
id via the ``HPCPAT_*`` env protocol (topology.init_distributed_from_env
— the MPI_Init analog), and ``--cpu-devices-per-proc`` K virtual CPU
devices, so an ``-np 2`` launch of the allreduce miniapp is a real
4-rank SPMD run across two OS processes with zero TPU hardware — the
multi-host communication path (cross-process collectives, cross-process
MAX timing) exercised for real, which the reference cannot do without a
GPU cluster (SURVEY.md §4's gap).

On an actual TPU pod this launcher is not needed: one process per host
is started by the pod runtime and ``jax.distributed.initialize`` reads
everything from the environment (topology.init_distributed with no
args).

Usage:
    python -m hpc_patterns_tpu.apps.launch -np 2 -- \
        python -m hpc_patterns_tpu.apps.allreduce_app -p 10

Exit 0 iff every rank exits 0 (the ctest contract); per-rank output is
echoed with a ``[r]`` prefix and a grep-able summary line closes the
run (run.sh:17-18 style).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading

from hpc_patterns_tpu import topology


def build_parser():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-np", "--num-processes", type=int, default=2,
                   help="processes to launch (mpirun -np)")
    p.add_argument("--cpu-devices-per-proc", type=int, default=2,
                   help="virtual CPU devices per process "
                        "(xla_force_host_platform_device_count)")
    p.add_argument("--slices", type=int, default=0,
                   help="treat the processes as this many equal TPU "
                        "slices (sets HPCPAT_SLICE_GROUPING so "
                        "group_by_slice/--dcn-dp see an N-slice system "
                        "whose DCN axis crosses real process "
                        "boundaries); 0 = no slice override")
    p.add_argument("--port", type=int, default=0,
                   help="coordinator port (0 = pick a free one)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-run timeout in seconds")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to launch, after --")
    return p


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(base: dict, coord: str, nprocs: int, pid: int,
               cpu_devices: int, slices: int = 0) -> dict:
    env = topology.cpu_worker_env(base, cpu_devices)
    env[topology.ENV_COORDINATOR] = coord
    env[topology.ENV_NUM_PROCESSES] = str(nprocs)
    env[topology.ENV_PROCESS_ID] = str(pid)
    if slices:
        # contiguous equal groups of processes per slice; the SAME value
        # goes to every child so each computes the identical grouping
        mapping = ",".join(str(q * slices // nprocs) for q in range(nprocs))
        env[topology.ENV_SLICE_GROUPING] = "process:" + mapping
    # children must resolve `-m hpc_patterns_tpu...` regardless of cwd
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = env.get("PYTHONPATH", "")
    if pkg_root not in paths.split(os.pathsep):
        env["PYTHONPATH"] = (
            f"{pkg_root}{os.pathsep}{paths}" if paths else pkg_root
        )
    return env


_pump = topology.pump_lines


def run(args) -> int:
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("ERROR: no command given (put it after --)")
        return 2
    nprocs = args.num_processes
    if nprocs < 1:
        print("ERROR: -np must be >= 1")
        return 2
    if args.slices and nprocs % args.slices:
        print(f"ERROR: -np {nprocs} must divide by --slices {args.slices}")
        return 2
    coord = f"127.0.0.1:{args.port or _free_port()}"
    procs, pumps = [], []
    for pid in range(nprocs):
        proc = subprocess.Popen(
            cmd,
            env=_child_env(os.environ, coord, nprocs, pid,
                           args.cpu_devices_per_proc, args.slices),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        t = threading.Thread(
            target=_pump, args=(f"[{pid}] ", proc.stdout, sys.stdout),
            daemon=True,
        )
        t.start()
        procs.append(proc)
        pumps.append(t)

    codes = []
    try:
        for proc in procs:
            codes.append(proc.wait(timeout=args.timeout))
    except subprocess.TimeoutExpired:
        for proc in procs:
            proc.kill()
        print(f"FAILURE: timeout after {args.timeout}s")
        return 1
    finally:
        for t in pumps:
            t.join(timeout=5)

    ok = all(c == 0 for c in codes)
    print(f"launch -np {nprocs}: exit codes {codes}")
    print("SUCCESS" if ok else "FAILURE")
    return 0 if ok else 1


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
