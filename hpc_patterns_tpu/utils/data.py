"""Async host→device data pipeline: the IO side of the framework.

The reference has no data loader (pure benchmarks), but its concurrency
suite exists to prove copies overlap compute (sycl_con.cpp) — this
module applies that proven overlap to the training input pipeline: a
background thread stages the next batch(es) to device while the current
step runs, so the M2D transfer the concurrency app measures is hidden
behind the train step. JAX async dispatch does the rest (device_put
returns immediately; the train step's first use blocks on arrival).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator

import jax

_STOP = object()


class PrefetchLoader:
    """Wrap a host-batch iterable; yield device-resident batches with
    ``depth`` transfers in flight (double buffering at depth=2 — the
    concurrency suite's M2D/compute overlap, applied to input data).

    ``place`` maps a host batch to device (default: ``jax.device_put``
    with no target — jit inputs; pass e.g. a NamedSharding placer for
    mesh layouts).
    """

    def __init__(
        self,
        batches: Iterable,
        *,
        depth: int = 2,
        place: Callable | None = None,
    ):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._batches = batches
        self._depth = depth
        self._place = place or jax.device_put

    def __iter__(self) -> Iterator:
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        error: list[BaseException] = []
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer is gone, so an
            # early consumer exit can never wedge the worker on a full
            # queue (it would otherwise pin staged device buffers)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for b in self._batches:
                    if stop.is_set():
                        return
                    # device_put here, on the worker thread: the transfer
                    # is in flight while the consumer computes
                    if not put(self._place(b)):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised on main
                error.append(e)
            finally:
                put(_STOP)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    break
                yield item
            if error:
                raise error[0]
        finally:
            stop.set()
            while True:  # unblock a worker mid-put and drop staged refs
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)


def synthetic_tokens(key, *, batch: int, seq: int, vocab: int, steps: int):
    """Host-side synthetic token batches (benchmark fuel for the
    trainer), one numpy array per step."""
    import numpy as np

    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 2**31 - 1)))
    for _ in range(steps):
        yield rng.integers(0, vocab, size=(batch, seq), dtype="int32")
