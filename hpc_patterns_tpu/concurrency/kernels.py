"""Pallas compute kernels for the concurrency suite.

The reference's compute command is ``busy_wait`` (sycl_con.cpp:26-33): a
parallel_for where every work-item runs ``64 * tripcount`` dependent FMAs
— pure ALU work with a tunable duration and a checkable result. The TPU
rebuild keeps both properties:

- duration ∝ ``tripcount``, passed as a *runtime* scalar (SMEM) so the
  autotuner (C12) can re-balance without recompiling;
- a dependent FMA chain on the VPU (8×128 lanes), so XLA cannot fold the
  loop away and the kernel occupies the compute unit while DMAs fly.

On non-TPU backends the same kernel runs through the Pallas interpreter,
so tests exercise the identical code path on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# FMAs per work-item per trip, matching the reference's unrolled factor 64
# (sycl_con.cpp:29-31: eight outer * eight inner in the original).
FMA_UNROLL = 8


def _busy_wait_kernel(trip_ref, x_ref, o_ref):
    trips = trip_ref[0]

    def body(_, acc):
        # Dependent multiply-adds: each feeds the next, so the chain
        # cannot be vectorized away across iterations; constants keep the
        # value bounded (fixed point of a*c1+c2 is ~ -c2/(c1-1) ~ 5e6).
        for _ in range(FMA_UNROLL):
            acc = acc * jnp.float32(0.9999999) + jnp.float32(0.5)
        return acc

    o_ref[:] = lax.fori_loop(0, trips, body, x_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _busy_wait_call(x, tripcount, *, interpret=False):
    # tripcount arrives as a raw host scalar and is wrapped to its
    # (1,) SMEM shape HERE, under the trace — wrapping at the call
    # site (`jnp.int32(tripcount)`, the pre-jaxlint form) was an extra
    # eager dispatch on the submit path per command
    return pl.pallas_call(
        _busy_wait_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(jnp.asarray(tripcount, jnp.int32).reshape(1), x)


def busy_wait(x, tripcount, *, interpret: bool | None = None):
    """Run the busy-wait chain over ``x`` for ``tripcount`` trips.

    ``x`` must be float32 with a TPU-tileable trailing shape (pad to
    (8k, 128) — see :func:`compute_buffer`). ``tripcount`` is a runtime
    scalar: changing it does NOT recompile (the reference re-runs its
    autotuner the same way, sycl_con.cpp:257-268).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _busy_wait_call(x, tripcount, interpret=interpret)


def compute_buffer(n_elements: int, device=None):
    """A VMEM-friendly float32 buffer of >= ``n_elements``, shaped
    (rows, 128) with rows a multiple of 8 (the float32 min tile).

    The analog of the compute command's ``malloc_device`` buffer
    (sycl_con.cpp:64-73); the reference sizes it by the device's first
    sub-group size (:168-172) — the TPU natural unit is one (8, 128)
    vector register tile.
    """
    rows = max(8, -(-n_elements // 128))
    rows += (-rows) % 8
    x = jnp.zeros((rows, 128), jnp.float32)
    if device is not None:
        x = jax.device_put(x, device)
    return jax.block_until_ready(x)


def busy_wait_reference(x, tripcount):
    """Pure-jnp oracle for tests: same recurrence, no Pallas."""
    acc = jnp.asarray(x, jnp.float32)
    for _ in range(int(tripcount) * FMA_UNROLL):
        acc = acc * jnp.float32(0.9999999) + jnp.float32(0.5)
    return acc
