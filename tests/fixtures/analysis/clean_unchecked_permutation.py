"""Known-clean: every pair list is bound to a name and sanitized by
``check_permutation`` before reaching ``ppermute`` (the
``comm.ring.ring_shift`` discipline), positional and keyword forms."""

from jax import lax

from hpc_patterns_tpu.comm.ring import check_permutation


def rotate_checked(x, size):
    pairs = [(i, (i + 2) % size) for i in range(size)]
    check_permutation(pairs, size)
    return lax.ppermute(x, "x", pairs)


def keyword_form(x, size):
    pairs = [(i, i ^ 1) for i in range(size)]
    check_permutation(pairs, size)
    return lax.ppermute(x, "x", perm=pairs)
