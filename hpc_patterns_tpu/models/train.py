"""Training step: jit-compiled, sharded, donated.

The full step — forward (bf16), loss, backward, optax update — under one
``jit`` over the mesh: XLA lays every collective (attention-ring
ppermutes, TP psums, DP gradient all-reduce) onto ICI from the sharding
annotations alone, the §2.3 "GPU-aware, no host staging" property at
training scale. Master params/opt state stay f32 and are donated, so the
update is in-place in HBM.

Sharding flows from the *data*: params are placed with
models/sharding.py rules, optax moments inherit those shardings at init
(zeros_like preserves sharding), tokens are placed with batch_sharding —
jit then propagates from its inputs, with the activation constraints in
forward() pinning the interior. No separate opt-state sharding spec to
maintain.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax

from hpc_patterns_tpu.models import sharding as shardlib
from hpc_patterns_tpu.models.transformer import TransformerConfig, init_params, loss_fn


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.01,
                   grad_clip: float = 1.0):
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, weight_decay=weight_decay),
    )


def make_train_step(cfg: TransformerConfig, mesh=None, optimizer=None):
    """Returns jitted ``step(params, opt_state, tokens) -> (loss, params,
    opt_state)`` with param/opt-state donation (in-place HBM update).

    Pass ``params``/``opt_state`` created by :func:`init_train_state`
    (sharded when ``mesh`` is given); the same code path is the
    single-device oracle when ``mesh`` is None (the §4 test strategy:
    distributed result must match the local one).
    """
    optimizer = optimizer or make_optimizer()

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(partial(loss_fn, cfg=cfg, mesh=mesh))(
            params, tokens
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    return jax.jit(step, donate_argnums=(0, 1))


def init_train_state(key, cfg: TransformerConfig, mesh=None, optimizer=None):
    """(params, opt_state): f32 master params placed per the sharding
    rules; optax state inherits the placement (zeros_like preserves
    sharding).

    With a mesh, init runs *under jit with sharded out_shardings*, so
    each device materializes only its own shards — no single device ever
    holds the full f32 copy (the point of TP at flagship scale)."""
    optimizer = optimizer or make_optimizer()
    if mesh is None:
        params = init_params(key, cfg)
    else:
        params = jax.jit(
            lambda k: init_params(k, cfg),
            out_shardings=shardlib.param_shardings(mesh, cfg),
        )(key)
    opt_state = optimizer.init(params)
    return params, opt_state


def make_batch(key, cfg: TransformerConfig, batch: int, seq: int, mesh=None):
    """Synthetic token batch (benchmark fuel), sharded when mesh given."""
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab, jnp.int32)
    if mesh is not None:
        tokens = jax.device_put(tokens, shardlib.batch_sharding(mesh, cfg))
    return tokens
