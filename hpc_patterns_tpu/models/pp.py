"""Pipeline-parallel training for the flagship transformer.

The missing member of the parallelism matrix (dp/sp/tp/ep live in
models/transformer.py + models/sharding.py): layers split into P
contiguous stages over the ``pp`` mesh axis, driven by the 1F1B schedule
(parallel/pipeline.py — itself built on the reference's pt2pt ring,
SURVEY.md §2.2 "pairwise pt2pt: the core of PP").

Decomposition:

- **embedding** (embed + pos_embed): computed outside the pipeline on
  every rank (replicated math); its gradient comes back through the
  pipeline's input cotangents (``return_input_grads``).
- **stages**: the stacked layer params' leading ``n_layers`` axis is
  sharded over ``pp`` — each rank scans its ``L/P`` layers as one
  shape-preserving ``stage_fn``.
- **head** (ln_f_scale + lm_head): the last stage's loss head,
  differentiated via the pipeline's ``loss_params`` hook.

Gradients for the replicated pieces are psum'd over ``pp`` (only one
rank produces nonzero values — rank 0 for the embedding, rank P-1 for
the head — so the psum is a broadcast), exactly the §2.3 backend
property: collectives on device-resident shards, no host staging.

Composes with data parallelism: on a ("dp", "pp") mesh the batch is
dp-sharded outside, the pipeline runs per dp-slice, and gradients are
pmean'd over dp. The dp axis may cross slices (a DCN axis from
topology.make_hybrid_mesh): the once-per-step gradient pmean is the
latency-tolerant collective, while the per-tick stage ppermutes stay
slice-internal.

Composes with FSDP (ZeRO-3) over an ``fsdp`` mesh axis: stage params
are stored sharded on a feature dim (the same per-weight dims as
models/sharding.param_specs), all-gathered JUST BEFORE the stage scan
inside the pipeline shard_map, and their gradients leave as a
reduce-scatter (psum_scatter) back to the shard — params, grads, AND
optimizer state hold 1/fsdp of each stage weight per rank. The batch
shards over (dp, fsdp) together, like the non-pp fsdp path. The
embedding/head stay replicated (they are not stage params; shard them
over fsdp via the vocab dim if they ever dominate).

Composes with MoE: stages return their load-balance aux loss alongside
the activation and the 1F1B schedule threads it through
(``stage_aux_weight``) — the aux gradient rides the normal backward,
and the reported loss adds the psum'd aux term. Experts are
stage-local (dense routing per pp rank, no ep axis inside the
pipeline).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import optax

from hpc_patterns_tpu.models.transformer import (
    TransformerConfig,
    _layer,
    _rmsnorm,
    chunked_masked_causal_nll,
    init_params,
    masked_causal_nll,
)
from hpc_patterns_tpu.models.train import make_optimizer
from hpc_patterns_tpu.parallel.pipeline import pipeline_train_1f1b


def _embed(outer, tokens, cfg):
    dt = jnp.dtype(cfg.dtype)
    T = tokens.shape[-1]
    x = outer["embed"].astype(dt)[tokens]
    if cfg.pos_embed == "learned":
        x = x + outer["pos_embed"].astype(dt)[:T]
    return x


def _stage_fn(layers_shard, h, cfg):
    """One pipeline stage: scan this rank's L/P layers (shape-preserving,
    single-device math — mesh=None inside the pp rank). MoE configs
    return ``(h, aux)`` — the stage-local load-balance loss sum, which
    the 1F1B schedule threads through via ``stage_aux_weight`` (experts
    are stage-local here: dense routing per rank, no ep axis inside the
    pipeline)."""
    def body(carry, lp):
        x, aux = carry
        x, a = _layer(x, lp, cfg, mesh=None, act_spec=None)
        return (x, aux + a), None

    (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                           layers_shard)
    if cfg.n_experts:
        return h, aux
    return h


def _loss_head(lp, y, target_tokens, *, loss_chunk: int = 0):
    """Final-norm + LM head + the shared masked causal NLL
    (transformer.masked_causal_nll — identical loss semantics to
    transformer.loss_fn by construction). With ``loss_chunk`` the NLL is
    the online-logsumexp chunked form: the per-microbatch (b, T, vocab)
    logits never materialize, which is where the long-context memory
    wall bites hardest inside a pipeline stage (the 1F1B tick holds the
    stage's activations AND the loss head's intermediates live)."""
    x = _rmsnorm(y, lp["ln_f_scale"])
    if loss_chunk:
        return chunked_masked_causal_nll(
            x, lp["lm_head"].astype(y.dtype), target_tokens,
            chunk=loss_chunk,
        )
    logits = jnp.dot(x, lp["lm_head"].astype(y.dtype)).astype(jnp.float32)
    return masked_causal_nll(logits, target_tokens)


def _pp_layer_specs(cfg: TransformerConfig, axis_pp: str,
                    axis_fsdp: str | None):
    """Per-leaf PartitionSpecs for the stacked layer params inside the
    pipeline: leading ``n_layers`` axis over pp, and (with
    ``axis_fsdp``) the same per-weight feature dim models/
    sharding.param_specs shards under fsdp — one rule table, two
    parallelism schemes. tp/ep axes are dropped (no such axes inside
    pipeline stages)."""
    import dataclasses

    from hpc_patterns_tpu.models import sharding as shardlib

    base = shardlib.param_specs(
        dataclasses.replace(cfg, fsdp=bool(axis_fsdp),
                            axis_fsdp=axis_fsdp or "fsdp")
    )["layers"]

    def fix(spec):
        rest = [ax if ax == axis_fsdp else None for ax in spec[1:]]
        return P(axis_pp, *rest)

    return jax.tree.map(fix, base, is_leaf=lambda x: isinstance(x, P))


def _fsdp_dim(spec, axis_fsdp):
    """Index of the fsdp-sharded dim in a layer-leaf spec (None when
    the leaf is replicated over fsdp — norm scales, router)."""
    for i, ax in enumerate(spec):
        if ax == axis_fsdp:
            return i
    return None


def pp_loss_and_grads(params, tokens, cfg: TransformerConfig, mesh,
                      *, microbatches: int, axis_pp: str = "pp",
                      axis_dp: str | None = None,
                      axis_fsdp: str | None = None):
    """Mean causal-LM loss and full-parameter gradients via a 1F1B
    pipeline over ``axis_pp`` (optionally data-parallel over ``axis_dp``
    and/or ZeRO-3-sharded over ``axis_fsdp`` — see module docstring).

    ``params``: the standard init_params pytree (layers stacked on
    n_layers, which must divide by the pp axis size); with
    ``axis_fsdp``, layer leaves sharded per
    :func:`init_pp_train_state`'s placement. ``tokens``: (batch, seq)
    int32, batch divisible by microbatches (× dp × fsdp size).
    Loss, embedding, and head gradients are replicated on return;
    layer gradients return fsdp-sharded when ``axis_fsdp`` is set
    (matching the param storage, what the optimizer update consumes).
    """
    M = microbatches
    pp = mesh.shape[axis_pp]
    L = cfg.n_layers
    if L % pp:
        raise ValueError(f"n_layers {L} must divide by pp={pp}")
    B = tokens.shape[0]
    dp = mesh.shape[axis_dp] if axis_dp else 1
    fs = mesh.shape[axis_fsdp] if axis_fsdp else 1
    if B % (M * dp * fs):
        raise ValueError(
            f"batch {B} must divide by microbatches*dp*fsdp={M * dp * fs}"
        )
    layer_specs = _pp_layer_specs(cfg, axis_pp, axis_fsdp)
    if axis_fsdp:
        for name, spec in layer_specs.items():
            d = _fsdp_dim(spec, axis_fsdp)
            if d is None:
                continue
            size = params["layers"][name].shape[d]
            if size % fs:
                raise ValueError(
                    f"layers[{name}] dim {d} ({size}) must divide by "
                    f"fsdp={fs}"
                )

    outer = {"embed": params["embed"]}
    if cfg.pos_embed == "learned":
        outer["pos_embed"] = params["pos_embed"]
    head = {"ln_f_scale": params["ln_f_scale"], "lm_head": params["lm_head"]}

    def local(outer, layers_shard, head, tokens_local):
        toks = tokens_local.reshape(M, -1, tokens_local.shape[-1])
        x_mb = _embed(outer, toks, cfg)

        if axis_fsdp:
            # ZeRO-3 gather: materialize this stage's full weights just
            # before use (the stored shard is 1/fs of each feature dim)
            layers_full = {
                k: (v if _fsdp_dim(layer_specs[k], axis_fsdp) is None
                    else lax.all_gather(
                        v, axis_fsdp,
                        axis=_fsdp_dim(layer_specs[k], axis_fsdp),
                        tiled=True,
                    ))
                for k, v in layers_shard.items()
            }
        else:
            layers_full = layers_shard

        loss, layer_grads, extras = pipeline_train_1f1b(
            partial(_stage_fn, cfg=cfg),
            layers_full,
            x_mb,
            toks,
            partial(_loss_head, loss_chunk=cfg.loss_chunk),
            axis_pp,
            loss_params=head,
            return_input_grads=True,
            stage_aux_weight=cfg.moe_aux_weight if cfg.n_experts else None,
        )

        # embedding backward: cotangents of the pipeline inputs (nonzero
        # on pp rank 0) pulled through the replicated embedding math
        _, embed_vjp = jax.vjp(lambda o: _embed(o, toks, cfg), outer)
        (outer_grads,) = embed_vjp(extras["input_grads"].astype(x_mb.dtype))

        # replicate the rank-local pieces: loss and head grads live on
        # the last pp rank, embedding grads on rank 0, so psum = broadcast
        loss = lax.psum(loss, axis_pp)
        if cfg.n_experts:
            # total load-balance loss: stage-local sums live per rank;
            # psum over pp = the sum over all layers, / M for the
            # per-microbatch mean (matching transformer.loss_fn, whose
            # aux is summed over layers on the whole batch)
            aux_mean = lax.psum(extras["aux_sum"], axis_pp) / M
            loss = loss + cfg.moe_aux_weight * aux_mean
        head_grads = jax.tree.map(lambda g: lax.psum(g, axis_pp),
                                  extras["loss_grads"])
        outer_grads = jax.tree.map(
            lambda g: lax.psum(
                jnp.where(lax.axis_index(axis_pp) == 0, g.astype(jnp.float32),
                          jnp.zeros_like(g, jnp.float32)),
                axis_pp,
            ),
            outer_grads,
        )
        if axis_fsdp:
            # ZeRO-3 reduce-scatter: each rank keeps the grad tile of
            # the shard it stores; /fs makes it the MEAN over the fsdp
            # batch shards (the dp convention)
            layer_grads = {
                k: (lax.pmean(g, axis_fsdp)
                    if _fsdp_dim(layer_specs[k], axis_fsdp) is None
                    else lax.psum_scatter(
                        g, axis_fsdp,
                        scatter_dimension=_fsdp_dim(layer_specs[k],
                                                    axis_fsdp),
                        tiled=True,
                    ) / fs)
                for k, g in layer_grads.items()
            }
        small = (outer_grads, head_grads)
        for ax in (axis_dp, axis_fsdp):
            if ax:
                loss = lax.pmean(loss, ax)
                small = jax.tree.map(lambda g: lax.pmean(g, ax), small)
        if axis_dp:
            layer_grads = jax.tree.map(
                lambda g: lax.pmean(g, axis_dp), layer_grads
            )
        outer_grads, head_grads = small
        grads_all = (outer_grads, layer_grads, head_grads)
        # grads are summed over microbatches; the loss head is per-
        # microbatch mean, so divide by M for the mean-loss gradient
        return loss[None], *jax.tree.map(lambda g: g / M, grads_all)

    batch_axes = tuple(a for a in (axis_dp, axis_fsdp) if a)
    tok_spec = P(batch_axes) if batch_axes else P()
    loss_spec = (P((*batch_axes, axis_pp)) if batch_axes else P(axis_pp))
    loss_r, outer_g, layer_g, head_g = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), layer_specs, P(), tok_spec),
        out_specs=(loss_spec, P(), layer_specs, P()),
        check_vma=False,  # validity masks + psum-broadcasts aren't VMA-provable
    )(outer, params["layers"], head, tokens)

    loss = loss_r[0]
    grads = {
        "embed": outer_g["embed"],
        "layers": layer_g,
        "ln_f_scale": head_g["ln_f_scale"],
        "lm_head": head_g["lm_head"],
    }
    if "pos_embed" in outer_g:
        grads["pos_embed"] = outer_g["pos_embed"]
    return loss, grads


def make_pp_train_step(cfg: TransformerConfig, mesh, *, microbatches: int,
                       axis_pp: str = "pp", axis_dp: str | None = None,
                       axis_fsdp: str | None = None, optimizer=None,
                       offload_opt_example=None):
    """Jitted ``step(params, opt_state, tokens) -> (loss, params,
    opt_state)`` training the full model through the 1F1B pipeline.

    ``axis_fsdp``: ZeRO-3 stage params (see :func:`pp_loss_and_grads`);
    the layer gradients arrive sharded like the params, so the
    optimizer update runs shard-local. ``offload_opt_example``: a
    host-resident optimizer state (models/train.offload_opt_state) —
    the update pulls it to HBM, applies, pushes back, all inside the
    one jit, exactly the sharded-train path's offload contract (the
    pipeline state lives inside the shard_map, but the OPTIMIZER state
    never does — it updates outside, where memory-kind streaming
    composes unchanged)."""
    optimizer = optimizer or make_optimizer()
    if offload_opt_example is not None:
        from hpc_patterns_tpu.models.train import offload_shardings

        host_sh, hbm_sh = offload_shardings(offload_opt_example)
    else:
        host_sh = hbm_sh = None

    def step(params, opt_state, tokens):
        if hbm_sh is not None:
            opt_state = jax.device_put(opt_state, hbm_sh)
        loss, grads = pp_loss_and_grads(
            params, tokens, cfg, mesh, microbatches=microbatches,
            axis_pp=axis_pp, axis_dp=axis_dp, axis_fsdp=axis_fsdp,
        )
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if host_sh is not None:
            opt_state = jax.device_put(opt_state, host_sh)
        return loss, params, opt_state

    if host_sh is not None:
        return jax.jit(
            step, donate_argnums=(0, 1),
            in_shardings=(None, host_sh, None),
            out_shardings=(None, None, host_sh),
        )
    return jax.jit(step, donate_argnums=(0, 1))


def init_pp_train_state(key, cfg: TransformerConfig, optimizer=None,
                        mesh=None, *, axis_pp: str = "pp",
                        axis_fsdp: str | None = None):
    """f32 params + opt state. Replicated by default (the layer stack's
    leading axis is what the pp shard_map slices); with ``mesh`` and
    ``axis_fsdp``, layer leaves are PLACED sharded over (pp, fsdp) —
    each rank materializes only its own stage-weight shard, and the
    optax state inherits the placement (zeros_like preserves
    sharding)."""
    optimizer = optimizer or make_optimizer()
    if mesh is not None and axis_fsdp:
        from jax.sharding import NamedSharding

        specs = _pp_layer_specs(cfg, axis_pp, axis_fsdp)
        shardings = {
            "layers": jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        }
        replicated = NamedSharding(mesh, P())
        full = jax.tree.map(
            lambda _: replicated,
            jax.eval_shape(lambda k: init_params(k, cfg), key),
        )
        full["layers"] = shardings["layers"]
        params = jax.jit(
            lambda k: init_params(k, cfg), out_shardings=full
        )(key)
    else:
        params = init_params(key, cfg)
    return params, optimizer.init(params)
