"""Speculative decoding gamma sweep vs plain decode, greedy and
sampling verify, on the real chip.

Protocol: per-token time by generation differencing — each
configuration generates N and N/2 tokens in ONE jitted call each
(prefill + the whole decode/verify loop live inside), both
completion-forced; the difference divided by N/2 cancels prefill,
compile, and dispatch/readback latency. Tunnel-noise caveat from
round 3 applies (single-token steps are floor-bound ~1 ms on this
chip); min-of-reps and adjacent measurement are the mitigations.

Usage: python benchmarks/bench_speculative.py [--n=256] [--temp=0.8]
                                              [--pair=DIR]

``--pair``: load an ALIGNED draft/target pair built by
benchmarks/make_draft_pair.py instead of independent random weights —
the honest envelope (random weights inflate greedy acceptance via
repetition loops and deflate sampling acceptance via independence).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from hpc_patterns_tpu.harness.timing import measure_forced
from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.decode import generate
from hpc_patterns_tpu.models.speculative import speculative_generate
from hpc_patterns_tpu.models.transformer import init_params


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def main():
    on_tpu = jax.default_backend() == "tpu"
    n = arg("n", 256 if on_tpu else 16)
    temp = arg("temp", 0.8, float)
    top_k = arg("topk", 40)
    base = dict(
        vocab=32768 if on_tpu else 256,
        d_model=1024 if on_tpu else 64,
        n_heads=8 if on_tpu else 4,
        n_layers=8 if on_tpu else 2,
        d_ff=4096 if on_tpu else 128,
        dtype="bfloat16" if on_tpu else "float32",
        n_kv_heads=2 if on_tpu else 0,
        pos_embed="rope",
    )
    gammas = (2, 4, 8)
    max_len = 128 + n + max(gammas) + 1
    pair = arg("pair", "", str)
    if pair:
        from hpc_patterns_tpu.utils.checkpoint import restore_params

        with open(os.path.join(pair, "META.json")) as f:
            meta = json.load(f)
        cfg = TransformerConfig(**{**meta["target_cfg"],
                                   "max_seq": max_len})
        dcfg = TransformerConfig(**{**meta["draft_cfg"],
                                    "max_seq": max_len})
        params, _ = restore_params(os.path.join(pair, "target"))
        dparams, _ = restore_params(os.path.join(pair, "draft"))
        acc = meta.get("acceptance", {})
        print(f"aligned pair from {pair}: greedy-agree "
              f"{acc.get('aligned_greedy', float('nan')):.3f} "
              f"E[min(p,q)] {acc.get('aligned_minpq', float('nan')):.3f} "
              f"(random baseline {acc.get('random_greedy', float('nan')):.3f}"
              f"/{acc.get('random_minpq', float('nan')):.3f})",
              flush=True)
        # prompt drawn from the SAME markov process the pair was
        # trained on (seed=0 transition table) via a DISJOINT sample
        # path — acceptance on-distribution without train-set reuse
        from make_draft_pair import markov_corpus

        corpus = markov_corpus(cfg.vocab, 8192, draw_seed=777)
        prompt = jax.numpy.asarray(corpus[:128], "int32")[None, :]
    else:
        cfg = TransformerConfig(**base, max_seq=max_len)
        dcfg = TransformerConfig(**{
            **base,
            "d_model": 256 if on_tpu else 32,
            "n_layers": 2 if on_tpu else 1,
            "d_ff": 1024 if on_tpu else 64,
            "n_heads": 4 if on_tpu else 2,
            "n_kv_heads": 2 if on_tpu else 0,
        }, max_seq=max_len)
        params = init_params(jax.random.PRNGKey(0), cfg)
        dparams = init_params(jax.random.PRNGKey(1), dcfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 128), 0,
                                    cfg.vocab, "int32")
    key = jax.random.PRNGKey(3)

    def per_token(fn):
        t_full = measure_forced(lambda: fn(n), repetitions=3).min_s
        t_half = measure_forced(lambda: fn(n // 2), repetitions=3).min_s
        return max(t_full - t_half, 0.0) / (n - n // 2)

    for label, kwargs in (("greedy", {}),
                          (f"temp={temp}/top{top_k}",
                           {"key": key, "temperature": temp,
                            "top_k": top_k})):
        t_plain = per_token(
            lambda m: generate(params, prompt, cfg, m, **kwargs)
        )
        print(f"plain {label}: {t_plain * 1e3:.3f} ms/token", flush=True)
        for gamma in gammas:
            t = per_token(
                lambda m: speculative_generate(
                    params, cfg, dparams, dcfg, prompt, m, gamma=gamma,
                    **kwargs)
            )
            print(f"spec  {label} gamma={gamma}: {t * 1e3:.3f} ms/token "
                  f"({t_plain / t:.2f}x)", flush=True)

    # --batched=B: the per-row-progress ragged impl vs the vmap-lifted
    # per-row loops, greedy, same heterogeneous batch (the measured
    # wall-clock note verdict item 7 asks for)
    bsz = arg("batched", 0)
    if bsz:
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        if pair:
            # heterogeneous on-distribution rows: per-row acceptance
            # varies, which is exactly what per-row progress is for
            import numpy as _np

            corpus = markov_corpus(cfg.vocab, 8192 + bsz * 512,
                                   draw_seed=778)
            prompts = jax.numpy.asarray(_np.stack(
                [corpus[i * 512:i * 512 + 128] for i in range(bsz)]),
                "int32")
        else:
            prompts = jax.random.randint(jax.random.PRNGKey(4),
                                         (bsz, 128), 0, cfg.vocab,
                                         "int32")
        for impl in ("ragged", "vmap"):
            t = per_token(lambda m: speculative_generate_batched(
                params, cfg, dparams, dcfg, prompts, m, gamma=4,
                impl=impl))
            print(f"spec batched[{impl}] B={bsz} gamma=4: "
                  f"{t * 1e3:.3f} ms/batch-token "
                  f"({bsz / t / 1e3:.2f}k tok/s)", flush=True)


if __name__ == "__main__":
    main()
