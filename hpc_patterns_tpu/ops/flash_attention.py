"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Standard flash-attention dataflow, TPU-shaped:

- grid = (batch·heads, T/BLOCK_Q): one program per query block per head;
  Pallas auto-pipelines each program's HBM→VMEM block loads against the
  previous program's compute (the same DMA/compute overlap the
  concurrency suite measures, here for free from the grid).
- K/V for the whole (small) sequence sit in VMEM per program; the kernel
  walks K/V blocks with ``lax.fori_loop``, maintaining the online
  softmax state (m, l, acc) in f32 — numerically identical to the
  two-pass softmax (same accumulator as parallel/ring_attention, which
  runs this dataflow *across chips*).
- block matmuls hit the MXU via ``jnp.dot(..., preferred_element_type=
  f32)``; bf16 inputs stay bf16 into the MXU.
- causal masking skips nothing but masks with a finite -1e30 (inf-free,
  like ring_attention), and whole K/V blocks strictly above the diagonal
  are skipped via ``lax.cond`` on the block index — half the FLOPs for
  causal.

Single-device kernel: under a mesh, distribute with
parallel.ring_attention / ulysses and let each rank call this locally
(mesh=None path of models.transformer).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
            causal: bool):
    # q_ref: (BLOCK_Q, D); k_ref/v_ref: (T, D); o_ref: (BLOCK_Q, D)
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    n_kv = t // block_k
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    q_start = qi * block_q

    def body(ki, state):
        m, l, acc = state
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        rescale = jnp.exp(m - m_new)
        l_new = l * rescale + p.sum(axis=-1, keepdims=True)
        acc_new = acc * rescale + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal:
        # K/V blocks strictly above the diagonal contribute nothing:
        # walk only blocks with start <= q block end
        last = (q_start + block_q - 1) // block_k + 1
        n_iter = jnp.minimum(last, n_kv)
    else:
        n_iter = n_kv
    m, l, acc = lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    # Recompute-based backward: the kernel and the dense formula compute
    # the same function, so differentiating the dense math on the saved
    # inputs gives exact gradients. Costs the O(T^2) score matrix in the
    # bwd only (the fwd stays O(block)); a Pallas bwd kernel is the
    # future upgrade (see pallas_guide "Patterns: Custom VJP").
    from hpc_patterns_tpu.parallel.ring_attention import full_attention

    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q, k, v: full_attention(q, k, v, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Softmax attention over (batch, seq, heads, head_dim) inputs.

    Numerically equal to parallel.ring_attention.full_attention (the
    oracle in tests); O(block) VMEM instead of the (T, T) score matrix.
    Sequence length must divide by the block sizes (pad upstream — the
    model keeps T a multiple of 128). Differentiable: custom VJP with a
    recompute-from-inputs backward.
    """
    return _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def _flash_forward(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"seq {T} must divide by blocks ({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # (B, T, H, D) -> (B*H, T, D): one grid row per (batch, head)
    qr = jnp.einsum("bthd->bhtd", q).reshape(B * H, T, D)
    kr = jnp.einsum("bthd->bhtd", k).reshape(B * H, T, D)
    vr = jnp.einsum("bthd->bhtd", v).reshape(B * H, T, D)

    kernel = functools.partial(
        _kernel, block_k=block_k, scale=float(scale), causal=causal,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)  # -> (B, T, H, D)
