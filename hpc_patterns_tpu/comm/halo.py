"""Halo exchange: ghost-cell neighbor transfer on the ring.

The interop suite's shared-USM role in BASELINE.json is the
stencil/halo-exchange config ("SYCL+OMP shared-USM stencil with halo
exchange"; SURVEY.md §5 calls it "the stencil/halo analog" of the ring
engine). A halo exchange is two simultaneous one-hop ring transfers:
each rank sends its boundary strip left and right and receives its
neighbors' strips — ``lax.ppermute`` in both directions over ICI, the
deadlock-free form of the reference's even/odd ordered Send/Recv pairs
(allreduce-mpi-sycl.cpp:50-58).

Rank-local functions for use inside ``shard_map``; the domain axis is
dim 0 of the local shard, mesh-axis order = global domain order,
periodic by construction (the ring closes — pass explicit boundary
handling downstream for non-periodic problems).
"""

from __future__ import annotations

import jax.numpy as jnp

from hpc_patterns_tpu.comm import ring


def halo_exchange(x, axis: str, *, halo: int = 1):
    """Return ``x`` padded with ``halo`` ghost rows from each ring
    neighbor: (n_local, ...) → (n_local + 2·halo, ...).

    Row layout: ``[left-neighbor's last halo rows | x | right-neighbor's
    first halo rows]`` with periodic wrap-around.
    """
    if halo < 1:
        raise ValueError(f"halo must be >= 1, got {halo}")
    if x.shape[0] < halo:
        raise ValueError(
            f"local shard ({x.shape[0]} rows) smaller than halo {halo}"
        )
    # +1 shift: my strip lands on my right neighbor => what *I* receive
    # came from my left neighbor, and vice versa.
    from_left = ring.ring_shift(x[-halo:], axis, +1)
    from_right = ring.ring_shift(x[:halo], axis, -1)
    return jnp.concatenate([from_left, x, from_right], axis=0)


def jacobi_step(u, axis: str, *, alpha: float = 0.25):
    """One periodic 1-D diffusion (3-point Jacobi) step with halo
    exchange: u' = (1-2α)·u + α·(left + right). The canonical stencil
    the halo pattern exists for."""
    g = halo_exchange(u, axis, halo=1)
    return (1.0 - 2.0 * alpha) * g[1:-1] + alpha * (g[:-2] + g[2:])
