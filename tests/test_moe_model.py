"""MoE-transformer integration tests.

Slow tier: multi-step MoE training compiles are the bulk; fast-tier MoE
coverage lives in test_moe.py (unit oracles) and the dryrun MoE leg."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.models import TransformerConfig, init_params, loss_fn
from hpc_patterns_tpu.models.train import init_train_state, make_batch, make_train_step

MOE_TINY = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq=32, dtype="float32", n_experts=4)


class TestMoEModel:
    def test_ep_only_mesh_matches_dense_oracle(self):
        """With a drop-free capacity factor the routing outcome cannot
        depend on how tokens are sharded, so the ep-sharded loss must
        equal the single-device loss."""
        cfg = TransformerConfig(**{**MOE_TINY, "capacity_factor": 8.0})
        mesh = topology.make_mesh({"ep": 4}, jax.devices()[:4])
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 2, 16)

        want = float(loss_fn(params, tokens, cfg))
        from hpc_patterns_tpu.models.sharding import shard_params

        got = float(
            jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(
                shard_params(params, mesh, cfg), tokens
            )
        )
        assert got == pytest.approx(want, rel=2e-5)

    def test_moe_training_learns(self):
        cfg = TransformerConfig(**{**MOE_TINY, "attention": "ring"})
        mesh = topology.make_mesh({"dp": 2, "sp": 2, "ep": 2})
        params, opt = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 4, 16, mesh)
        losses = []
        for _ in range(4):
            loss, params, opt = step(params, opt, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_moe_params_sharded_on_ep(self):
        cfg = TransformerConfig(**MOE_TINY)
        mesh = topology.make_mesh({"dp": 2, "ep": 4})
        params, _ = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        spec = params["layers"]["w1"].sharding.spec
        assert spec == jax.sharding.PartitionSpec(None, "ep", None, None)


class TestRoutingShardingTelemetry:
    def test_partitioned_routing_is_silent(self):
        # flagship-shaped config: batch divides batch_shards*ep, so
        # routing work partitions over ep — no fallback warning allowed
        import warnings

        cfg = TransformerConfig(**{**MOE_TINY, "capacity_factor": 8.0})
        mesh = topology.make_mesh({"dp": 2, "ep": 2}, jax.devices()[:4])
        from hpc_patterns_tpu.models.sharding import shard_params

        params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 4, 16, mesh)
        with warnings.catch_warnings():
            warnings.filterwarnings("error", message=".*routing runs replicated.*")
            loss = float(jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(params, tokens))
        assert np.isfinite(loss)

    def test_replicated_routing_warns(self):
        # batch 2 cannot split over dp*ep = 4 token shards: routing
        # replicates across ep and must SAY so
        cfg = TransformerConfig(**{**MOE_TINY, "capacity_factor": 8.0})
        mesh = topology.make_mesh({"dp": 2, "ep": 2}, jax.devices()[:4])
        from hpc_patterns_tpu.models.sharding import shard_params

        params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 2, 16, mesh)
        with pytest.warns(UserWarning, match="routing runs replicated"):
            loss = float(jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(params, tokens))
        assert np.isfinite(loss)


class TestTopKModel:
    def test_top2_ep_mesh_matches_dense_oracle(self):
        # drop-free capacity: the ep-sharded top-2 loss equals the
        # single-device top-2 loss (routing invariant to token sharding)
        cfg = TransformerConfig(**{**MOE_TINY, "capacity_factor": 8.0,
                                   "n_experts_top_k": 2})
        mesh = topology.make_mesh({"ep": 4}, jax.devices()[:4])
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 2, 16)
        want = float(loss_fn(params, tokens, cfg))
        from hpc_patterns_tpu.models.sharding import shard_params

        got = float(jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(
            shard_params(params, mesh, cfg), tokens))
        assert got == pytest.approx(want, rel=2e-5)

    def test_top2_training_learns(self):
        cfg = TransformerConfig(**{**MOE_TINY, "n_experts_top_k": 2})
        mesh = topology.make_mesh({"dp": 2, "ep": 2}, jax.devices()[:4])
        params, opt = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 4, 16, mesh)
        losses = []
        for _ in range(4):
            loss, params, opt = step(params, opt, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_drop_rate_telemetry(self):
        from hpc_patterns_tpu.models.transformer import moe_drop_rates

        tight = TransformerConfig(**{**MOE_TINY, "capacity_factor": 0.3})
        roomy = TransformerConfig(**{**MOE_TINY, "capacity_factor": 8.0})
        params = init_params(jax.random.PRNGKey(0), tight)
        tokens = make_batch(jax.random.PRNGKey(1), tight, 2, 16)
        d_tight = np.asarray(moe_drop_rates(params, tokens, tight))
        d_roomy = np.asarray(moe_drop_rates(params, tokens, roomy))
        assert d_tight.shape == (tight.n_layers,)
        assert d_tight.max() > 0.0     # starved capacity MUST show up
        assert d_roomy.max() == 0.0    # drop-free stays clean
        # and the ep-sharded diagnostic agrees with the local one
        mesh = topology.make_mesh({"ep": 4}, jax.devices()[:4])
        from hpc_patterns_tpu.models.sharding import shard_params

        d_mesh = np.asarray(jax.jit(lambda p, t: moe_drop_rates(
            p, t, roomy, mesh))(shard_params(params, mesh, roomy), tokens))
        assert d_mesh.max() == 0.0
