"""Native sweep driver (native/sweep.cpp) tests: build, parse, verdict."""

import json
import subprocess
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "native"
DRIVER = NATIVE / "hpcpat-sweep"


@pytest.fixture(scope="module", autouse=True)
def build_driver():
    # always invoke make: its dependency tracking makes the no-op case
    # free, and a stale binary after sweep.cpp edits would test old code
    r = subprocess.run(["make", "-C", str(NATIVE), "hpcpat-sweep"],
                       capture_output=True, timeout=120)
    if r.returncode != 0:
        pytest.skip(f"native build failed: {r.stderr.decode()[:200]}")


def _write_log(path, records):
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")


def _run(*args):
    return subprocess.run([str(DRIVER), *args], capture_output=True,
                          text=True, timeout=60)


class TestNativeSweep:
    def test_all_success_exits_zero(self, tmp_path):
        log = tmp_path / "run.jsonl"
        _write_log(log, [
            {"kind": "result", "name": "a", "success": True},
            {"kind": "step", "loss": 1.0},  # non-result lines ignored
            {"kind": "result", "name": "b", "success": True},
        ])
        r = _run("--log", str(log))
        assert r.returncode == 0, r.stdout
        assert "SUCCESS count: 2" in r.stdout
        assert "FAILURE count: 0" in r.stdout

    def test_any_failure_exits_one(self, tmp_path):
        log = tmp_path / "run.jsonl"
        _write_log(log, [
            {"kind": "result", "name": "a", "success": True},
            {"kind": "result", "name": "b", "success": False},
        ])
        r = _run("--log", str(log))
        assert r.returncode == 1
        assert "FAILURE count: 1" in r.stdout

    def test_empty_log_is_failure(self, tmp_path):
        log = tmp_path / "run.jsonl"
        log.write_text("")
        assert _run("--log", str(log)).returncode == 1

    def test_runs_commands_before_parsing(self, tmp_path):
        log = tmp_path / "run.jsonl"
        record = json.dumps({"kind": "result", "name": "x", "success": True})
        r = _run("--log", str(log), "--run", f"echo '{record}' > {log}")
        assert r.returncode == 0, r.stdout
        assert "SUCCESS count: 1" in r.stdout

    def test_failing_command_fails_run(self, tmp_path):
        log = tmp_path / "run.jsonl"
        record = json.dumps({"kind": "result", "name": "a", "success": True})
        r = _run("--log", str(log),
                 "--run", f"echo '{record}' >> {log}",
                 "--run", "false")
        assert r.returncode == 1
        assert "command exited with 1" in r.stdout  # decoded, not raw 256

    def test_stale_log_truncated_before_run(self, tmp_path):
        log = tmp_path / "run.jsonl"
        _write_log(log, [{"kind": "result", "name": "stale", "success": False}])
        record = json.dumps({"kind": "result", "name": "fresh", "success": True})
        r = _run("--log", str(log), "--run", f"echo '{record}' >> {log}")
        assert r.returncode == 0, r.stdout
        assert "SUCCESS count: 1" in r.stdout
        assert "FAILURE count: 0" in r.stdout

    def test_missing_log_is_usage_error(self, tmp_path):
        assert _run("--log", str(tmp_path / "nope.jsonl")).returncode == 2
        assert _run().returncode == 2

    @pytest.mark.slow  # boots a python app process on the CPU mesh
    def test_drives_allreduce_size_sweep(self, tmp_path):
        # the registered CI line for the BASELINE busbw-vs-size metric:
        # the native driver runs the sweep and judges its JSONL records
        import os
        import sys

        log = tmp_path / "ar.jsonl"
        cmd = (
            f"{sys.executable} -m hpc_patterns_tpu.apps.allreduce_app "
            f"--sweep --min-p 3 -p 4 --repetitions 2 --warmup 1 "
            f"--log {log} --log-append"
        )
        env = dict(os.environ)
        repo = str(Path(__file__).resolve().parent.parent)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        r = subprocess.run([str(DRIVER), "--log", str(log), "--run", cmd],
                           capture_output=True, text=True, timeout=600,
                           env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "SUCCESS count: 6" in r.stdout  # 3 algorithms x p in {3,4}
