"""Pipeline-parallel training for the flagship transformer.

The missing member of the parallelism matrix (dp/sp/tp/ep live in
models/transformer.py + models/sharding.py): layers split into P
contiguous stages over the ``pp`` mesh axis, driven by the 1F1B schedule
(parallel/pipeline.py — itself built on the reference's pt2pt ring,
SURVEY.md §2.2 "pairwise pt2pt: the core of PP").

Decomposition:

- **embedding** (embed + pos_embed): computed outside the pipeline on
  every rank (replicated math); its gradient comes back through the
  pipeline's input cotangents (``return_input_grads``).
- **stages**: the stacked layer params' leading ``n_layers`` axis is
  sharded over ``pp`` — each rank scans its ``L/P`` layers as one
  shape-preserving ``stage_fn``.
- **head** (ln_f_scale + lm_head): the last stage's loss head,
  differentiated via the pipeline's ``loss_params`` hook.

Gradients for the replicated pieces are psum'd over ``pp`` (only one
rank produces nonzero values — rank 0 for the embedding, rank P-1 for
the head — so the psum is a broadcast), exactly the §2.3 backend
property: collectives on device-resident shards, no host staging.

Composes with data parallelism: on a ("dp", "pp") mesh the batch is
dp-sharded outside, the pipeline runs per dp-slice, and gradients are
pmean'd over dp. The dp axis may cross slices (a DCN axis from
topology.make_hybrid_mesh): the once-per-step gradient pmean is the
latency-tolerant collective, while the per-tick stage ppermutes stay
slice-internal.

Composes with FSDP (ZeRO-3) over an ``fsdp`` mesh axis: stage params
are stored sharded on a feature dim (the same per-weight dims as
models/sharding.param_specs), all-gathered JUST BEFORE the stage scan
inside the pipeline shard_map, and their gradients leave as a
reduce-scatter (psum_scatter) back to the shard — params, grads, AND
optimizer state hold 1/fsdp of each stage weight per rank. The batch
shards over (dp, fsdp) together, like the non-pp fsdp path. The
embedding/head stay replicated (they are not stage params; shard them
over fsdp via the vocab dim if they ever dominate).

Composes with Megatron tensor parallelism over an ``tp`` mesh axis
INSIDE each stage (the canonical large-model layout: tp innermost over
ICI neighbors, pp across): stage weights column/row-split per
models/sharding.py's rule table, rank-local attention on local
q/kv-head shards, the f/g conjugate pair at region boundaries (explicit
custom_vjps — see the Megatron block below), two psums per layer.
Dense MLP stages only (MoE + tp rejected); the packed qkv weight is
column-permuted on the way in so contiguous tp splits align with the
q/k/v sections (public layout unchanged).

Composes with MoE: stages return their load-balance aux loss alongside
the activation and the 1F1B schedule threads it through
(``stage_aux_weight``) — the aux gradient rides the normal backward,
and the reported loss adds the psum'd aux term. Experts are
stage-local (dense routing per pp rank, no ep axis inside the
pipeline).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import optax

from hpc_patterns_tpu.topology import shard_map

from hpc_patterns_tpu.models.transformer import (
    TransformerConfig,
    _attention,
    _layer,
    _rmsnorm,
    apply_rope,
    chunked_masked_causal_nll,
    init_params,
    masked_causal_nll,
)
from hpc_patterns_tpu.models.train import make_optimizer
from hpc_patterns_tpu.parallel.pipeline import pipeline_train_1f1b


def _embed(outer, tokens, cfg):
    dt = jnp.dtype(cfg.dtype)
    T = tokens.shape[-1]
    x = outer["embed"].astype(dt)[tokens]
    if cfg.pos_embed == "learned":
        x = x + outer["pos_embed"].astype(dt)[:T]
    return x


def _stage_fn(layers_shard, h, cfg):
    """One pipeline stage: scan this rank's L/P layers (shape-preserving,
    single-device math — mesh=None inside the pp rank). MoE configs
    return ``(h, aux)`` — the stage-local load-balance loss sum, which
    the 1F1B schedule threads through via ``stage_aux_weight`` (experts
    are stage-local here: dense routing per rank, no ep axis inside the
    pipeline)."""
    def body(carry, lp):
        x, aux = carry
        x, a = _layer(x, lp, cfg, mesh=None, act_spec=None)
        return (x, aux + a), None

    (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                           layers_shard)
    if cfg.n_experts:
        return h, aux
    return h


# ---------------------------------------------------------------------------
# Megatron TP inside pipeline stages
# ---------------------------------------------------------------------------
#
# Stage math runs rank-local inside the pipeline shard_map, so tensor
# parallelism here is the MANUAL Megatron form: column-parallel
# qkv/up-projections, row-parallel out/down-projections, and the f/g
# conjugate operators at the region boundaries. f and g are explicit
# custom_vjps (identity-fwd/psum-bwd and psum-fwd/identity-bwd) rather
# than relying on lax.psum's transpose under check_vma=False — psum
# transposing to psum would double-count the replicated residual
# cotangent by a factor of tp (the documented shard_map AD footgun).
# This is the building-block composition SURVEY.md §2.2 calls for: the
# row-parallel reduction IS the reference's allreduce
# (allreduce-mpi-sycl.cpp:61-67) riding inside a pipeline stage.


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_f(x, axis):
    """Megatron's f: identity forward; backward psums the cotangent
    over ``axis`` (the input is replicated over tp, and each rank only
    computes its own column-shard's contribution)."""
    return x


def _tp_f_fwd(x, axis):
    return x, None


def _tp_f_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


_tp_f.defvjp(_tp_f_fwd, _tp_f_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_g(x, axis):
    """Megatron's g: psum forward (the row-parallel reduction);
    backward passes the replicated cotangent straight through to every
    rank's partial sum."""
    return lax.psum(x, axis)


def _tp_g_fwd(x, axis):
    return lax.psum(x, axis), None


def _tp_g_bwd(axis, _, ct):
    return (ct,)


_tp_g.defvjp(_tp_g_fwd, _tp_g_bwd)


def tp_permute_wqkv(wqkv, cfg: TransformerConfig, tp: int):
    """Reorder the packed-qkv columns ``[q | k | v]`` into per-rank
    blocks ``[q_0|k_0|v_0 | q_1|k_1|v_1 | ...]`` so a contiguous
    last-dim split over tp hands each rank its own q/k/v sections (a
    naive contiguous split of the packed layout would cut across the
    sections). Pure column gather — applied once per step on the way
    into the pipeline shard_map; the public param layout stays
    standard."""
    D = cfg.d_model
    S = cfg.kv_heads * cfg.head_dim
    q, k, v = jnp.split(wqkv, [D, D + S], axis=-1)
    qs = jnp.split(q, tp, axis=-1)
    ks = jnp.split(k, tp, axis=-1)
    vs = jnp.split(v, tp, axis=-1)
    return jnp.concatenate(
        [jnp.concatenate([qs[r], ks[r], vs[r]], axis=-1)
         for r in range(tp)],
        axis=-1,
    )


def tp_unpermute_wqkv(wqkv_p, cfg: TransformerConfig, tp: int):
    """Inverse of :func:`tp_permute_wqkv` (applied to the wqkv gradient
    on the way out, so optimizer/checkpoint/oracle all see the standard
    packed layout)."""
    Dl = cfg.d_model // tp
    Sl = cfg.kv_heads * cfg.head_dim // tp
    qs, ks, vs = [], [], []
    for blk in jnp.split(wqkv_p, tp, axis=-1):
        qb, kb, vb = jnp.split(blk, [Dl, Dl + Sl], axis=-1)
        qs.append(qb)
        ks.append(kb)
        vs.append(vb)
    return jnp.concatenate(qs + ks + vs, axis=-1)


def _tp_layer(x, lp, cfg: TransformerConfig, axis_tp: str, tp: int):
    """One pre-norm block with Megatron TP over ``axis_tp``: local
    q/kv heads (column split), rank-local attention (heads are
    embarrassingly parallel; GQA stays narrow — tp must divide
    kv_heads), row-parallel wo and w2 closed by g. Activations x are
    replicated over tp; exactly two psums per layer."""
    B, T, D = x.shape
    dt = x.dtype
    Hl, Hkvl, Dh = cfg.n_heads // tp, cfg.kv_heads // tp, cfg.head_dim
    Dl = D // tp

    a = _tp_f(x, axis_tp)
    h = _rmsnorm(a, lp["ln1_scale"])
    qkv = jnp.dot(h, lp["wqkv"].astype(dt))  # local [q_r|k_r|v_r]
    q, k, v = jnp.split(qkv, [Dl, Dl + Hkvl * Dh], axis=-1)
    q = q.reshape(B, T, Hl, Dh)
    k = k.reshape(B, T, Hkvl, Dh)
    v = v.reshape(B, T, Hkvl, Dh)
    if cfg.pos_embed == "rope":
        pos = lax.broadcasted_iota(jnp.int32, (T,), 0)
        q = apply_rope(q, pos, cfg)
        k = apply_rope(k, pos, cfg)
    o = _attention(q, k, v, cfg, None).reshape(B, T, Dl)
    x = x + _tp_g(jnp.dot(o, lp["wo"].astype(dt)), axis_tp)

    b = _tp_f(x, axis_tp)
    h2 = _rmsnorm(b, lp["ln2_scale"])
    if cfg.mlp_impl == "fused":
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        y = fused_mlp(h2, lp["w1"].astype(dt), lp["w2"].astype(dt))
    else:
        y = jnp.dot(jax.nn.gelu(jnp.dot(h2, lp["w1"].astype(dt))),
                    lp["w2"].astype(dt))
    return x + _tp_g(y, axis_tp)


def _tp_stage_fn(layers_shard, h, cfg, axis_tp, tp):
    """TP counterpart of :func:`_stage_fn` (dense MLP only — pp x tp
    with MoE stages is rejected upstream)."""
    def body(x, lp):
        return _tp_layer(x, lp, cfg, axis_tp, tp), None

    h, _ = lax.scan(body, h, layers_shard)
    return h


def check_tp(cfg: TransformerConfig, tp: int):
    if cfg.n_experts:
        raise ValueError(
            "pp x tp with MoE stages is unsupported: experts route "
            "densely per stage (use ep outside pp, or tp without "
            "experts)"
        )
    for name, val in (("d_model", cfg.d_model), ("n_heads", cfg.n_heads),
                      ("kv_heads", cfg.kv_heads), ("d_ff", cfg.d_ff)):
        if val % tp:
            raise ValueError(
                f"{name} {val} must divide by tp={tp} for Megatron "
                "stage sharding"
            )


def _loss_head(lp, y, target_tokens, *, loss_chunk: int = 0):
    """Final-norm + LM head + the shared masked causal NLL
    (transformer.masked_causal_nll — identical loss semantics to
    transformer.loss_fn by construction). With ``loss_chunk`` the NLL is
    the online-logsumexp chunked form: the per-microbatch (b, T, vocab)
    logits never materialize, which is where the long-context memory
    wall bites hardest inside a pipeline stage (the 1F1B tick holds the
    stage's activations AND the loss head's intermediates live)."""
    x = _rmsnorm(y, lp["ln_f_scale"])
    if loss_chunk:
        return chunked_masked_causal_nll(
            x, lp["lm_head"].astype(y.dtype), target_tokens,
            chunk=loss_chunk,
        )
    logits = jnp.dot(x, lp["lm_head"].astype(y.dtype)).astype(jnp.float32)
    return masked_causal_nll(logits, target_tokens)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_pmax_sg(x, axis):
    """stop-gradient pmax over ``axis``: lax.pmax has no
    differentiation rule at all (even a downstream stop_gradient
    doesn't save the trace), and a logsumexp stability shift's
    cotangent is identically zero anyway — so the backward is an
    explicit zero."""
    return lax.pmax(x, axis)


def _tp_pmax_sg_fwd(x, axis):
    return lax.pmax(x, axis), None


def _tp_pmax_sg_bwd(axis, _, ct):
    return (jnp.zeros_like(ct),)


_tp_pmax_sg.defvjp(_tp_pmax_sg_fwd, _tp_pmax_sg_bwd)


def _loss_head_tp(lp, y, target_tokens, *, axis_tp: str):
    """Vocab-sharded pipeline loss head: the last stage's lm_head is
    column-split over tp (each rank holds V/tp vocab columns — the
    Megatron head), so per-rank logits are (b, T, V/tp) instead of the
    full vocabulary replicated per tp rank, and the masked causal NLL
    comes out of sharded-softmax reductions. The tp sums ride the g
    operator (psum-fwd/identity-bwd — lax.psum's transpose under
    check_vma=False would be wrong, same as the layer math) and the
    stability max-shift is stop_gradient'd (exact: a logsumexp shift's
    cotangent is identically zero). ``y`` enters through f so the
    stage backward receives a REPLICATED cotangent (each rank only
    computes the contribution through its own vocab columns).
    Numerically masked_causal_nll at f32, oracle-tested."""
    y = _tp_f(y, axis_tp)
    x = _rmsnorm(y, lp["ln_f_scale"])
    logits = jnp.dot(x, lp["lm_head"].astype(y.dtype)).astype(
        jnp.float32)  # (b, T, V/tp)
    B, T = target_tokens.shape
    targets = jnp.roll(target_tokens, -1, axis=1)
    v_loc = logits.shape[-1]
    lo = lax.axis_index(axis_tp) * v_loc
    m = _tp_pmax_sg(jnp.max(logits, axis=-1), axis_tp)
    se = jnp.sum(jnp.exp(logits - m[..., None]), axis=-1)
    t_loc = targets - lo
    in_shard = (t_loc >= 0) & (t_loc < v_loc)
    gold_local = jnp.take_along_axis(
        logits, jnp.clip(t_loc, 0, v_loc - 1)[..., None], axis=-1
    )[..., 0]
    # one stacked psum for both reductions (se and the masked gold
    # logit share the (B, T) shape; the pmax above must stay separate
    # — se depends on m)
    se, gold = _tp_g(
        jnp.stack([se, jnp.where(in_shard, gold_local, 0.0)]), axis_tp)
    logz = m + jnp.log(se)
    nll = logz - gold
    mask = (lax.broadcasted_iota(jnp.int32, (B, T), 1)
            < T - 1).astype(nll.dtype)
    return jnp.sum(nll * mask) / jnp.sum(mask)


def _pp_layer_specs(cfg: TransformerConfig, axis_pp: str,
                    axis_fsdp: str | None, axis_tp: str | None = None):
    """Per-leaf PartitionSpecs for the stacked layer params inside the
    pipeline: leading ``n_layers`` axis over pp, and (with
    ``axis_fsdp``/``axis_tp``) the same per-weight feature dims models/
    sharding.param_specs shards under fsdp and Megatron tp — one rule
    table, three parallelism schemes. ep axes are dropped (no expert
    axis inside pipeline stages); tp is dropped unless requested."""
    import dataclasses

    from hpc_patterns_tpu.models import sharding as shardlib

    base = shardlib.param_specs(
        dataclasses.replace(cfg, fsdp=bool(axis_fsdp),
                            axis_fsdp=axis_fsdp or "fsdp",
                            axis_tp=axis_tp or "tp")
    )["layers"]
    keep = {ax for ax in (axis_fsdp, axis_tp) if ax}

    def fix(spec):
        rest = [ax if ax in keep else None for ax in spec[1:]]
        return P(axis_pp, *rest)

    return jax.tree.map(fix, base, is_leaf=lambda x: isinstance(x, P))


def _fsdp_dim(spec, axis_fsdp):
    """Index of the fsdp-sharded dim in a layer-leaf spec (None when
    the leaf is replicated over fsdp — norm scales, router)."""
    for i, ax in enumerate(spec):
        if ax == axis_fsdp:
            return i
    return None


def pp_loss_and_grads(params, tokens, cfg: TransformerConfig, mesh,
                      *, microbatches: int, axis_pp: str = "pp",
                      axis_dp: str | None = None,
                      axis_fsdp: str | None = None,
                      axis_tp: str | None = None):
    """Mean causal-LM loss and full-parameter gradients via a 1F1B
    pipeline over ``axis_pp`` (optionally data-parallel over ``axis_dp``,
    ZeRO-3-sharded over ``axis_fsdp``, and/or Megatron tensor-parallel
    INSIDE each stage over ``axis_tp`` — see module docstring).

    ``params``: the standard init_params pytree (layers stacked on
    n_layers, which must divide by the pp axis size); with
    ``axis_fsdp``, layer leaves sharded per
    :func:`init_pp_train_state`'s placement. ``tokens``: (batch, seq)
    int32, batch divisible by microbatches (× dp × fsdp size).
    Loss, embedding, and head gradients are replicated on return;
    layer gradients return fsdp-sharded when ``axis_fsdp`` is set
    (matching the param storage, what the optimizer update consumes).

    ``axis_tp``: the canonical large-model layout — tp innermost (ICI
    neighbors), stage weights column/row-split per models/sharding.py's
    rule table, activations replicated over tp, two psums per layer
    (see the Megatron block above). The loss head is vocab-sharded too
    (lm_head column-split over tp, V/tp logits per rank, sharded-
    softmax NLL — :func:`_loss_head_tp`) whenever vocab divides by tp
    and ``loss_chunk`` is off; otherwise it falls back to the
    replicated head (chunked when ``loss_chunk`` is set). Tokens are
    shared across tp. MoE stages reject tp.
    """
    M = microbatches
    from hpc_patterns_tpu.models.transformer import QUANT_SCALE_SUFFIX

    if any(k.endswith(QUANT_SCALE_SUFFIX)
           for k in (*params, *params["layers"])):
        raise ValueError(
            "pp_loss_and_grads refuses an int8-quantized params tree "
            "(quantize_weights_int8): the pipeline's stage math spells "
            "its own matmuls and would apply raw int8 magnitudes — "
            "quantized weights are a decode-serving artifact "
            "(transformer.matmul_weight; docs/quantization.md)")
    pp = mesh.shape[axis_pp]
    L = cfg.n_layers
    if L % pp:
        raise ValueError(f"n_layers {L} must divide by pp={pp}")
    B = tokens.shape[0]
    dp = mesh.shape[axis_dp] if axis_dp else 1
    fs = mesh.shape[axis_fsdp] if axis_fsdp else 1
    tp = mesh.shape[axis_tp] if axis_tp else 1
    if tp == 1:
        axis_tp = None  # size-1 tp axis: plain stage math
    else:
        check_tp(cfg, tp)
    # Megatron (vocab-sharded) loss head whenever it can serve;
    # otherwise the replicated head stays available as the fallback
    # (loss_chunk keeps its chunked form, and a vocab tp doesn't
    # divide keeps full-vocab logits per rank)
    shard_head = bool(axis_tp) and cfg.vocab % tp == 0 and not cfg.loss_chunk
    if B % (M * dp * fs):
        raise ValueError(
            f"batch {B} must divide by microbatches*dp*fsdp={M * dp * fs}"
        )
    layer_specs = _pp_layer_specs(cfg, axis_pp, axis_fsdp, axis_tp)
    if axis_fsdp:
        for name, spec in layer_specs.items():
            d = _fsdp_dim(spec, axis_fsdp)
            if d is None:
                continue
            size = params["layers"][name].shape[d]
            if size % fs:
                raise ValueError(
                    f"layers[{name}] dim {d} ({size}) must divide by "
                    f"fsdp={fs}"
                )

    outer = {"embed": params["embed"]}
    if cfg.pos_embed == "learned":
        outer["pos_embed"] = params["pos_embed"]
    head = {"ln_f_scale": params["ln_f_scale"], "lm_head": params["lm_head"]}

    def local(outer, layers_shard, head, tokens_local):
        toks = tokens_local.reshape(M, -1, tokens_local.shape[-1])
        x_mb = _embed(outer, toks, cfg)

        if axis_fsdp:
            # ZeRO-3 gather: materialize this stage's full weights just
            # before use (the stored shard is 1/fs of each feature dim)
            layers_full = {
                k: (v if _fsdp_dim(layer_specs[k], axis_fsdp) is None
                    else lax.all_gather(
                        v, axis_fsdp,
                        axis=_fsdp_dim(layer_specs[k], axis_fsdp),
                        tiled=True,
                    ))
                for k, v in layers_shard.items()
            }
        else:
            layers_full = layers_shard

        stage = (partial(_tp_stage_fn, cfg=cfg, axis_tp=axis_tp, tp=tp)
                 if axis_tp else partial(_stage_fn, cfg=cfg))
        loss, layer_grads, extras = pipeline_train_1f1b(
            stage,
            layers_full,
            x_mb,
            toks,
            (partial(_loss_head_tp, axis_tp=axis_tp) if shard_head
             else partial(_loss_head, loss_chunk=cfg.loss_chunk)),
            axis_pp,
            loss_params=head,
            return_input_grads=True,
            stage_aux_weight=cfg.moe_aux_weight if cfg.n_experts else None,
        )

        # embedding backward: cotangents of the pipeline inputs (nonzero
        # on pp rank 0) pulled through the replicated embedding math
        _, embed_vjp = jax.vjp(lambda o: _embed(o, toks, cfg), outer)
        (outer_grads,) = embed_vjp(extras["input_grads"].astype(x_mb.dtype))

        # replicate the rank-local pieces: loss and head grads live on
        # the last pp rank, embedding grads on rank 0, so psum = broadcast
        loss = lax.psum(loss, axis_pp)
        if cfg.n_experts:
            # total load-balance loss: stage-local sums live per rank;
            # psum over pp = the sum over all layers, / M for the
            # per-microbatch mean (matching transformer.loss_fn, whose
            # aux is summed over layers on the whole batch)
            aux_mean = lax.psum(extras["aux_sum"], axis_pp) / M
            loss = loss + cfg.moe_aux_weight * aux_mean
        head_grads = jax.tree.map(lambda g: lax.psum(g, axis_pp),
                                  extras["loss_grads"])
        if shard_head:
            # sharded-head grads: lm_head's shard is per-rank unique,
            # but ln_f_scale is replicated over tp and each rank only
            # computed the contribution through its own vocab columns.
            # (The replicated-head fallback needs neither: its grads
            # are identical across tp ranks.)
            head_grads = dict(head_grads)
            head_grads["ln_f_scale"] = lax.psum(
                head_grads["ln_f_scale"], axis_tp)
        outer_grads = jax.tree.map(
            lambda g: lax.psum(
                jnp.where(lax.axis_index(axis_pp) == 0, g.astype(jnp.float32),
                          jnp.zeros_like(g, jnp.float32)),
                axis_pp,
            ),
            outer_grads,
        )
        if axis_tp:
            # tp-replicated stage leaves (the norm scales): each rank
            # only computed its own column-shard's contribution through
            # the f region, so the true grad is the sum over tp
            layer_grads = {
                k: (lax.psum(g, axis_tp)
                    if axis_tp not in layer_specs[k] else g)
                for k, g in layer_grads.items()
            }
        if axis_fsdp:
            # ZeRO-3 reduce-scatter: each rank keeps the grad tile of
            # the shard it stores; /fs makes it the MEAN over the fsdp
            # batch shards (the dp convention)
            layer_grads = {
                k: (lax.pmean(g, axis_fsdp)
                    if _fsdp_dim(layer_specs[k], axis_fsdp) is None
                    else lax.psum_scatter(
                        g, axis_fsdp,
                        scatter_dimension=_fsdp_dim(layer_specs[k],
                                                    axis_fsdp),
                        tiled=True,
                    ) / fs)
                for k, g in layer_grads.items()
            }
        small = (outer_grads, head_grads)
        for ax in (axis_dp, axis_fsdp):
            if ax:
                loss = lax.pmean(loss, ax)
                small = jax.tree.map(lambda g: lax.pmean(g, ax), small)
        if axis_dp:
            layer_grads = jax.tree.map(
                lambda g: lax.pmean(g, axis_dp), layer_grads
            )
        outer_grads, head_grads = small
        grads_all = (outer_grads, layer_grads, head_grads)
        # grads are summed over microbatches; the loss head is per-
        # microbatch mean, so divide by M for the mean-loss gradient
        return loss[None], *jax.tree.map(lambda g: g / M, grads_all)

    layers_in = params["layers"]
    if axis_tp:
        # per-rank packed-qkv blocks so the contiguous tp split lands
        # each rank its own q/k/v sections; grads unpermute below
        layers_in = dict(layers_in)
        layers_in["wqkv"] = tp_permute_wqkv(layers_in["wqkv"], cfg, tp)

    batch_axes = tuple(a for a in (axis_dp, axis_fsdp) if a)
    tok_spec = P(batch_axes) if batch_axes else P()
    # with the Megatron head, lm_head enters column-split over tp and
    # the final norm replicated
    head_specs = ({"ln_f_scale": P(), "lm_head": P(None, axis_tp)}
                  if shard_head else P())
    loss_spec = (P((*batch_axes, axis_pp)) if batch_axes else P(axis_pp))
    loss_r, outer_g, layer_g, head_g = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), layer_specs, head_specs, tok_spec),
        out_specs=(loss_spec, P(), layer_specs, head_specs),
        check_vma=False,  # validity masks + psum-broadcasts aren't VMA-provable
    )(outer, layers_in, head, tokens)
    if axis_tp:
        layer_g = dict(layer_g)
        layer_g["wqkv"] = tp_unpermute_wqkv(layer_g["wqkv"], cfg, tp)

    # pin the scalar replicated: XLA may otherwise leave it sharded
    # along an axis that spans OS processes (observed with pp x tp in
    # a 2-process launch), making float(loss) fail on non-addressable
    # ranks
    from jax.sharding import NamedSharding

    loss = lax.with_sharding_constraint(
        loss_r[0], NamedSharding(mesh, P()))
    grads = {
        "embed": outer_g["embed"],
        "layers": layer_g,
        "ln_f_scale": head_g["ln_f_scale"],
        "lm_head": head_g["lm_head"],
    }
    if "pos_embed" in outer_g:
        grads["pos_embed"] = outer_g["pos_embed"]
    return loss, grads


def make_pp_train_step(cfg: TransformerConfig, mesh, *, microbatches: int,
                       axis_pp: str = "pp", axis_dp: str | None = None,
                       axis_fsdp: str | None = None,
                       axis_tp: str | None = None, optimizer=None,
                       offload_opt_example=None):
    """Jitted ``step(params, opt_state, tokens) -> (loss, params,
    opt_state)`` training the full model through the 1F1B pipeline.

    ``axis_fsdp``: ZeRO-3 stage params (see :func:`pp_loss_and_grads`);
    the layer gradients arrive sharded like the params, so the
    optimizer update runs shard-local. ``offload_opt_example``: a
    host-resident optimizer state (models/train.offload_opt_state) —
    the update pulls it to HBM, applies, pushes back, all inside the
    one jit, exactly the sharded-train path's offload contract (the
    pipeline state lives inside the shard_map, but the OPTIMIZER state
    never does — it updates outside, where memory-kind streaming
    composes unchanged)."""
    optimizer = optimizer or make_optimizer()
    if offload_opt_example is not None:
        # tolerant of offload_opt_state's probe-gated identity
        # fallback (no usable pinned_host -> the example was left in
        # place and the tiers collapse), same as make_train_step
        from hpc_patterns_tpu.models.train import (
            offload_example_shardings,
        )

        host_sh, hbm_sh = offload_example_shardings(offload_opt_example)
    else:
        host_sh = hbm_sh = None

    def step(params, opt_state, tokens):
        if hbm_sh is not None:
            opt_state = jax.device_put(opt_state, hbm_sh)
        loss, grads = pp_loss_and_grads(
            params, tokens, cfg, mesh, microbatches=microbatches,
            axis_pp=axis_pp, axis_dp=axis_dp, axis_fsdp=axis_fsdp,
            axis_tp=axis_tp,
        )
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if host_sh is not None:
            opt_state = jax.device_put(opt_state, host_sh)
        return loss, params, opt_state

    # the loss OUTPUT is pinned replicated at the jit boundary: the
    # internal with_sharding_constraint alone can be overridden by the
    # partitioner's output placement, and a loss left sharded along a
    # process-spanning axis (seen with pp x tp under a 2-process
    # launch) breaks float(loss) on non-addressable ranks
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    if host_sh is not None:
        return jax.jit(
            step, donate_argnums=(0, 1),
            in_shardings=(None, host_sh, None),
            out_shardings=(rep, None, host_sh),
        )
    return jax.jit(step, donate_argnums=(0, 1),
                   out_shardings=(rep, None, None))


def init_pp_train_state(key, cfg: TransformerConfig, optimizer=None,
                        mesh=None, *, axis_pp: str = "pp",
                        axis_fsdp: str | None = None):
    """f32 params + opt state. Replicated by default (the layer stack's
    leading axis is what the pp shard_map slices); with ``mesh`` and
    ``axis_fsdp``, layer leaves are PLACED sharded over (pp, fsdp) —
    each rank materializes only its own stage-weight shard, and the
    optax state inherits the placement (zeros_like preserves
    sharding)."""
    optimizer = optimizer or make_optimizer()
    if mesh is not None and axis_fsdp:
        from jax.sharding import NamedSharding

        specs = _pp_layer_specs(cfg, axis_pp, axis_fsdp)
        shardings = {
            "layers": jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        }
        replicated = NamedSharding(mesh, P())
        full = jax.tree.map(
            lambda _: replicated,
            jax.eval_shape(lambda k: init_params(k, cfg), key),
        )
        full["layers"] = shardings["layers"]
        # jaxlint: disable=recompile-hazard — init-time one-shot (once
        # per pp train state); out_shardings close over the runtime mesh
        params = jax.jit(
            lambda k: init_params(k, cfg), out_shardings=full
        )(key)
    else:
        params = init_params(key, cfg)
    return params, optimizer.init(params)
