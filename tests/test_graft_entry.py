"""The driver's dry run must exercise the FLAGSHIP paths.

The reference's tests are its binaries — a flagship check has to run
the flagship code path, not a degraded fallback
(aurora.mpich.miniapps/src/CMakeLists.txt:39-50 runs the real miniapps).
Round 3's dryrun violated that twice, silently: the MoE leg's batch did
not divide dp*ep (routing replicated across ep — the exact fallback its
own warning exists to flag), and the FSDP leg's embedding table
resharding made the spmd partitioner emit "involuntary full
rematerialization" warnings. This test runs the real
``_dryrun_multichip_impl`` with those warnings promoted to errors.
"""

import sys
import warnings
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


@pytest.mark.slow
def test_dryrun_runs_flagship_paths(capfd):
    import jax

    import __graft_entry__ as g

    # the spmd partitioner only runs during COMPILATION — a warm
    # persistent compile cache would skip it and the stderr assert
    # below would pass vacuously against an empty stream. Force cold
    # compiles: drop the persistent cache for this test and clear the
    # in-memory executable caches, so partitioning provably happened.
    old_cache = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.clear_caches()
        with warnings.catch_warnings():
            # any degraded-path telemetry warning fails the dry run
            warnings.filterwarnings(
                "error", message=".*routing runs replicated.*")
            warnings.filterwarnings("error", message=".*falls back.*")
            g.dryrun_multichip(8)
    finally:
        jax.config.update("jax_compilation_cache_dir", old_cache)

    # the spmd partitioner logs involuntary full remats to stderr (C++
    # absl logging); a clean flagship dry run has none
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err
