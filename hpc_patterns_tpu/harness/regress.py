"""Bench regression gate over the checked-in ``BENCH_r*.json`` rounds.

The bench trajectory was write-only: every round appends a capture
(``bench.py``'s one-line JSON verdict wrapped in the driver's round
schema — ``{"n", "cmd", "rc", "tail", "parsed"}``), and nothing ever
reads it back, so a perf regression lands silently and is only noticed
rounds later by a human eyeballing RESULTS.md. This module is the
machine check: parse the trajectory, compare the NEWEST comparable
round's headline numbers against the BEST prior round, and exit
nonzero with a readable table when any gated metric degrades beyond
tolerance.

What is compared (when present in a round's ``parsed`` payload):

- ``value`` / ``vs_baseline`` — the capture's headline (the on-chip
  overlap speedup today; any future ``bench.py`` headline rides the
  same keys);
- serving numbers under ``detail`` (``serving_tok_s`` higher-better,
  ``serving_bubble_frac`` / ``serving_prefill_compiles`` lower-better)
  and ``allreduce_busbw_gbps`` — the production-serving headline set;
- ``detail.dma_gbps`` is reported but NOT gated: bench.py's own
  session-health telemetry (NOMINAL_DMA_GBPS) established that DMA
  rate tracks chip/tunnel session quality, not code — a slow session
  must down-weight the ratio's interpretation, not fail the gate.

Rounds that measured nothing are excluded, not failed: ``parsed`` null
(the round-4 rc=1 traceback) or ``detail.degenerate`` true (the
round-5 tunnel timeout) mean the ENVIRONMENT broke, and a gate that
fails on a dead chip session would train everyone to ignore it. They
are listed as skipped; the newest round that actually measured is what
gates.

Coverage loss warns (stderr + table): when the newest round LACKS a
gated key that a prior comparable same-headline round carried (e.g.
``detail.serving_tok_s`` silently dropping out of a capture), that is
a lost measurement, not a pass — value-only gating would never notice.
The gate still exits 0 (the round may legitimately skip a subsystem),
but the warning makes the day a key disappears visible;
``--strict-coverage`` promotes it to a gate failure for CI legs where
every subsystem is expected to capture.

Usage::

    python -m hpc_patterns_tpu.harness.regress BENCH_r0*.json
    python bench.py --gate        # capture a new round, then gate it

Exit 0: no regression (or nothing to compare). 1: regression, table on
stdout names the metric. 2: unreadable input.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import Any

DEFAULT_TOLERANCE = 0.10  # 10% relative


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One headline metric: where it lives in ``parsed`` (dot path),
    which direction is good, whether it gates (vs. informational), and
    an absolute slack added to the relative tolerance band (so
    near-zero lower-better values like bubble fractions don't turn a
    0.001 → 0.002 wobble into a 2x 'regression')."""
    path: str
    direction: str  # "higher" | "lower"
    gated: bool = True
    abs_slack: float = 0.0
    label: str | None = None

    @property
    def name(self) -> str:
        return self.label or self.path


SPECS: tuple[MetricSpec, ...] = (
    MetricSpec("value", "higher", label="headline value"),
    MetricSpec("vs_baseline", "higher"),
    MetricSpec("detail.dma_gbps", "higher", gated=False,
               label="dma_gbps (session health)"),
    MetricSpec("detail.serving_tok_s", "higher"),
    MetricSpec("detail.serving_bubble_frac", "lower", abs_slack=0.05),
    MetricSpec("detail.serving_prefill_compiles", "lower", abs_slack=1),
    MetricSpec("detail.allreduce_busbw_gbps", "higher"),
    # the robustness row (bench_serving --scenario): goodput is the
    # SLO-attained tok/s of the chaos scenario — a scheduling change
    # that keeps raw tok/s but blows the latency targets regresses
    # HERE; degraded-mode bubble gets the same near-zero slack as the
    # clean bubble fraction
    MetricSpec("detail.serving_goodput_tok_s", "higher"),
    MetricSpec("detail.serving_degraded_bubble_frac", "lower",
               abs_slack=0.05),
    # the device-initiated fused-collective row (comm/fused.py, PR 8):
    # fused ring allreduce bus bandwidth, and the fraction of the
    # host-driven gather-then-matmul time the fused allgather_matmul
    # hides under in-flight remote DMAs. The overlap fraction is
    # legitimately ~0 on the CPU smoke (the dma-discharge interpreter
    # serializes), so it gets the same near-zero absolute slack as the
    # bubble fractions.
    MetricSpec("detail.fused_allreduce_gbps", "higher"),
    MetricSpec("detail.allreduce_overlap_frac", "higher",
               abs_slack=0.05),
    # the serving-plane row (bench_serving --plane, round 10): plane
    # goodput is the SLO-attained tok/s of the 2-replica router run,
    # and the migration-overlap fraction is the measured share of each
    # KV-handoff window hidden under the destination's in-flight
    # decode chunk (serving_plane/router.py) — the disaggregation
    # claim in one number. Overlap varies with the stream's cold
    # starts, so it carries a wider absolute slack than the bubbles.
    MetricSpec("detail.plane_goodput_tok_s", "higher"),
    MetricSpec("detail.kv_migration_overlap_frac", "higher",
               abs_slack=0.10),
    # the device-side migration tier (round 17): the overlap fraction
    # measured ONLY over bundles that rode the fused paired remote-DMA
    # kernel (ServingPlane(migration="dma") — the router's DMA ledger
    # is None when nothing did, so a silent fallback to device_put
    # reads as coverage loss here, never as a passing number measured
    # on the wrong transport). Same cold-start wobble as the other
    # overlap fractions, same wider absolute slack. Bytes-per-round is
    # the dataplane pressure the tier carries — transport-invariant
    # workload geometry, so its band is tight: a bundle that silently
    # grows (a scale pool duplicated, a payload staged twice) regresses
    # here even when the wall clock forgives it.
    MetricSpec("detail.dma_migration_overlap_frac", "higher",
               abs_slack=0.10),
    # the Σ-bytes numerator is exact; the per-round denominator wobbles
    # with scheduler timing (a fast box drains the stream in fewer
    # rounds and the ratio RISES) — the absolute slack covers roughly
    # one round's worth of smoke-shape payload on top of the relative
    # band so only a real payload-size change (not a round-count
    # wobble) trips the gate
    MetricSpec("detail.migration_bytes_per_round", "lower",
               abs_slack=2048),
    # the tiered-memory row (bench_serving --offload, round 11):
    # constrained-HBM goodput is the SLO-attained tok/s of an engine
    # serving a working set ~2x its HBM pool through the residency
    # manager (token-identical to all-HBM — a capacity claim, not an
    # approximation), and the prefetch-overlap fraction is the
    # measured share of each host->HBM pull hidden under the decode
    # chunk. Overlap varies with rotation timing like the plane's
    # migration overlap, so it carries the same wider absolute slack.
    MetricSpec("detail.offload_goodput_tok_s", "higher"),
    MetricSpec("detail.prefetch_overlap_frac", "higher",
               abs_slack=0.10),
    # the prefix-sharing row (bench_serving --shared, round 12):
    # shared goodput is the SLO-attained tok/s of the sharing-aware
    # arena on the template/conversation-tree mix (token-identical to
    # private pages — a capacity/TTFT claim, not an approximation),
    # and the prefill-skip fraction is the measured share of prompt
    # tokens the radix match kept out of the prefill. The skip
    # fraction is a property of the MIX more than the engine, so it
    # carries the same wider absolute slack as the overlap fractions.
    MetricSpec("detail.shared_goodput_tok_s", "higher"),
    MetricSpec("detail.prefill_skip_frac", "higher",
               abs_slack=0.10),
    # the quantized-decode row (bench_serving --quant, round 13):
    # quantized goodput is the SLO-attained tok/s of an int8-KV engine
    # (both precision oracles — exact-within-precision and the
    # teacher-forced TV/greedy law — pass before the number exists),
    # and the pool-bytes fraction is the measured quantized-pool bytes
    # over a bf16 pool at equal residents. The fraction is pure
    # dtype geometry (~0.53), so its band is tight: a scale-pool
    # layout change that silently doubles the overhead regresses here.
    MetricSpec("detail.quant_goodput_tok_s", "higher"),
    MetricSpec("detail.kv_pool_bytes_frac", "lower", abs_slack=0.02),
    MetricSpec("detail.quant_bubble_frac", "lower", abs_slack=0.05),
    # the elastic-plane row (bench_serving --elastic, round 14):
    # attainment is the autoscaled plane's per-class SLO fraction on
    # the diurnal-ramp-under-replica-death scenario (the bench itself
    # asserts it strictly exceeds the fixed plane's before the number
    # exists — here the gate holds the trajectory: an autoscaler
    # change that starts shedding regresses attainment), and
    # goodput-per-replica-round is SLO-attained tokens per live
    # replica-round — the EFFICIENCY direction, so over-provisioning
    # into a green attainment still regresses. Attainment is a
    # fraction near 1.0; the small absolute slack absorbs a single
    # judgment flipping on a loaded CI box.
    MetricSpec("detail.elastic_slo_attainment", "higher",
               abs_slack=0.05),
    MetricSpec("detail.goodput_per_replica_round", "higher"),
    # the autofit row (bench_serving --fit, round 16): fitted goodput
    # is the tok/s of an engine configured by harness/autofit.py from
    # the recording leg's own RunLog (the fitted ladder's expected
    # padding is asserted strictly below the default's before the
    # number exists), and the gain fraction is fitted/default - 1 on
    # the same stream and pool geometry. The gain is a small ratio of
    # two wall clocks on a shared CI box, so it carries an absolute
    # slack wide enough that scheduler noise cannot fail the gate —
    # the fitter going WRONG shows up as the row's own strict-padding
    # assertion (coverage loss here), not as a small gain wobble.
    MetricSpec("detail.fitted_goodput_tok_s", "higher"),
    MetricSpec("detail.autofit_gain_frac", "higher", abs_slack=0.05),
    # the request-forensics row (bench_serving --scenario under
    # harness/reqtrace.py, round 18): coverage is the fraction of
    # finished-request wall time the lifecycle-segment tilings account
    # for — the row asserts >= 0.95 in-run, so the gate holds the
    # TRAJECTORY with a tight band (a new engine transition that
    # forgets its stamp site leaks `untracked` time and regresses here
    # before anyone reads a wrong attribution table). The p99 queue
    # share is WHERE the tail went, not how big it is — load-shape
    # dependent and legitimately mobile, so informational: the gate
    # prints the drift, the attribution table explains it.
    MetricSpec("detail.attribution_coverage_frac", "higher",
               abs_slack=0.02),
    MetricSpec("detail.ttft_p99_queue_share", "lower", gated=False,
               abs_slack=0.10,
               label="ttft_p99_queue_share (tail attribution)"),
    # the segment-budget row (bench_serving --slo-budget, round 20):
    # the stall share is what fraction of the pooled p99 inter-token
    # gap band the seeded slow_host_transfer run spends in decode-
    # stall segments — seeded physics, but the share rides scheduler
    # timing on a shared CI box, so the band is wide; it GROWING past
    # the slack means decode stalls got structurally worse (or a new
    # stall mechanism joined the band). The breach-segment count is
    # structural: the row asserts the set is exactly {prefetch_wait}
    # in-run, so any count above 1 means attribution smeared out of
    # the injected mechanism — zero slack.
    MetricSpec("detail.tpot_p99_stall_share", "lower",
               abs_slack=0.15,
               label="tpot_p99_stall_share (inter-token tail)"),
    MetricSpec("detail.budget_breach_segments", "lower",
               abs_slack=0.0),
)


def _dig(obj: Any, path: str):
    for part in path.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj


def load_round(path: str | Path) -> dict[str, Any]:
    with open(path) as f:
        rec = json.load(f)
    rec["_path"] = str(path)
    return rec


def comparable(rec: dict[str, Any]) -> bool:
    """A round that actually measured something: parsed verdict present
    and not self-declared degenerate (dead backend / tunnel timeout)."""
    parsed = rec.get("parsed")
    if not isinstance(parsed, dict):
        return False
    detail = parsed.get("detail")
    if isinstance(detail, dict) and detail.get("degenerate"):
        return False
    return True


def extract_metrics(rec: dict[str, Any]) -> dict[str, tuple[MetricSpec, float]]:
    """{metric name: (spec, value)} for every spec present in the
    round. Keyed by the capture's metric name too, so trajectories that
    change headline metric (onchip overlap -> something else) never
    compare apples to oranges."""
    parsed = rec["parsed"]
    prefix = parsed.get("metric", "?")
    out: dict[str, tuple[MetricSpec, float]] = {}
    for spec in SPECS:
        v = _dig(parsed, spec.path)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[f"{prefix}:{spec.name}"] = (spec, float(v))
    return out


@dataclasses.dataclass
class Row:
    name: str
    best_prior: float
    best_round: int
    newest: float
    delta_frac: float  # signed: + means improved in the good direction
    gated: bool
    failed: bool


def compare(rounds: list[dict[str, Any]],
            tolerance: float = DEFAULT_TOLERANCE) -> dict[str, Any]:
    """Newest comparable round vs the best prior comparable round,
    metric by metric. Returns {rows, newest, skipped, n_prior}; rows is
    empty when fewer than two rounds measured anything."""
    rounds = sorted(rounds, key=lambda r: r.get("n", 0))
    usable = [r for r in rounds if comparable(r)]
    skipped = [r for r in rounds if not comparable(r)]
    if len(usable) < 2:
        return {"rows": [], "newest": usable[-1] if usable else None,
                "skipped": skipped, "n_prior": max(0, len(usable) - 1),
                "coverage_loss": []}
    newest, prior = usable[-1], usable[:-1]
    # same-backend rounds only: a CPU-fallback capture gated against
    # the TPU trajectory would always "regress" — that is a backend
    # mismatch, not a perf change, so those priors are set aside (and
    # an all-mismatched history gates nothing rather than lying)
    backend = _dig(newest["parsed"], "detail.backend")
    if backend is not None:
        mismatched = [r for r in prior
                      if _dig(r["parsed"], "detail.backend")
                      not in (None, backend)]
        if mismatched:
            skipped = skipped + mismatched
            prior = [r for r in prior if r not in mismatched]
    if not prior:
        return {"rows": [], "newest": newest, "skipped": skipped,
                "n_prior": 0, "coverage_loss": []}
    new_metrics = extract_metrics(newest)
    # coverage-loss check: a gated key that prior comparable rounds
    # carried but the newest lacks is NOT a pass — the capture lost a
    # measurement (detail.serving_tok_s silently dropping out reads as
    # green under value-only gating). Same-prefix priors only: a round
    # that changed its headline metric is a different trajectory, not
    # lost coverage. Warn, don't fail: the round may legitimately not
    # exercise that subsystem, and the human owns that call.
    lost: dict[str, int] = {}  # lost key -> last round that carried it
    new_prefix = newest["parsed"].get("metric", "?")
    for r in prior:
        if r["parsed"].get("metric", "?") != new_prefix:
            continue
        for name, (spec, _v) in extract_metrics(r).items():
            if spec.gated and name not in new_metrics:
                lost[name] = max(lost.get(name, 0), r.get("n", 0))
    coverage_loss = sorted(lost.items())
    rows: list[Row] = []
    for name, (spec, new_v) in sorted(new_metrics.items()):
        prior_vals = []
        for r in prior:
            got = extract_metrics(r).get(name)
            if got is not None:
                prior_vals.append((got[1], r.get("n", 0)))
        if not prior_vals:
            continue
        if spec.direction == "higher":
            best, best_n = max(prior_vals)
            floor = best * (1.0 - tolerance) - spec.abs_slack
            failed = spec.gated and new_v < floor
            delta = (new_v - best) / abs(best) if best else 0.0
        else:
            best, best_n = min(prior_vals)
            ceil = best * (1.0 + tolerance) + spec.abs_slack
            failed = spec.gated and new_v > ceil
            delta = (best - new_v) / abs(best) if best else 0.0
        rows.append(Row(name, best, best_n, new_v, delta, spec.gated,
                        failed))
    return {"rows": rows, "newest": newest, "skipped": skipped,
            "n_prior": len(prior), "coverage_loss": coverage_loss}


def format_table(result: dict[str, Any], tolerance: float) -> str:
    lines = []
    newest = result["newest"]
    if result["skipped"]:
        names = ", ".join(
            f"r{r.get('n', '?')}" for r in result["skipped"])
        lines.append("skipped (degenerate/unparsed/backend-mismatched "
                     f"capture): {names}")
    if newest is None:
        lines.append("no comparable rounds — nothing to gate")
        return "\n".join(lines)
    if not result["rows"]:
        lines.append(
            f"newest comparable round r{newest.get('n', '?')} "
            f"({newest['_path']}) has no prior round to compare "
            "against — nothing to gate")
        return "\n".join(lines)
    lines.append(
        f"newest comparable round r{newest.get('n', '?')} "
        f"({newest['_path']}) vs best of {result['n_prior']} prior "
        f"round(s), tolerance {tolerance:.0%}:")
    lines.append("")
    lines.append(f"{'metric':<44} {'best prior':>12} {'newest':>12} "
                 f"{'delta':>8}  status")
    for row in result["rows"]:
        status = ("REGRESSION" if row.failed
                  else "ok" if row.gated else "info")
        lines.append(
            f"{row.name:<44} {row.best_prior:>12.4g} "
            f"(r{row.best_round}) {row.newest:>12.4g} "
            f"{row.delta_frac:>+7.1%}  {status}")
    for name, last_n in result.get("coverage_loss", []):
        lines.append("")
        lines.append(
            f"WARNING: coverage loss — gated key {name!r} (last "
            f"carried by r{last_n}) is absent from "
            f"r{newest.get('n', '?')}: the capture lost a "
            "measurement, not passed it")
    n_fail = sum(r.failed for r in result["rows"])
    lines.append("")
    lines.append("GATE: " + (f"FAIL ({n_fail} regression(s))" if n_fail
                             else "PASS"))
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("rounds", nargs="+",
                   help="bench round files, e.g. BENCH_r0*.json")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative degradation allowed before the gate "
                        f"fails (default {DEFAULT_TOLERANCE:.0%} — wide "
                        "enough for session-to-session chip noise, "
                        "narrow enough to catch a real fast-path "
                        "regression)")
    p.add_argument("--strict-coverage", action="store_true",
                   help="fail (exit 1) on coverage loss instead of "
                        "warning: a gated key that prior rounds "
                        "carried but the newest lacks becomes a gate "
                        "failure — for CI legs where every subsystem "
                        "is expected to capture")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not 0 <= args.tolerance < 1:
        print(f"ERROR: --tolerance must be in [0, 1), got "
              f"{args.tolerance}", file=sys.stderr)
        return 2
    try:
        rounds = [load_round(p) for p in args.rounds]
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    result = compare(rounds, tolerance=args.tolerance)
    print(format_table(result, args.tolerance))
    coverage_loss = result.get("coverage_loss", [])
    for name, last_n in coverage_loss:
        # stderr too: CI logs that only keep stderr still surface it
        severity = "ERROR" if args.strict_coverage else "WARNING"
        print(f"{severity}: coverage loss — gated key {name!r} absent "
              f"from the newest round (last carried by r{last_n})",
              file=sys.stderr)
    if any(r.failed for r in result["rows"]):
        return 1
    if args.strict_coverage and coverage_loss:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
