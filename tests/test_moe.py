"""Expert-parallel MoE tests: sharded result == dense oracle per token
shard (§4.2 style), drop semantics, aux loss."""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.topology import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu.parallel import moe

E, D, F = 8, 16, 32  # 8 experts over 8 ranks -> 1 expert/rank
N_LOCAL = 16


@pytest.fixture(scope="module")
def weights():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    router = jax.random.normal(ks[0], (D, E), jnp.float32)
    w1 = jax.random.normal(ks[1], (E, D, F), jnp.float32) / 4
    w2 = jax.random.normal(ks[2], (E, F, D), jnp.float32) / 6
    return router, w1, w2


class TestMoE:
    def test_ep_matches_dense_per_shard(self, mesh8, weights):
        router, w1, w2 = weights
        cap = moe.default_capacity(N_LOCAL, E)
        x = jax.random.normal(jax.random.PRNGKey(3), (8 * N_LOCAL, D), jnp.float32)

        y_ep, aux_ep = jax.jit(
            shard_map(
                lambda xl, wa, wb: moe.moe_ep(
                    xl, router, wa, wb, axis="x", capacity=cap
                ),
                mesh=mesh8,
                in_specs=(P("x", None), P("x", None, None), P("x", None, None)),
                out_specs=(P("x", None), P()),
                check_vma=False,
            )
        )(x, w1, w2)

        # dense oracle on each token shard with all experts local
        want = np.concatenate([
            np.asarray(
                moe.moe_dense(
                    x[r * N_LOCAL : (r + 1) * N_LOCAL], router, w1, w2,
                    capacity=cap,
                )[0]
            )
            for r in range(8)
        ])
        np.testing.assert_allclose(np.asarray(y_ep), want, atol=2e-5)
        assert np.isfinite(float(aux_ep))

    def test_dense_capacity_drops_tokens(self, weights):
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(4), (32, D), jnp.float32)
        y_small, _ = moe.moe_dense(x, router, w1, w2, capacity=1)
        y_big, _ = moe.moe_dense(x, router, w1, w2, capacity=32)
        # tighter capacity must zero-out some token outputs
        dropped_small = np.sum(np.all(np.asarray(y_small) == 0, axis=-1))
        dropped_big = np.sum(np.all(np.asarray(y_big) == 0, axis=-1))
        assert dropped_small > dropped_big

    def test_aux_loss_uniform_is_one(self, weights):
        router, w1, w2 = weights
        # uniform router -> f_e = P_e = 1/E -> aux = E * E * (1/E^2) = 1
        x = jax.random.normal(jax.random.PRNGKey(5), (1024, D), jnp.float32)
        # a zero router ties every token (argmax -> expert 0), so use a
        # small random router: near-uniform gates, near-uniform routing
        _, aux = moe.moe_dense(x, router * 1e-3, w1, w2, capacity=256)
        assert float(aux) == pytest.approx(1.0, rel=0.2)

    def test_default_capacity(self):
        assert moe.default_capacity(128, 8) == 20
        assert moe.default_capacity(4, 64) == 1


class TestTopK:
    def test_top2_drop_free_equals_gate_mixture(self, weights):
        # capacity >= all: top-2 output must equal the analytic mixture
        # sum_j norm_gate_j * FFN_j(x) over each token's 2 best experts
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(5), (24, D), jnp.float32)
        y, aux = moe.moe_dense(x, router, w1, w2, capacity=48, top_k=2)

        gates = jax.nn.softmax(x @ router, axis=-1)
        vals, idx = jax.lax.top_k(gates, 2)
        norm = vals / vals.sum(-1, keepdims=True)
        ffn = jnp.stack([
            jax.nn.gelu(x @ w1[e]) @ w2[e] for e in range(E)
        ])  # (E, N, D)
        want = sum(
            norm[:, j, None] * jnp.take_along_axis(
                ffn, idx[:, j][None, :, None], axis=0
            )[0]
            for j in range(2)
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=2e-5)
        assert np.isfinite(float(aux))

    def test_top2_first_choices_never_evicted(self, weights):
        # GShard priority: raising k must not change which FIRST choices
        # get slots — at capacity 1, top-1 kept set == the first-choice
        # assignments kept under top-2
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(6), (32, D), jnp.float32)
        d1, _, _, kept1 = moe._dispatch_combine(x, router, E, 1, top_k=1)
        d2, _, _, _ = moe._dispatch_combine(x, router, E, 1, top_k=2)
        # a token's first choice occupies the same slot in both
        gates = jax.nn.softmax(x @ router, axis=-1)
        first = jnp.argmax(gates, axis=-1)
        oh = jax.nn.one_hot(first, E)
        np.testing.assert_array_equal(
            np.asarray(jnp.einsum("nec,ne->nc", d1, oh)),
            np.asarray(jnp.einsum("nec,ne->nc", d2, oh)),
        )
        assert 0.0 < float(kept1) <= 1.0

    def test_ep_top2_matches_dense_per_shard(self, mesh8, weights):
        router, w1, w2 = weights
        cap = moe.default_capacity(2 * N_LOCAL, E)
        x = jax.random.normal(jax.random.PRNGKey(7), (8 * N_LOCAL, D),
                              jnp.float32)
        y_ep, aux_ep, kept_ep = jax.jit(
            shard_map(
                lambda xl, wa, wb: moe.moe_ep(
                    xl, router, wa, wb, axis="x", capacity=cap, top_k=2,
                    with_stats=True,
                ),
                mesh=mesh8,
                in_specs=(P("x", None), P("x", None, None), P("x", None, None)),
                out_specs=(P("x", None), P(), P()),
                check_vma=False,
            )
        )(x, w1, w2)
        want = np.concatenate([
            np.asarray(moe.moe_dense(
                x[r * N_LOCAL:(r + 1) * N_LOCAL], router, w1, w2,
                capacity=cap, top_k=2,
            )[0]) for r in range(8)
        ])
        np.testing.assert_allclose(np.asarray(y_ep), want, atol=2e-5)
        assert np.isfinite(float(aux_ep))
        assert 0.0 < float(kept_ep) <= 1.0

    def test_stats_report_drops(self, weights):
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(8), (32, D), jnp.float32)
        _, _, kept_tight = moe.moe_dense(x, router, w1, w2, capacity=1,
                                         with_stats=True)
        _, _, kept_roomy = moe.moe_dense(x, router, w1, w2, capacity=32,
                                         with_stats=True)
        assert float(kept_roomy) == 1.0
        assert float(kept_tight) < 1.0


class TestScatterDispatch:
    """Sort/scatter routing must reproduce the einsum (one-hot) oracle's
    assignments exactly — same kept set, same slots — at a fraction of
    the memory (the einsum form is O(N^2·cf/E) and OOMs a chip near 16k
    tokens)."""

    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("capacity", [1, 4, 64])
    def test_matches_einsum_dense(self, weights, top_k, capacity):
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(9), (32, D), jnp.float32)
        y_e, aux_e, kept_e = moe.moe_dense(x, router, w1, w2,
                                           capacity=capacity, top_k=top_k,
                                           with_stats=True)
        y_s, aux_s, kept_s = moe.moe_dense(x, router, w1, w2,
                                           capacity=capacity, top_k=top_k,
                                           with_stats=True,
                                           dispatch="scatter")
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                                   atol=2e-5)
        assert float(kept_s) == float(kept_e)
        np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)

    def test_grads_match_einsum(self, weights):
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(10), (32, D), jnp.float32)

        def loss(disp):
            def f(x, router, w1, w2):
                y, aux = moe.moe_dense(x, router, w1, w2, capacity=4,
                                       top_k=2, dispatch=disp)
                return jnp.sum(y * y) + 0.01 * aux
            return jax.grad(f, argnums=(0, 1, 2, 3))(x, router, w1, w2)

        for a, b in zip(loss("scatter"), loss("einsum")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5)

    def test_ep_scatter_matches_dense_scatter(self, mesh8, weights):
        router, w1, w2 = weights
        cap = moe.default_capacity(N_LOCAL, E)
        x = jax.random.normal(jax.random.PRNGKey(11), (8 * N_LOCAL, D),
                              jnp.float32)
        y_ep, aux_ep = jax.jit(
            shard_map(
                lambda xl, wa, wb: moe.moe_ep(
                    xl, router, wa, wb, axis="x", capacity=cap,
                    dispatch="scatter",
                ),
                mesh=mesh8,
                in_specs=(P("x", None), P("x", None, None), P("x", None, None)),
                out_specs=(P("x", None), P()),
                check_vma=False,
            )
        )(x, w1, w2)
        want = np.concatenate([
            np.asarray(moe.moe_dense(
                x[r * N_LOCAL:(r + 1) * N_LOCAL], router, w1, w2,
                capacity=cap, dispatch="scatter",
            )[0]) for r in range(8)
        ])
        np.testing.assert_allclose(np.asarray(y_ep), want, atol=2e-5)
        assert np.isfinite(float(aux_ep))

    def test_bad_dispatch_rejected(self, weights):
        router, w1, w2 = weights
        x = jax.random.normal(jax.random.PRNGKey(12), (8, D), jnp.float32)
        with pytest.raises(ValueError, match="dispatch"):
            moe.moe_dense(x, router, w1, w2, capacity=2, dispatch="magic")
