"""Zero-copy buffer sharing across runtimes: native C++ ↔ numpy ↔ JAX ↔
torch.

The reference proves OMP↔SYCL zero-copy by writing through one runtime
and reading through the other with asserts (interop_omp_ze_sycl.cpp:
81-101). Here the runtimes are the native allocator (hpcpat.cpp), numpy,
JAX (via the dlpack protocol) and torch; each bridge returns the shared
view AND the proof — *pointer identity* between producer and consumer —
which is stronger than value equality (a copy could pass a value check).

Scope note (honest TPU story): true zero-copy aliasing is a same-memory-
space property. These bridges are zero-copy on the host (CPU backend /
pinned host buffers); crossing into TPU HBM is a DMA by physics, which
is the M2D path of the concurrency suite, not interop. The reference is
the same: its zero-copy claim holds within one GPU's Level-Zero context.
"""

from __future__ import annotations

import numpy as np

import jax


def jax_pointer(arr) -> int:
    """Device-buffer address of a jax.Array (single shard)."""
    return arr.addressable_shards[0].data.unsafe_buffer_pointer()


def numpy_to_jax(x: np.ndarray):
    """Import host memory into JAX via dlpack, zero-copy.

    Returns (jax_array, zero_copy: bool) — zero_copy is proven by
    pointer identity, the ``assert`` of interop_omp_ze_sycl.cpp:90-91
    made airtight.

    XLA only aliases imports with >= 64-byte-aligned storage (it copies
    otherwise) — the TPU-stack reason the reference's ALIGNMENT-style
    aligned allocator (native.AlignedBuffer, ≙ allreduce-mpi-sycl.cpp:
    19-21) is load-bearing, not cosmetic: plain numpy allocations are
    16-aligned and silently lose the aliasing."""
    arr = jax.dlpack.from_dlpack(x)  # consumes x.__dlpack__()
    same = jax_pointer(arr) == x.ctypes.data
    return arr, bool(same)


def jax_to_numpy(arr) -> tuple[np.ndarray, bool]:
    """Export a CPU jax.Array to numpy via dlpack, zero-copy."""
    out = np.from_dlpack(arr)
    same = out.ctypes.data == jax_pointer(arr)
    return out, bool(same)


def jax_to_torch(arr):
    """Export a CPU jax.Array to torch via dlpack (torch is the stand-in
    for the reference's *other* runtime, as SYCL was to OpenMP)."""
    import torch

    t = torch.from_dlpack(arr)
    same = t.data_ptr() == jax_pointer(arr)
    return t, bool(same)


def torch_to_jax(t):
    """Import a torch CPU tensor into JAX via dlpack."""
    arr = jax.dlpack.from_dlpack(t)
    same = jax_pointer(arr) == t.data_ptr()
    return arr, bool(same)


def native_to_jax(buf):
    """The full reference chain: native-allocator memory → numpy view →
    JAX array, all aliasing one allocation (≙ ``omp_target_alloc_device``
    memory read by a SYCL queue, interop_omp_ze_sycl.cpp:81-91)."""
    np_view = buf.as_numpy()
    assert np_view.ctypes.data == buf.address, "numpy view must alias"
    arr, zc = numpy_to_jax(np_view)
    return arr, zc
