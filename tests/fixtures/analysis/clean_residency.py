"""Known-clean: the tiered-memory transfer discipline.

The prefetch/evict dispatch paths stay dispatch-only: pulls are async
``device_put`` trees the decode chunk hides, installs enqueue behind
the in-flight chunk, and the deliberate syncs (the swap-out cursor
snapshot, the round-boundary window completions) live in
``_detach_row`` / ``_complete_prefetches`` with their justified
suppressions — not in the dispatch paths themselves.
"""


def _dispatch_prefetch(engine, bundle):
    # dispatch-only: the pull enqueues async; the cursor decision was
    # made from host bookkeeping, not a device readback
    payload, handle = engine.residency.pull_payload(
        bundle.pages_payload,
        attrs={"seq_id": bundle.seq_id, "pages": bundle.n_pages})
    return payload, handle


def _install_prefetched(engine, bundle, payload):
    # the scatter + cursor seeding enqueue behind the in-flight chunk;
    # completion is observed at the round boundary, not here
    return engine._attach_row(bundle)


def _swap_out(engine, slot):
    # the payload moves tiers THROUGH the manager: pinned-host tier =
    # async device_put per leaf, window accounted
    bundle = engine._detach_row(slot)
    return engine.residency.push_payload(
        bundle.pages_payload, attrs={"seq_id": bundle.seq_id})
