"""Chaos injection: seeded, replayable fault injectors for robustness runs.

The pattern suites are self-validating benchmarks, but until now they
only ever measured the HAPPY path: every rank healthy, every host
responsive, every worker alive to the end. Production serving is the
opposite regime — the ROADMAP's "millions of users" scenario axis — and
the claim that degradation is *graceful* needs the same discipline as
every other claim in this repo: inject the fault on purpose, then PROVE
the observed behavior through the instruments (the distributed flight
recorder's skew/straggler/bubble rollups, the collective schedule
verifier) rather than asserting it.

Three fault kinds, each deterministic given its spec (replayable — the
same spec + the same workload reproduces the same perturbation):

- ``straggler``: injected delay at the ``collective`` site — the eager
  Communicator hot path (``comm/communicator.py``) AND every
  ``harness.timing.measure`` timed repetition (the launched
  benchmarks' collective loop — the same rep↔collective
  identification the cross-rank skew fan is built on) probe
  :func:`maybe_inject` per collective, so one rank running late shows
  up in the cross-rank merge exactly like a real slow rank: the skew
  fan points at it and the straggler table names it.
- ``stall``: injected delay at the ``engine_round`` site — the serving
  loop (``models/serving.py``) checks once per scheduler round, so a
  paused host reads as a bubble in the busy/bubble rollup.
- ``die``: mid-stream worker death at the ``collective`` site —
  ``SIGKILL`` (default) or ``os._exit(code)``, the hard kill that never
  reaches an exit handler. The launcher's rank report records the
  fault kind and still merges the surviving ranks' trace files
  (``apps/launch.py``).
- ``slow_host_transfer``: injected delay at the ``host_transfer`` site
  — the tiered-memory residency manager (``memory/residency.py``)
  probes it at every host->HBM prefetch dispatch, INSIDE the
  ``mem.prefetch`` trace window, so degraded host bandwidth shows up
  as exactly the widened window the overlap claim is gated on.

Spec grammar (the ``HPCPAT_CHAOS`` env value, or
``apps/launch.py --chaos``; ``;``-separated faults)::

    kind:key=value,key=value
    straggler:rank=1,delay_ms=40            # every collective on rank 1
    straggler:rank=1,delay_ms=40,every=4    # every 4th
    stall:at=3,delay_ms=100                 # one stall at round 3
    die:rank=1,at=5                         # SIGKILL at collective 5
    die:rank=1,at=5,code=7                  # os._exit(7) instead
    die:replica=2,at=5,site=replica_round   # kill ONE serving-plane
                                            # replica at its 5th round
    slow_host_transfer:delay_ms=40          # every tiered-memory
                                            # prefetch pays 40ms extra
    slow_host_transfer:at=2,delay_ms=40,every=0   # only the 3rd pull

``rank`` matches the launcher's ``HPCPAT_PROCESS_ID`` (absent = rank 0;
``rank`` omitted = every rank). Delays may carry deterministic jitter
(``jitter_ms`` + ``seed``): the jitter at a given (site, index) is a
pure hash, so a replay is byte-for-byte the same perturbation.

Import-light on purpose (stdlib only): the injection check sits on hot
paths whose disabled cost must be one cached-config read, and the
module must be importable from jax-free launcher children.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import signal
import threading
import time
from dataclasses import dataclass

ENV_CHAOS = "HPCPAT_CHAOS"

#: mirrors topology.ENV_PROCESS_ID as a literal so this module stays
#: jax-free (same discipline as analysis/runtime.py; asserted in sync
#: by tests/test_chaos.py)
ENV_PROCESS_ID = "HPCPAT_PROCESS_ID"

KINDS = ("straggler", "stall", "die", "slow_host_transfer")
#: ``replica_round`` (round 10): the serving plane's per-replica
#: scheduler round (serving_plane/service.py probes it once per
#: ``round`` message) — ``die:replica=2,at=5,site=replica_round``
#: kills one REPLICA of many mid-stream, where the original ``die``
#: killed one rank of one SPMD program. ``replica=`` is an alias for
#: ``rank=``: in a launched plane each replica IS one launcher process.
#: ``host_transfer`` (round 11): the tiered-memory prefetch dispatch
#: site (memory/residency.py probes it per host->HBM pull, between the
#: ``mem.prefetch`` window open and the transfer dispatch) —
#: ``slow_host_transfer:delay_ms=40`` models degraded host<->device
#: bandwidth: the injected delay WIDENS exactly the window it claims
#: to, so a degraded-bandwidth run is replayable and trace-provable.
SITES = ("collective", "engine_round", "replica_round",
         "host_transfer")

#: default injection site per kind (overridable via ``site=``)
_DEFAULT_SITE = {"straggler": "collective", "stall": "engine_round",
                 "die": "collective",
                 "slow_host_transfer": "host_transfer"}


@dataclass(frozen=True)
class Fault:
    """One parsed injector. ``at`` is the first matching index at the
    site; ``every`` repeats every k-th index after it (0 = fire at
    ``at`` only). ``rank`` None matches every process."""
    kind: str
    site: str
    rank: int | None = None
    at: int = 0
    every: int = 1
    delay_s: float = 0.0
    jitter_s: float = 0.0
    seed: int = 0
    exit_code: int | None = None  # die: None = SIGKILL

    def matches(self, site: str, index: int, rank: int) -> bool:
        if site != self.site:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if index < self.at:
            return False
        if self.every <= 0:
            return index == self.at
        return (index - self.at) % self.every == 0

    def delay_at(self, site: str, index: int) -> float:
        """The (deterministic) injected delay for this firing: base
        delay plus a pure-hash jitter fraction — replaying the same
        spec over the same schedule reproduces the same perturbation."""
        if self.jitter_s <= 0.0:
            return self.delay_s
        h = hashlib.sha256(
            f"{self.seed}|{site}|{index}".encode()).digest()
        u = int.from_bytes(h[:4], "big") / 2**32
        return self.delay_s + self.jitter_s * u


def parse(spec: str) -> tuple[Fault, ...]:
    """Parse a ``HPCPAT_CHAOS`` spec string into faults. Raises
    ``ValueError`` on unknown kinds/sites/keys — a typo'd chaos spec
    silently injecting nothing would be the worst failure mode of a
    tool whose job is making failures visible."""
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, body = part.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} (known: {', '.join(KINDS)})")
        kw: dict = {}
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            key, _, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if key in ("rank", "replica"):
                # one replica of a launched serving plane IS one
                # launcher process, so replica-targeting is rank-
                # targeting under the plane's spelling
                kw["rank"] = int(val)
            elif key == "at":
                kw["at"] = int(val)
            elif key == "every":
                kw["every"] = int(val)
            elif key == "delay_ms":
                kw["delay_s"] = float(val) / 1e3
            elif key == "jitter_ms":
                kw["jitter_s"] = float(val) / 1e3
            elif key == "seed":
                kw["seed"] = int(val)
            elif key == "code":
                kw["exit_code"] = int(val)
            elif key == "site":
                if val not in SITES:
                    raise ValueError(
                        f"unknown chaos site {val!r} "
                        f"(known: {', '.join(SITES)})")
                kw["site"] = val
            else:
                raise ValueError(f"unknown chaos key {key!r} in {part!r}")
        kw.setdefault("site", _DEFAULT_SITE[kind])
        if kind in ("die", "stall"):
            # death fires once definitionally; a stall is one pause at
            # ``at`` unless ``every`` asks for a recurring one — only
            # the straggler defaults to every matching index
            kw.setdefault("every", 0)
        faults.append(Fault(kind=kind, **kw))
    return tuple(faults)


# process-local state: an explicit configure() override wins; otherwise
# the env spec is parsed once per distinct value and cached. _UNSET is
# the "no override installed" sentinel (None is a real override: chaos
# explicitly OFF regardless of env).
_UNSET = object()
_override: object = _UNSET
_env_cache: tuple[str | None, tuple[Fault, ...] | None] = (None, None)
_log: list[dict] = []
_LOG_CAP = 10000


def configure(spec: str | tuple[Fault, ...] | None):
    """Install a process-local fault set overriding the env (None =
    chaos explicitly off). Clears the injection log. Returns the
    installed faults. Tests pair this with :func:`reset`."""
    global _override
    faults = parse(spec) if isinstance(spec, str) else (
        tuple(spec) if spec is not None else None)
    _override = faults
    _log.clear()
    return faults


def reset() -> None:
    """Drop any configure() override (back to env-driven) and clear
    the injection log."""
    global _override
    _override = _UNSET
    _log.clear()


def active() -> tuple[Fault, ...] | None:
    """The faults in force: the configure() override when installed,
    else the parsed ``HPCPAT_CHAOS`` env spec (cached per value), else
    None. The no-chaos fast path is this one call returning None."""
    global _env_cache
    if _override is not _UNSET:
        return _override  # type: ignore[return-value]
    spec = os.environ.get(ENV_CHAOS)
    if not spec:
        return None
    cached_spec, cached = _env_cache
    if spec != cached_spec:
        cached = parse(spec)
        _env_cache = (spec, cached)
    return cached


def _process_rank() -> int:
    try:
        return int(os.environ.get(ENV_PROCESS_ID) or 0)
    except ValueError:
        return 0


_claimed = threading.local()


@contextlib.contextmanager
def suppress(site: str):
    """Claim ``site`` for the caller's dynamic scope: probes of the
    same site underneath do not fire. ``harness.timing.measure`` claims
    ``collective`` around each timed rep AFTER probing it once — the
    rep IS the collective in the skew-fan identification, and an eager
    Communicator collective inside the rep re-probing the site would
    double the injected delay against the declared spec."""
    stack = getattr(_claimed, "sites", None)
    if stack is None:
        stack = _claimed.sites = []
    stack.append(site)
    try:
        yield
    finally:
        stack.pop()


def injections() -> tuple[dict, ...]:
    """What fired so far (site, index, kind, delay_s per event) — the
    assertion handle for tests and the scenario benchmarks ("the
    seeded stall actually fired" is part of the verdict, not assumed)."""
    return tuple(_log)


def matching(site: str, index: int,
             rank: int | None = None) -> tuple[Fault, ...]:
    """The faults that WOULD fire at (site, index, rank) — without
    executing them. For callers that implement kind-specific semantics
    themselves: the in-process serving plane runs every replica in ONE
    process, so a ``die:replica=N`` fault must mark replica N dead
    (router-visible, recoverable) instead of SIGKILLing the whole
    plane the way :func:`maybe_inject` would. ``rank`` overrides the
    process rank for the match — the plane passes the REPLICA ordinal,
    which is what ``replica=`` addresses there (in the launched plane
    each replica is its own process, so the two spellings coincide).
    The caller records what it executed via :func:`record_injection`
    so the fault-actually-fired asserts keep working."""
    faults = active()
    if not faults:
        return ()
    if site in getattr(_claimed, "sites", ()):
        return ()
    r = _process_rank() if rank is None else int(rank)
    return tuple(f for f in faults if f.matches(site, index, r))


def record_injection(site: str, index: int, kind: str, *,
                     rank: int | None = None,
                     delay_s: float = 0.0) -> None:
    """Log one caller-executed injection (the :func:`matching`
    counterpart of the log append :func:`maybe_inject` does itself)."""
    if len(_log) < _LOG_CAP:
        _log.append({
            "site": site, "index": index, "kind": kind,
            "rank": _process_rank() if rank is None else int(rank),
            "delay_s": delay_s})


def maybe_inject(site: str, index: int) -> None:
    """Fire every active fault matching (site, index, this rank).

    ``straggler``/``stall`` sleep their (deterministic) delay; ``die``
    kills the process the hard way — ``SIGKILL`` by default, so no
    Python-level cleanup runs, exactly like an OOM-killed or
    preempted worker. Call sites guard with ``active() is not None``
    so the disabled path costs one cached read."""
    faults = active()
    if not faults:
        return
    if site in getattr(_claimed, "sites", ()):
        return  # an enclosing scope (a timed rep) owns this site
    rank = _process_rank()
    for f in faults:
        if not f.matches(site, index, rank):
            continue
        if f.kind == "die":
            if len(_log) < _LOG_CAP:
                _log.append({"site": site, "index": index, "kind": f.kind,
                             "rank": rank, "delay_s": 0.0})
            if f.exit_code is not None:
                os._exit(f.exit_code)
            os.kill(os.getpid(), signal.SIGKILL)
        delay = f.delay_at(site, index)
        if len(_log) < _LOG_CAP:
            _log.append({"site": site, "index": index, "kind": f.kind,
                         "rank": rank, "delay_s": delay})
        if delay > 0.0:
            time.sleep(delay)
