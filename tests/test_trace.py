"""Tests for the flight recorder (harness/trace.py).

The timeline contract: ring-buffer overflow keeps the NEWEST events
with B/E pairs still balanced, exports are valid Chrome-trace JSON
(every B matched, per-thread timestamps monotonic), the compile
watcher stamps a forced recompile exactly once, and the disabled path
allocates nothing per span (the same no-op guard discipline as
tests/test_metrics.py — the tier-1 protection).
"""

import json
import time

import pytest

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.harness.trace import TraceRecorder


@pytest.fixture(autouse=True)
def _fresh_state():
    # the production default: no recorder, disabled registry — never
    # leak enablement into other tests
    yield
    tracelib.configure(enabled=False)
    metricslib.configure(enabled=False)


def _spans(chrome):
    return [e for e in chrome["traceEvents"]
            if e.get("cat") == "span"]


class TestRingBuffer:
    def test_overflow_keeps_newest_events(self):
        rec = TraceRecorder(capacity=10, mem_interval_s=float("inf"))
        for i in range(40):
            rec.span_begin(f"s{i}", {})
            rec.span_end(f"s{i}")  # 2 events per span, 80 total
        assert len(rec.events) == 10
        assert rec.n_events == 80
        names = {ev[2] for ev in rec.events}
        # the newest span survives, the oldest is long gone
        assert "s39" in names
        assert "s0" not in names
        assert rec.snapshot()["n_dropped"] == 70

    def test_balanced_export_across_eviction_edge(self):
        # evict an outer B while keeping its E: the orphan E must not
        # reach the export (Perfetto rejects unmatched ends)
        rec = TraceRecorder(capacity=4)
        rec.span_begin("outer", {})
        rec.span_begin("inner", {})
        rec.span_end("inner")
        rec.span_begin("tail", {})
        rec.span_end("tail")
        rec.span_end("outer")  # outer's B was evicted by now
        spans = _spans(rec.to_chrome())
        b = [e["name"] for e in spans if e["ph"] == "B"]
        e = [e["name"] for e in spans if e["ph"] == "E"]
        assert sorted(b) == sorted(e)
        assert "outer" not in b  # dropped whole, not half

    def test_open_span_synthesizes_end(self):
        rec = TraceRecorder(capacity=16)
        rec.span_begin("still_open", {})
        spans = _spans(rec.to_chrome())
        assert [e["ph"] for e in spans] == ["B", "E"]
        assert spans[1]["ts"] >= spans[0]["ts"]

    def test_overlapping_device_windows_use_subtracks(self):
        # admission windows overlap the decode chunk by design; Chrome
        # sync slices on ONE track must nest, so concurrent windows go
        # to per-slot subtracks and the export labels them distinctly
        rec = TraceRecorder(capacity=64)
        t_chunk = rec.mark_dispatch("serve.chunk", track=0)
        t_admit = rec.mark_dispatch("serve.admit", track=1)
        rec.mark_complete("serve.chunk", t_chunk, track=0)
        rec.mark_complete("serve.admit", t_admit, track=1)  # overlaps
        chrome = rec.to_chrome()
        xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
        assert len({e["tid"] for e in xs}) == 2
        labels = {e["args"]["name"] for e in chrome["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "thread_name"}
        assert "device (dispatch→completion)" in labels
        assert "device (admit slot 0)" in labels


class TestChromeExport:
    def test_export_is_valid_chrome_trace(self, tmp_path):
        rec = tracelib.configure(enabled=True)
        m = metricslib.configure(enabled=True)
        with m.span("outer", chunk=4):
            with m.span("inner"):
                time.sleep(0.001)
        t0 = rec.mark_dispatch("work", {"n": 1})
        rec.mark_complete("work", t0)
        rec.compile_event("fn", 0.01, args={"shapes": ["f32[2]"]})
        rec.counter("mem", {"live_bytes": 123.0})
        path = rec.export(tmp_path / "t.trace.json")
        chrome = json.loads(path.read_text())  # strict JSON
        evs = chrome["traceEvents"]
        # every B has a matching E, LIFO order per thread
        stacks = {}
        for e in evs:
            if e["ph"] == "B":
                stacks.setdefault(e["tid"], []).append(e["name"])
            elif e["ph"] == "E":
                assert stacks[e["tid"]].pop() == e["name"]
        assert all(not s for s in stacks.values())
        # timestamps monotonic per thread, nonnegative microseconds
        by_tid = {}
        for e in evs:
            if e["ph"] == "M":
                continue
            assert e["ts"] >= 0
            assert e["ts"] >= by_tid.get(e["tid"], 0.0)
            by_tid[e["tid"]] = e["ts"]
        # the four tracks are distinct: host spans, device, compile,
        # memory counters
        cats = {e.get("cat") for e in evs if e["ph"] != "M"}
        assert {"span", "device", "compile", "counter"} <= cats
        tids = {e.get("cat"): e["tid"] for e in evs if e["ph"] != "M"}
        assert len(set(tids.values())) == 4
        # X slices carry durations; the counter carries its value
        xs = [e for e in evs if e["ph"] == "X"]
        assert xs and all("dur" in e for e in xs)
        c = next(e for e in evs if e["ph"] == "C")
        assert c["args"]["live_bytes"] == 123.0

    def test_cli_roundtrip_from_runlog(self, tmp_path, capsys):
        from hpc_patterns_tpu.harness.runlog import RunLog

        rec = tracelib.configure(enabled=True)
        m = metricslib.configure(enabled=True)
        with m.span("phase"):
            pass
        log = RunLog(tmp_path / "run.jsonl")
        log.emit(kind="trace", **rec.snapshot())
        out = tmp_path / "out.trace.json"
        assert tracelib.main([str(tmp_path / "run.jsonl"),
                              "-o", str(out)]) == 0
        chrome = json.loads(out.read_text())
        names = [e["name"] for e in chrome["traceEvents"]
                 if e.get("cat") == "span"]
        assert names == ["phase", "phase"]
        capsys.readouterr()

    def test_cli_no_trace_records_errors(self, tmp_path, capsys):
        (tmp_path / "empty.jsonl").write_text(
            '{"kind": "result", "success": true}\n')
        assert tracelib.main([str(tmp_path / "empty.jsonl")]) == 2
        capsys.readouterr()

    def test_cli_multi_file_gets_distinct_pid_lanes(self, tmp_path,
                                                    capsys):
        # two runlogs from two (single-process) runs must NOT collapse
        # onto one pid lane — each source file gets its own, labeled
        from hpc_patterns_tpu.harness.runlog import RunLog

        m = metricslib.configure(enabled=True)
        files = []
        for name in ("a.jsonl", "b.jsonl"):
            rec = tracelib.configure(enabled=True)
            with m.span("phase"):
                pass
            log = RunLog(tmp_path / name)
            log.emit(kind="trace", **rec.snapshot())
            files.append(str(tmp_path / name))
        out = tmp_path / "multi.trace.json"
        assert tracelib.main([*files, "-o", str(out)]) == 0
        capsys.readouterr()
        chrome = json.loads(out.read_text())
        meta = [e for e in chrome["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"]
        assert len({e["pid"] for e in meta}) == 2
        assert {e["args"]["name"] for e in meta} == \
            {"a.jsonl", "b.jsonl"}
        spans = [e for e in chrome["traceEvents"]
                 if e.get("cat") == "span"]
        assert len({e["pid"] for e in spans}) == 2


class TestCompileWatcher:
    def test_forced_recompile_counted_exactly_once(self):
        import jax
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)

        f = jax.jit(lambda x: x * 2)
        with tracelib.compile_watch("unit.f", f, tag="a"):
            f(jnp.ones((3,)))
        first = rec.compile_count
        assert first >= 1  # the explicit hook; the jax.monitoring
        # listener may add backend events on top
        hook_events = [ev for ev in rec.events
                       if ev[1] == "compile" and ev[2] == "unit.f"]
        assert len(hook_events) == 1
        assert hook_events[0][6]["new_variants"] == 1

        # warm call: same shape, NO new compile event
        with tracelib.compile_watch("unit.f", f, tag="a"):
            f(jnp.ones((3,)))
        assert len([ev for ev in rec.events
                    if ev[1] == "compile" and ev[2] == "unit.f"]) == 1

        # forced recompile: new shape grows the cache — exactly one
        # more hook event
        with tracelib.compile_watch("unit.f", f, tag="b"):
            f(jnp.ones((5,)))
        hook_events = [ev for ev in rec.events
                       if ev[1] == "compile" and ev[2] == "unit.f"]
        assert len(hook_events) == 2

    def test_instrument_jit_records_shapes(self):
        import jax
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)
        g = tracelib.instrument_jit(jax.jit(lambda x: x + 1), "unit.g")
        g(jnp.ones((4,)))
        g(jnp.ones((4,)))  # warm
        events = [ev for ev in rec.events
                  if ev[1] == "compile" and ev[2] == "unit.g"]
        assert len(events) == 1
        assert events[0][6]["shapes"] == ["float32[4]"]

    def test_prefill_cache_size_uses_shared_probe(self):
        from hpc_patterns_tpu.models import serving

        n = serving.prefill_cache_size()
        assert n == tracelib.jit_cache_size(serving._prefill_one)
        assert isinstance(n, int)

    def test_strict_probe_raises_on_missing_cache_size(self):
        # the bucket-ladder assertions gate on this count and 0 reads
        # as success — a vanished probe must raise, not return 0
        def not_jitted():
            pass

        assert tracelib.jit_cache_size(not_jitted) == 0
        with pytest.raises(AttributeError):
            tracelib.jit_cache_size(not_jitted, strict=True)

    def test_one_compile_counted_once_in_rollup(self):
        # the same compilation is seen by BOTH the backend listener
        # and the named hook; only the listener bumps the rollup, so
        # report.py's "N compiles" is the true XLA compile count
        import jax
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)
        f = jax.jit(lambda x: x * 3)
        with tracelib.compile_watch("unit.once", f):
            f(jnp.ones((6,)))
        hook = [ev for ev in rec.events
                if ev[1] == "compile" and ev[2] == "unit.once"]
        backend = [ev for ev in rec.events
                   if ev[2] == "xla.backend_compile"]
        assert len(hook) == 1 and len(backend) >= 1
        # rollup == backend events, hook slices are annotations
        assert rec.compile_count == len(backend)

    def test_monitoring_listener_feeds_recorder(self):
        import jax
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)
        jax.jit(lambda x: x - 7)(jnp.ones((2,)))
        backend = [ev for ev in rec.events
                   if ev[2] == "xla.backend_compile"]
        assert backend  # the process-wide listener saw the compile


class TestDisabledPath:
    def test_disabled_span_is_shared_nullcontext(self):
        tracelib.configure(enabled=False)
        m = metricslib.configure(enabled=False)
        # trace off + metrics off: span() must return the SAME object
        # every call — the no-op fast path allocates nothing per span
        assert m.span("x") is m.span("y")

    def test_disabled_compile_watch_is_shared_nullcontext(self):
        tracelib.configure(enabled=False)
        assert tracelib.compile_watch("a", None) is \
            tracelib.compile_watch("b", None)

    def test_disabled_recorder_records_nothing(self):
        rec = tracelib.configure(enabled=False)
        m = metricslib.configure(enabled=True)  # metrics alone
        with m.span("s"):
            pass
        assert rec.n_events == 0
        assert tracelib.active() is None

    def test_trace_without_metrics_records_events_not_histograms(self):
        rec = tracelib.configure(enabled=True)
        m = metricslib.configure(enabled=False)
        with m.span("only_traced"):
            pass
        assert m.snapshot()["histograms"] == {}
        assert any(ev[2] == "only_traced" for ev in rec.events)

    def test_configure_detaches_sink(self):
        tracelib.configure(enabled=True)
        assert metricslib._trace_sink is not None
        tracelib.configure(enabled=False)
        assert metricslib._trace_sink is None


class TestRunInstrumented:
    def test_trace_flag_appends_kind_trace_record(self, tmp_path):
        import argparse

        from hpc_patterns_tpu.apps import common
        from hpc_patterns_tpu.harness.runlog import RunLog

        path = tmp_path / "app.jsonl"
        args = argparse.Namespace(metrics=False, trace=True,
                                  trace_capacity=None, log=str(path))

        def fake_app(a):
            with metricslib.span("app.phase"):
                pass
            RunLog(a.log).emit(kind="result", name="app", success=True)
            return 0

        assert common.run_instrumented(fake_app, args) == 0
        records = [json.loads(l)
                   for l in path.read_text().splitlines()]
        assert [r["kind"] for r in records] == ["result", "trace"]
        trace_rec = records[1]
        assert trace_rec["by_cat"].get("span", 0) >= 2
        # the record is itself exportable
        chrome = tracelib.chrome_from_snapshots([trace_rec])
        assert any(e["name"] == "app.phase"
                   for e in chrome["traceEvents"])

    def test_no_flags_appends_nothing(self, tmp_path):
        import argparse

        from hpc_patterns_tpu.apps import common
        from hpc_patterns_tpu.harness.runlog import RunLog

        path = tmp_path / "app.jsonl"
        args = argparse.Namespace(metrics=False, trace=False,
                                  trace_capacity=None, log=str(path))

        def fake_app(a):
            RunLog(a.log).emit(kind="result", name="app", success=True)
            return 0

        assert common.run_instrumented(fake_app, args) == 0
        kinds = [json.loads(l)["kind"]
                 for l in path.read_text().splitlines()]
        assert kinds == ["result"]


class TestDistributedHandoff:
    """The per-rank capture protocol (rung 4's capture half): snapshots
    carry process identity + dual clock anchors + sync anchors, and a
    traced child under HPCPAT_TRACE_DIR hands its ring to the launcher
    as rank<id>.trace.json (the merge half lives in test_collect.py)."""

    def test_snapshot_carries_process_and_dual_clock_anchors(self):
        rec = TraceRecorder(capacity=8)
        snap = rec.snapshot()
        proc = snap["process"]
        assert proc["process_id"] == 0 and proc["num_processes"] == 1
        c = snap["clock"]
        assert c["mono1"] >= c["mono0"] and c["wall1"] >= c["wall0"]
        # the two anchor pairs agree on the offset (same clocks here)
        assert (c["wall1"] - c["mono1"]) == pytest.approx(
            c["wall0"] - c["mono0"], abs=0.05)

    def test_snapshot_reads_launcher_env_protocol(self, monkeypatch):
        monkeypatch.setenv("HPCPAT_PROCESS_ID", "3")
        monkeypatch.setenv("HPCPAT_NUM_PROCESSES", "4")
        monkeypatch.setenv("HPCPAT_SLICE_GROUPING", "process:0,0,1,1")
        snap = TraceRecorder(capacity=8).snapshot()
        assert snap["process"] == {"process_id": 3, "num_processes": 4,
                                   "slice_id": 1}

    def test_mark_sync_anchors_survive_eviction(self):
        rec = TraceRecorder(capacity=2)
        rec.mark_sync("make_communicator")
        for i in range(10):  # overflow the ring
            rec.span_begin(f"s{i}", {})
            rec.span_end(f"s{i}")
        snap = rec.snapshot()
        assert len(snap["sync"]) == 1
        assert snap["sync"][0]["name"] == "make_communicator"
        assert snap["sync"][0]["mono"] <= snap["clock"]["mono1"]

    def test_write_rank_snapshot(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HPCPAT_PROCESS_ID", "1")
        monkeypatch.setenv("HPCPAT_NUM_PROCESSES", "2")
        rec = TraceRecorder(capacity=8)
        rec.span_begin("x", {})
        rec.span_end("x")
        path = tracelib.write_rank_snapshot(rec, tmp_path)
        assert path == tmp_path / "rank00001.trace.json"
        snap = json.loads(path.read_text())
        assert snap["kind"] == "trace"
        assert snap["process"]["process_id"] == 1
        assert len(snap["events"]) == 2

    def test_run_instrumented_hands_off_under_env(self, tmp_path,
                                                  monkeypatch):
        import argparse

        from hpc_patterns_tpu.apps import common

        monkeypatch.setenv("HPCPAT_TRACE_DIR", str(tmp_path))
        args = argparse.Namespace(metrics=False, trace=True,
                                  trace_capacity=None, log=None)
        assert common.run_instrumented(lambda a: 0, args) == 0
        files = list(tmp_path.glob("rank*.trace.json"))
        assert len(files) == 1

    def test_no_handoff_without_trace_flag(self, tmp_path, monkeypatch):
        import argparse

        from hpc_patterns_tpu.apps import common

        monkeypatch.setenv("HPCPAT_TRACE_DIR", str(tmp_path))
        args = argparse.Namespace(metrics=False, trace=False,
                                  trace_capacity=None, log=None)
        assert common.run_instrumented(lambda a: 0, args) == 0
        assert list(tmp_path.glob("rank*.trace.json")) == []


class TestMemorySampling:
    def test_sample_memory_records_counter(self):
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)
        keep = jnp.ones((128,))  # noqa: F841 — held live on purpose
        sample = rec.sample_memory()
        assert sample is not None
        assert sample["live_bytes"] >= keep.nbytes
        assert rec.peak_live_bytes >= keep.nbytes
        counters = [ev for ev in rec.events if ev[0] == "C"]
        assert counters

    def test_record_executable_memory(self):
        import jax
        import jax.numpy as jnp

        rec = tracelib.configure(enabled=True)
        compiled = jax.jit(lambda x: x @ x).lower(
            jnp.ones((8, 8))).compile()
        vals = tracelib.record_executable_memory("unit.mm", compiled)
        if vals is None:
            pytest.skip("backend has no memory_analysis")
        assert any(ev[2] == "exec_mem.unit.mm" for ev in rec.events)


class TestMaybeTraceRestoration:
    def test_maybe_trace_restores_on_raise(self, tmp_path):
        # the satellite guarantee: an exception inside the traced
        # region must not leave the global registry permanently
        # mirroring spans into TraceAnnotations
        from hpc_patterns_tpu.harness.profiling import maybe_trace

        m = metricslib.configure(enabled=False)
        assert m.mirror_traces is False
        with pytest.raises(RuntimeError):
            with maybe_trace(True, str(tmp_path / "tr")):
                assert m.mirror_traces is True
                raise RuntimeError("boom inside traced region")
        assert m.mirror_traces is False

    def test_maybe_trace_restores_preexisting_true(self, tmp_path):
        from hpc_patterns_tpu.harness.profiling import maybe_trace

        m = metricslib.configure(enabled=False)
        m.mirror_traces = True  # e.g. an enclosing trace
        with maybe_trace(True, str(tmp_path / "tr")):
            pass
        assert m.mirror_traces is True
