"""The in-process serving plane: router + N engine replicas.

One process, N :class:`~hpc_patterns_tpu.models.serving.EngineCore`
replicas (optionally pinned to distinct devices), one front-end
:class:`ServingPlane` routing an open-loop request stream across them.
This is the plane's ORACLE tier: everything runs where the tests can
see it, on the 8-device CPU mesh, and the disaggregation claim — a
request routed prefill → KV-migration → decode emits byte-identical
tokens to the same request on a colocated single engine, greedy and
sampled — is asserted here (tests/test_serving_plane.py) before the
cross-process plane (``serving_plane/service.py``) is believed.

Placement policies (``policy=``):

- ``least_loaded``  — the replica with the most free pages (ties:
  shallowest queue, then submission order) among those that can EVER
  fit the request;
- ``round_robin``   — cycle through the eligible replicas;
- ``prefill_decode``— role-aware: fresh requests go to prefill-role
  replicas (least-loaded among them); decode-role replicas receive
  work only through KV migration. This IS the disaggregated mode —
  constructing a plane with any ``role="prefill"`` replica selects it
  implicitly.

The migration pipeline per plane round (the overlap discipline):

1. each prefill replica runs an admission-only round
   (``service_round(decode=False)``): bucket-padded prefill + first
   token, no decode chunk ever;
2. rows whose first token resolved are EXPORTED and their transfer is
   DISPATCHED toward the chosen decode replica over the plane's
   transport tier (``migration=`` kwarg, see MIGRATION_TRANSPORTS:
   the fused remote-DMA pair of ``comm/migration_dma.py``, the
   ``migration.migrate_pages`` async ``device_put``, or the socket
   codec's byte round-trip), before that replica's decode chunk of
   the round;
3. the decode replica's round dispatches its chunk FIRST, then
   installs arrived bundles BEHIND it (``service_round``'s
   ``pre_collect`` hook → ``install_migration``), exactly like
   round-6 overlapped admission — the handoff hides behind compute;
4. after the chunk readback the install is confirmed
   (``block_until_ready`` on the seeded cursors — completion
   measurement, the ``_ready_in_span`` contract) and the migration
   window closes.

Every migration is fingerprinted into the collective-schedule chain
(``kv_migration`` with the plane-assigned ``seq``) and drawn as a
device-track window named ``plane.kv_migration`` — under ``--trace``
the cross-rank merge threads flow arrows through matched windows and
the schedule verifier catches router/replica desyncs (in-process both
ends share one chain; the launched plane records one chain per side).

``kv_migration_overlap_frac``: Σ over migrations of the window time
spent under an in-flight decode chunk on the DESTINATION replica,
over Σ window time — the measured proof that the handoff hid behind
compute (gated via ``detail.kv_migration_overlap_frac``).
``dma_migration_overlap_frac`` is the same ratio restricted to
bundles that actually rode the DMA tier (None when none did — a
fallback can't impersonate the kernel path), and
``migration_bytes_per_round`` pins the dataplane pressure the tier
carries; both are regress-gated (``harness/regress.py``).
"""

from __future__ import annotations

import os
import time
import warnings
from collections import Counter, deque
from contextlib import nullcontext

import numpy as np

from hpc_patterns_tpu.analysis import runtime as analysis_runtime
from hpc_patterns_tpu.comm import migration_dma
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import reqtrace as reqtracelib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.models.serving import EngineCore, fit_bucket_ladder
from hpc_patterns_tpu.serving_plane.migration import (
    bundle_from_wire,
    bundle_to_wire,
    migrate_pages,
)
from hpc_patterns_tpu.serving_plane.service import migration_track

ROLES = ("both", "prefill", "decode")

#: KV-handoff transport tiers, fastest first — the fallback ladder
#: :meth:`ServingPlane._resolve_transport` walks LOUDLY (a warning +
#: a ``plane_transport_fallback`` emit per distinct reason):
#: ``dma`` = the paired remote-DMA kernel (comm/migration_dma.py,
#: chips must be ICI-reachable), ``device_put`` = host-staged
#: cross-device copy (today's default; a device-less pair degrades
#: further to the in-place passthrough, recorded as ``local``),
#: ``wire`` = the socket codec's byte round-trip (the DCN analog).
MIGRATION_TRANSPORTS = ("dma", "device_put", "wire")


class Replica:
    """One engine replica in the plane. ``role``: ``"both"`` (admit +
    decode — the homogeneous plane), ``"prefill"`` (admission-prefill
    only; every row leaves via KV migration), or ``"decode"``
    (receives work only through migration — plus resumes the router
    re-queues onto it). ``device``: pin the engine's dispatches to one
    device (``jax.default_device`` around every engine call), so
    replicas model distinct chips and migration is a real
    cross-device copy; None = wherever the engine's arrays live."""

    def __init__(self, engine: EngineCore, *, name: str | None = None,
                 role: str = "both", device=None):
        if role not in ROLES:
            raise ValueError(f"role {role!r} not in {ROLES}")
        if engine.draft_params is not None and role != "both":
            raise ValueError(
                "draft-assisted engines cannot take a migration role "
                "(the draft cache's row state does not migrate)")
        self.engine = engine
        self.role = role
        self.device = device
        self.name = name or role
        self.alive = True
        #: bundles transferred toward this replica, awaiting install
        self.pending_migrations: list = []
        #: plane-assigned ordinal (set at plane construction / scale-up)
        #: — the identity ``die:replica=N`` chaos addresses in-process,
        #: mirroring the launched plane where replica N is rank N
        self.index = -1
        #: replica-local round counter: the chaos ``replica_round``
        #: site's index, and the autoscaler's per-replica clock
        self.rounds = 0
        #: a draining replica serves what it holds but receives no new
        #: routing and no migrations — the voluntary scale-down state
        self.draining = False

    def device_ctx(self):
        if self.device is None:
            return nullcontext()
        import jax

        return jax.default_device(self.device)

    @property
    def can_prefill(self) -> bool:
        return self.role in ("both", "prefill")

    @property
    def can_decode(self) -> bool:
        return self.role in ("both", "decode")


def _eligible(plane: "ServingPlane", prompt_len: int,
              max_new: int) -> list[Replica]:
    return [r for r in plane.replicas
            if r.alive and not r.draining and r.can_prefill
            and r.engine.would_fit(prompt_len, max_new)]


def _least_loaded(plane, prompt_len, max_new):
    cand = _eligible(plane, prompt_len, max_new)
    if not cand:
        return None
    return max(cand, key=lambda r: (r.engine.free_page_count,
                                    -r.engine.queue_depth,
                                    -plane.replicas.index(r)))


def _round_robin(plane, prompt_len, max_new):
    cand = _eligible(plane, prompt_len, max_new)
    if not cand:
        return None
    r = cand[plane._rr % len(cand)]
    plane._rr += 1
    return r


def _weighted(plane, prompt_len, max_new):
    # autofit's fitted capacity shares: route toward the replica with
    # the most fitted weight per unit of present pressure. A replica
    # the fit never saw gets weight 1.0 (neutral), so a fresh spin-up
    # is routable immediately.
    cand = _eligible(plane, prompt_len, max_new)
    if not cand:
        return None
    return max(cand, key=lambda r: (
        plane.placement_weights.get(r.name, 1.0)
        / (1.0 + r.engine.queue_depth),
        r.engine.free_page_count,
        -plane.replicas.index(r)))


PLACEMENT_POLICIES = {
    "least_loaded": _least_loaded,
    "round_robin": _round_robin,
    # role-awareness is structural: _eligible already restricts to
    # prefill-capable replicas, so in a disaggregated plane the
    # least-loaded pick IS the prefill-decode policy
    "prefill_decode": _least_loaded,
    # per-replica weights fitted from a prior run's busy/queue rollups
    # (harness/autofit.py) — plane.placement_weights holds them
    "weighted": _weighted,
}


class ServingPlane:
    """Route a request stream across N replicas (see module docstring).

    ``slo``: ``{priority: harness.slo.SLOTarget}`` — after each
    :meth:`run`, ``last_slo`` holds the PLANE-level attainment rollup
    (goodput next to raw tok/s over the router's own stats table,
    which spans replicas — a migrated request is judged once, end to
    end). Per-replica queue depth / free pages land as
    ``plane.<name>.queue_depth`` / ``.free_pages`` gauges each round.
    """

    def __init__(self, replicas, *, policy: str = "least_loaded",
                 slo: dict | None = None, emit=None,
                 placement_weights: dict | None = None,
                 migration: str = "device_put"):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("need at least one replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        if policy not in PLACEMENT_POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} "
                f"(known: {', '.join(sorted(PLACEMENT_POLICIES))})")
        self.policy_name = policy
        self.policy = PLACEMENT_POLICIES[policy]
        #: fitted per-replica capacity shares ({name: weight}), read by
        #: the "weighted" policy; empty = neutral
        self.placement_weights = {
            str(k): float(v)
            for k, v in (placement_weights or {}).items()}
        self.disaggregated = any(r.role != "both" for r in self.replicas)
        if self.disaggregated:
            if not any(r.can_prefill for r in self.replicas):
                raise ValueError("disaggregated plane has no "
                                 "prefill-capable replica")
            if not any(r.can_decode for r in self.replicas):
                raise ValueError("disaggregated plane has no "
                                 "decode-capable replica")
        self._validate_engines()
        # decode-role replicas track chunk windows: the migration-
        # overlap fraction is measured against them
        for r in self.replicas:
            if r.can_decode:
                r.engine.track_chunk_windows = True
        for i, r in enumerate(self.replicas):
            r.index = i
        self.slo = slo
        self._emit = emit or (lambda **kw: None)
        self.stats: dict[int, dict] = {}
        self.finished: dict[int, np.ndarray] = {}
        self._next_rid = 0
        self._assignment: dict[int, Replica] = {}
        self._rr = 0
        self._mig_seq = 0
        self.migrations = 0
        if migration not in MIGRATION_TRANSPORTS:
            raise ValueError(
                f"unknown migration transport {migration!r} "
                f"(known: {', '.join(MIGRATION_TRANSPORTS)})")
        #: requested KV-handoff transport tier (module constant
        #: MIGRATION_TRANSPORTS); per-bundle resolution may fall back
        #: down the ladder — loudly — when a pair can't serve it
        self.migration = migration
        #: bundles dispatched per RESOLVED transport ("dma" /
        #: "device_put" / "local" / "wire") — what the oracle tests
        #: assert so a silent fallback can't impersonate the DMA tier
        self.migration_transports: Counter = Counter()
        #: distinct (requested, actual, reason) fallbacks already
        #: warned about — loud once, not once per bundle
        self._transport_warned: set = set()
        #: Σ payload bytes over dispatched bundles (all transports) —
        #: the numerator of ``migration_bytes_per_round``
        self.migration_bytes = 0
        #: open migration windows: seq -> (t_trace_dispatch, t_host0)
        self._mig_open: dict[int, tuple[float, float]] = {}
        self._mig_overlap_s = 0.0
        self._mig_total_s = 0.0
        # the DMA tier's own overlap ledger (subset of the above):
        # ``dma_migration_overlap_frac`` gates on it, so a plane that
        # silently fell back to device_put reports None, not a number
        # measured on the wrong transport
        self._dma_overlap_s = 0.0
        self._dma_total_s = 0.0
        self._serve_s = 0.0
        #: total plane rounds served (unconditional — unlike
        #: ``_plane_rounds``, which only counts SLO-judged rounds):
        #: the denominator of ``migration_bytes_per_round``
        self.rounds_total = 0
        self.last_slo: dict | None = None
        self.last_kv_migration_overlap_frac: float | None = None
        self.last_dma_migration_overlap_frac: float | None = None
        #: original submit kwargs per request — what replica-death
        #: recovery needs (the elastic plane rebuilds a queued request
        #: or a resume from them; the static plane's shed path only
        #: reads them for accounting)
        self._requests: dict[int, dict] = {}
        #: replicas lost to chaos (by name, death order)
        self.deaths: list[str] = []
        #: requests shed BECAUSE their replica died (the static
        #: plane's degraded mode — the number the elastic comparison
        #: exists to drive to zero)
        self.shed_on_death = 0
        #: Σ over plane rounds of live (serving) replica count — the
        #: denominator of ``goodput_per_replica_round``: the gated
        #: efficiency metric that rewards holding the SLO with FEWER
        #: replica-rounds, not just holding it
        self.replica_rounds = 0
        #: the sliding-window SLO-attainment signal (satellite of the
        #: autofit round): every request judged as it RESOLVES, the
        #: window fraction emitted per plane round as a gauge, a trace
        #: counter, and a ``kind=plane_attainment`` record — the one
        #: signal the in-process autoscaler, the launched router, and
        #: the offline autofit threshold fitter all consume
        self.attain_window = slolib.AttainmentWindow()
        self._plane_rounds = 0
        self._attain_emitted = (0, 0)  # (judged, attained) last round

    # -- construction checks ----------------------------------------------

    def _validate_engines(self) -> None:
        """Replicas must agree on everything a request's tokens depend
        on, or routing would change outputs: sampling mode (greedy /
        top_k are compile-time constants of the chunk step), eos, the
        per-request key derivation (same seed => same request_key on
        every replica AND on the colocated oracle), and — for planes
        that migrate — the page/pool layout."""
        e0 = self.replicas[0].engine
        for r in self.replicas[1:]:
            e = r.engine
            for attr in ("greedy", "top_k", "temperature", "eos_id"):
                if getattr(e, attr) != getattr(e0, attr):
                    raise ValueError(
                        f"replica {r.name!r} disagrees on {attr}: "
                        f"{getattr(e, attr)} vs {getattr(e0, attr)} — "
                        "routing would change outputs")
            if not e0.greedy and not np.array_equal(
                    np.asarray(e._req_key_base),
                    np.asarray(e0._req_key_base)):
                raise ValueError(
                    f"replica {r.name!r} was built with a different "
                    "seed: request_key(sid) would differ by placement")
        if self.disaggregated:
            for r in self.replicas:
                e = r.engine
                if e.page_size != e0.page_size or e.cfg != e0.cfg:
                    raise ValueError(
                        f"replica {r.name!r}: migration needs identical "
                        "model config and page_size across replicas")

    @classmethod
    def from_fitted(cls, replicas, fitted, *, slo: dict | None = None,
                    emit=None, **kw):
        """Build a plane from an autofit ``FittedConfig``: the fitted
        ``placement`` section picks the policy (``weighted`` routes by
        the fitted per-replica capacity shares) — a config with no
        placement signal yields the default least-loaded plane. An
        explicit ``policy=`` kwarg wins over the fit."""
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fitted = autofitlib.validate_fitted(fitted)
        section = fitted.get("placement") or {}
        if "policy" not in kw and section.get("policy"):
            kw["policy"] = section["policy"]
        if "placement_weights" not in kw and section.get("weights"):
            kw["placement_weights"] = section["weights"]
        return cls(replicas, slo=slo, emit=emit, **kw)

    # -- submission (the router transport) ---------------------------------

    @staticmethod
    def fit_buckets(lengths, max_rungs: int, *, max_len=None):
        """Ladder autotuning hook: fit the prompt-length bucket ladder
        to an observed/loadgen length sample before building replica
        engines (``serving.fit_bucket_ladder``)."""
        return fit_bucket_ladder(lengths, max_rungs, max_len=max_len)

    def submit(self, prompt, max_new: int, *, priority: int = 0,
               deadline_s: float | None = None,
               temperature: float | None = None, key=None,
               resume_prefix=None) -> int:
        """Route one request: the placement policy picks a replica NOW
        (load is what the policy reads), the request enters that
        replica's queue under a plane-global id, and the plane's stats
        row opens. Raises when no live replica could ever fit it."""
        prompt = np.asarray(prompt, np.int32)
        rid = self._next_rid
        self._next_rid += 1
        target = self.policy(self, int(prompt.size), int(max_new))
        if target is None:
            raise ValueError(
                f"no live replica can serve prompt {prompt.size} + "
                f"budget {max_new} (table width / ladder / max_seq)")
        if target.role == "prefill":
            # the row will LEAVE via migration: some decode-capable
            # replica must be able to hold the donor's pages, or the
            # request would park on the prefill replica forever and
            # surface later as a mid-stream plane deadlock instead of
            # a submit-time rejection
            need = target.engine._pages_for(int(prompt.size),
                                            int(max_new))
            if not any(r.alive and r.can_decode
                       and need <= min(r.engine.pages_per_seq,
                                       r.engine.pool_pages)
                       for r in self.replicas):
                raise ValueError(
                    f"no decode-capable replica can hold the "
                    f"{need}-page migrated row of prompt "
                    f"{prompt.size} + budget {max_new}")
        target.engine.submit(
            prompt, max_new, seq_id=rid, priority=priority,
            deadline_s=deadline_s, temperature=temperature, key=key,
            resume_prefix=resume_prefix)
        self._requests[rid] = {
            "prompt": prompt, "max_new": int(max_new),
            "priority": int(priority), "deadline_s": deadline_s,
            "temperature": temperature, "key": key,
        }
        now = time.perf_counter()
        self.stats[rid] = {
            "priority": int(priority), "t_submit": now, "t_first": None,
            "t_finish": None, "tokens": 0, "outcome": None,
            "preemptions": 0, "replica": target.name,
        }
        self._assignment[rid] = target
        self._emit(kind="plane_route", seq_id=rid, replica=target.name,
                   policy=self.policy_name, prompt_len=int(prompt.size),
                   budget=int(max_new), priority=int(priority))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.routed").inc()
            m.gauge(f"plane.{target.name}.queue_depth").set(
                target.engine.queue_depth)
        return rid

    # -- migration pipeline ------------------------------------------------

    def _reserved_pages(self, r: Replica) -> int:
        return sum(b.n_pages for b in r.pending_migrations)

    def _pick_target(self, n_pages: int, src: Replica) -> Replica | None:
        """The decode replica this bundle should land on: alive,
        decode-capable, not the donor, with capacity left AFTER the
        bundles already in flight toward it (reservations — two
        exports must not race one free slot). Least-loaded first."""
        cand = []
        for r in self.replicas:
            if not (r.alive and r.can_decode) or r is src \
                    or r.draining:
                continue
            e = r.engine
            free_slots = (sum(1 for s in e._slots if not s.active)
                          - len(r.pending_migrations))
            if free_slots < 1:
                continue
            if (self._reserved_pages(r) + n_pages > e.free_page_count
                    or n_pages > e.pages_per_seq):
                continue
            cand.append(r)
        if not cand:
            return None
        return max(cand, key=lambda r: (
            r.engine.free_page_count - self._reserved_pages(r),
            -r.engine.queue_depth))

    def _export_ready(self, src: Replica) -> int:
        """Export every migration-ready row of a prefill replica whose
        transfer has a destination with capacity, and DISPATCH the
        transfer immediately — before the destination's decode chunk
        of this round, so the copy flies under the chunk. A row with
        no destination stays parked on the donor (its pages keep their
        state; nothing is dropped)."""
        n = 0
        for slot in src.engine.exportable_slots():
            need = len(src.engine._slots[slot].pages)
            dst = self._pick_target(need, src)
            if dst is None:
                # no capacity for THIS row yet — smaller rows behind
                # it may still fit somewhere; a head-of-line break
                # here would starve them behind one big parked row
                continue
            self._dispatch_migration(src, slot, dst)
            n += 1
        return n

    def _transport_fallback(self, requested: str, actual: str,
                            reason: str) -> None:
        """The LOUD half of the fallback ladder: a warning (once per
        distinct reason), an emit record, and a counter — a plane
        asked for DMA must never quietly serve on a slower tier."""
        key = (requested, actual, reason)
        if key not in self._transport_warned:
            self._transport_warned.add(key)
            warnings.warn(
                f"plane migration transport fell back "
                f"{requested} -> {actual}: {reason}",
                RuntimeWarning, stacklevel=3)
        self._emit(kind="plane_transport_fallback", requested=requested,
                   actual=actual, reason=reason)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.transport_fallbacks").inc()

    def _resolve_transport(self, src: Replica,
                           dst: Replica) -> tuple[str, str]:
        """(transport to attempt, fallback reason so far) for one
        (src, dst) pair under the plane's requested tier. ``dma``
        demands an ICI-reachable device pair
        (:func:`migration_dma.dma_reachable`); the per-bundle VMEM
        gate inside ``send_migration`` may still drop an oversized
        slab to ``device_put`` at dispatch time."""
        if self.migration == "dma":
            ok, reason = migration_dma.dma_reachable(src.device,
                                                     dst.device)
            if ok:
                return "dma", ""
            self._transport_fallback("dma", "device_put", reason)
            return "device_put", reason
        return self.migration, ""

    def _dispatch_migration(self, src: Replica, slot: int,
                            dst: Replica) -> None:
        """Export + transfer dispatch (dispatch-only: the gather and
        the cross-device copy enqueue async; the deliberate cursor
        snapshot inside export_migration is the chunk-boundary resume
        contract). Opens the migration's device-track window and
        fingerprints it into the schedule chain — with the RESOLVED
        transport as the entry's ``algorithm``, so a fallback is
        visible in the verifier's chain, not just the logs."""
        bundle = src.engine.export_migration(slot)
        bundle.seq = self._mig_seq
        self._mig_seq += 1
        rtr = reqtracelib.active()
        if rtr is not None:
            # the engine opened `migrating` at export; the router owns
            # the plane seq — tag the open segment so the cross-rank
            # merge can thread the request lane into THIS migration's
            # device window (harness/collect.py flow arrows)
            rtr.annotate_open(bundle.seq_id, seq=bundle.seq)
        self.migration_bytes += sum(
            int(a.nbytes) for arrs in bundle.pages_payload.values()
            for a in arrs)
        transport, _ = self._resolve_transport(src, dst)
        if transport == "dma":
            try:
                bundle = migration_dma.send_migration(
                    bundle, src.device, dst.device)
            except migration_dma.MigrationDmaError as e:
                self._transport_fallback("dma", "device_put", str(e))
                transport = "device_put"
        if transport == "device_put":
            # dst.device None degrades further to the in-place
            # passthrough; the bundle then says "local" truthfully
            bundle = migrate_pages(bundle, dst.device)
        elif transport == "wire":
            # the byte codec round-trip IS the transport: the installed
            # payload crossed the same encode/decode the socket plane
            # ships, so the oracle covers the codec end to end
            w = bundle_to_wire(bundle)
            w["transport"] = "wire"
            bundle = bundle_from_wire(w)
        self.migration_transports[bundle.transport] += 1
        ps = self.stats.get(bundle.seq_id)
        if ps is not None and ps["t_first"] is None:
            ps["t_first"] = bundle.t_first
        rec = tracelib.active()
        t_disp = 0.0
        if rec is not None:
            t_disp = rec.mark_dispatch(
                "plane.kv_migration",
                {"seq": bundle.seq, "src": src.name, "dst": dst.name,
                 "pages": bundle.n_pages, "seq_id": bundle.seq_id},
                track=migration_track(bundle.seq))
        if rec is not None \
                or analysis_runtime.ENV_TRACE_DIR in os.environ:
            kdt = str(bundle.pages_payload["k"][0].dtype)
            analysis_runtime.record_collective(
                "kv_migration", bundle.seq,
                shape=(bundle.n_pages, bundle.page_size), dtype=kdt,
                axis="plane", algorithm=bundle.transport)
        self._mig_open[bundle.seq] = (t_disp, time.perf_counter())
        dst.pending_migrations.append(bundle)
        self._emit(kind="plane_migrate", seq=bundle.seq,
                   seq_id=bundle.seq_id, src=src.name, dst=dst.name,
                   pages=bundle.n_pages)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.migrations").inc()

    def _install_pending(self, r: Replica, overlapped: bool) -> list:
        """The decode side of the handoff, run from ``service_round``'s
        ``pre_collect`` hook — BEHIND the in-flight chunk when there is
        one (``overlapped``). Installs every arrived bundle the engine
        can take, in arrival order."""
        installed = []
        while r.pending_migrations and r.engine.migration_admissible(
                r.pending_migrations[0].n_pages):
            b = r.pending_migrations.pop(0)
            if b.transport == "dma":
                # metadata-only landing check (device residency /
                # chunk-shape sanity) — raises MigrationDmaError
                # rather than scattering a misdelivered payload
                migration_dma.recv_migration(b, r.device)
            r.engine.install_migration(b)
            installed.append((b, overlapped))
            self.migrations += 1
            self.stats.setdefault(b.seq_id, {})["replica"] = r.name
        if r.pending_migrations:
            # a bundle is parked for lack of pages: on a tiered-memory
            # replica (EngineCore(residency=...)) ask the manager to
            # evict for it at this round's balance point — the install
            # retries next round against the freed arena
            r.engine.request_pages(r.pending_migrations[0].n_pages)
        return installed

    def _complete_migrations(self, r: Replica, installed: list) -> None:
        """Close the installed bundles' windows: the install's device
        work resolved (block on the last seeded array — completion
        measurement, the _ready_in_span contract), stamp the overlap
        against the destination's chunk windows, and mark the
        device-track completion the cross-rank merge threads its flow
        arrows through."""
        import jax

        # jaxlint: disable=host-sync-in-dispatch — completion
        # measurement at the round boundary (the chunk readback already
        # happened); the window must not close before the install's
        # device work it claims to cover has finished
        jax.block_until_ready(r.engine.temps)
        t_done = time.perf_counter()
        rec = tracelib.active()
        # prune chunk windows no open migration can still intersect
        # (the installed bundles are still in _mig_open here — they
        # pop below): without this, every completion rescans up to
        # the deque's full history for intersections that are zero by
        # construction (windows that ended before any open migration
        # began)
        floor = min((t0 for _, t0 in self._mig_open.values()),
                    default=t_done)
        while r.engine.chunk_windows \
                and r.engine.chunk_windows[0][1] < floor:
            r.engine.chunk_windows.popleft()
        windows = list(r.engine.chunk_windows)
        for bundle, overlapped in installed:
            t_disp, t0 = self._mig_open.pop(bundle.seq, (0.0, t_done))
            span = max(t_done - t0, 1e-9)
            under_chunk = sum(
                max(0.0, min(t_done, e) - max(t0, s))
                for s, e in windows)
            self._mig_total_s += span
            self._mig_overlap_s += min(under_chunk, span)
            if bundle.transport == "dma":
                self._dma_total_s += span
                self._dma_overlap_s += min(under_chunk, span)
            if rec is not None and t_disp:
                rec.mark_complete(
                    "plane.kv_migration", t_disp,
                    {"seq": bundle.seq, "dst": r.name,
                     "overlapped": overlapped},
                    track=migration_track(bundle.seq))

    # -- result collection -------------------------------------------------

    def _collect_finished(self, r: Replica) -> int:
        """Pull finished/shed rows out of a replica into the plane's
        tables, merging the replica-side timing into the plane's
        end-to-end stats row (a migrated request keeps the t_first its
        user actually saw on the prefill replica)."""
        eng = r.engine
        n = 0
        for sid in list(eng.finished):
            ps = self.stats.get(sid)
            if ps is None or ps.get("outcome") is not None:
                continue
            toks = eng.finished.pop(sid)
            es = eng.stats.get(sid, {})
            self.finished[sid] = toks
            if ps["t_first"] is None:
                ps["t_first"] = es.get("t_first")
            ps["t_finish"] = es.get("t_finish", time.perf_counter())
            ps["tokens"] = int(es.get("tokens") or len(toks))
            ps["outcome"] = es.get("outcome") or "ok"
            ps["preemptions"] = int(es.get("preemptions") or 0)
            ps["replica"] = r.name
            self._judge_window(ps)
            # the recovery record resolves with the request (death
            # recovery only ever reads UNRESOLVED rows): a long-lived
            # plane must not grow one prompt array per served request
            self._requests.pop(sid, None)
            n += 1
        return n

    def _judge_window(self, ps: dict) -> None:
        """Fold one RESOLVED stats row (served or shed) into the
        sliding attainment window — at resolution time, so the window
        tracks recent service quality rather than the end-of-run
        average."""
        if self.slo is None:
            return
        target = self.slo.get(int(ps.get("priority") or 0),
                              slolib.SLOTarget())
        self.attain_window.judge(ps, target)

    def _emit_attainment(self) -> None:
        """The per-round sliding-window SLO-attainment gauge: one
        number in three mediums (metrics gauge, trace counter, RunLog
        record), emitted from the SAME window the elastic controller
        reads — so autofit's offline threshold replay sees exactly the
        trajectory the live autoscaler saw."""
        if self.slo is None:
            return
        self._plane_rounds += 1
        snap = self.attain_window.snapshot()
        judged, attained = (self.attain_window.judged,
                            self.attain_window.attained)
        judged_round = judged - self._attain_emitted[0]
        attained_round = attained - self._attain_emitted[1]
        self._attain_emitted = (judged, attained)
        queued = sum(r.engine.queue_depth for r in self.replicas
                     if r.alive)
        active = sum(1 for r in self.replicas if r.alive
                     for s in r.engine._slots if s.active)
        live = sum(1 for r in self.replicas
                   if r.alive and not r.draining)
        m = metricslib.get_metrics()
        if m.enabled and snap["overall"] is not None:
            m.gauge("plane.attainment").set(snap["overall"])
            for prio, frac in snap["per_class"].items():
                m.gauge(f"plane.attainment.p{prio}").set(frac)
        rec = tracelib.active()
        if rec is not None and snap["overall"] is not None:
            rec.counter("plane.attainment", {
                "overall": snap["overall"],
                **{f"p{prio}": frac
                   for prio, frac in snap["per_class"].items()}})
        self._emit(kind="plane_attainment", round=self._plane_rounds,
                   overall=snap["overall"],
                   per_class={str(p): f
                              for p, f in snap["per_class"].items()},
                   window_n=snap["n"], judged_round=judged_round,
                   attained_round=attained_round, queued=queued,
                   active=active, replicas=live)

    def _update_gauges(self) -> None:
        m = metricslib.get_metrics()
        if not m.enabled:
            return
        for r in self.replicas:
            m.gauge(f"plane.{r.name}.queue_depth").set(
                r.engine.queue_depth)
            m.gauge(f"plane.{r.name}.free_pages").set(
                r.engine.free_page_count)

    # -- replica-level chaos + death recovery ------------------------------

    def _probe_replica_chaos(self, r: Replica) -> bool:
        """The ``replica_round`` chaos site for the IN-PROCESS plane,
        probed once per replica per plane round against the replica's
        ORDINAL (``die:replica=N`` addresses the same identity the
        launched plane's rank-N process has). Executed here rather
        than through ``maybe_inject`` because every in-process replica
        shares one OS process — a literal SIGKILL would take the whole
        plane down instead of one replica. Stalls sleep their
        (deterministic) delay; ``die`` marks the replica dead through
        :meth:`_kill_replica`. Returns True when the replica died."""
        for f in chaoslib.matching("replica_round", r.rounds, r.index):
            if f.kind == "die":
                chaoslib.record_injection("replica_round", r.rounds,
                                          "die", rank=r.index)
                self._kill_replica(r)
                return True
            delay = f.delay_at("replica_round", r.rounds)
            chaoslib.record_injection("replica_round", r.rounds,
                                      f.kind, rank=r.index,
                                      delay_s=delay)
            if delay > 0.0:
                time.sleep(delay)
        return False

    def _kill_replica(self, r: Replica) -> None:
        """An involuntary replica loss: its engine's device state is
        gone (in-process, the plane simply never touches it again).
        Everything the replica held — active rows, queued requests,
        bundles parked toward it — goes to
        :meth:`_recover_casualties`: the base (fixed-replica) plane
        SHEDS them, counted in the SLO table and ``shed_on_death``,
        never silently — which is exactly the degraded mode the
        elastic plane's checkpoint-resume recovery exists to beat."""
        if not r.alive:
            return
        r.alive = False
        self.deaths.append(r.name)
        active = [s.seq_id for s in r.engine._slots if s.active]
        queued = [req.seq_id for req in r.engine._queue]
        bundles = list(r.pending_migrations)
        r.pending_migrations.clear()
        for b in bundles:
            # the handoff died with its destination: its window can
            # never complete (don't let it rot in the overlap floor)
            self._mig_open.pop(b.seq, None)
        self._emit(kind="plane_replica_death", replica=r.name,
                   active=len(active), queued=len(queued),
                   bundles=len(bundles))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.replica_deaths").inc()
        self._recover_casualties(r, active, queued, bundles)

    def _recover_casualties(self, r: Replica, active_sids, queued_sids,
                            bundles) -> None:
        """Fixed-replica recovery: SHED every casualty (the static
        plane cannot adapt — a death today ends in shedding). The
        elastic plane overrides this with checkpoint resume +
        re-routing (serving_plane/autoscaler.py)."""
        for sid in [*active_sids, *queued_sids,
                    *(b.seq_id for b in bundles)]:
            self._shed_request(sid, on_death=True)

    def _shed_request(self, sid: int, *, on_death: bool = False) -> None:
        ps = self.stats.get(sid)
        if ps is None or ps.get("outcome") is not None:
            return
        ps["outcome"] = "shed"
        ps["t_finish"] = time.perf_counter()
        rtr = reqtracelib.active()
        if rtr is not None:
            # plane-side shed (death / unplaceable arrival): the
            # request may never have reached an engine's recorder —
            # open its queued span retroactively so the shed life
            # still tiles instead of finalizing as one untracked gap
            if rtr.segments(sid) is None:
                rtr.begin_request(sid, ps["t_submit"])
            rtr.finish_request(sid, ps["t_finish"], final="shed")
        self._judge_window(ps)  # a shed never attains — it counts
        self.finished[sid] = np.zeros((0,), np.int32)
        self._requests.pop(sid, None)  # resolved: recovery never
        if on_death:                   # reads it again
            self.shed_on_death += 1
        self._emit(kind="plane_shed", seq_id=sid, on_death=on_death)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.shed").inc()

    def _autoscale_round(self) -> bool:
        """Post-round scaling hook — the base plane is FIXED (the
        ROADMAP's nobody-closes-the-loop baseline); the elastic plane
        overrides this with the SLO-feedback controller. Returns True
        when the plane changed shape (counts as progress)."""
        return False

    # -- the plane loop ----------------------------------------------------

    def _round_order(self) -> list[Replica]:
        # prefill replicas first: their exports of THIS round must be
        # in flight before the decode replicas dispatch their chunks
        return ([r for r in self.replicas if r.role == "prefill"]
                + [r for r in self.replicas if r.role != "prefill"])

    def _has_work(self) -> bool:
        return any(
            r.alive and (r.engine.has_work() or r.pending_migrations)
            for r in self.replicas)

    def run(self, *, arrivals=None, max_rounds: int | None = None):
        """Serve until every replica's queue/slots and (open-loop)
        arrivals drain; returns the plane's ``finished`` table.
        ``arrivals``: ``(t_rel_s, submit_kwargs)`` pairs on the
        schedule's clock, exactly like ``ContinuousBatcher.run`` —
        TTFT/goodput charge the queueing delay the user actually saw.
        ``max_rounds``: park after this many plane rounds (every
        replica at a chunk boundary) and return."""
        t_run0 = time.perf_counter()
        pending_arrivals = (deque(sorted(arrivals, key=lambda a: a[0]))
                            if arrivals else None)
        rounds = 0
        while True:
            if pending_arrivals:
                now_rel = time.perf_counter() - t_run0
                while pending_arrivals \
                        and pending_arrivals[0][0] <= now_rel:
                    t_arr, kw = pending_arrivals.popleft()
                    try:
                        rid = self.submit(**kw)
                    except ValueError:
                        if not self.deaths:
                            raise  # a config error, not degradation
                        # an arrival no surviving replica can place:
                        # the degraded plane sheds it, counted — the
                        # run must keep serving what it can
                        rid = self._next_rid
                        self._next_rid += 1
                        self.stats[rid] = {
                            "priority": int(kw.get("priority", 0)),
                            "t_submit": t_run0 + t_arr,
                            "t_first": None, "t_finish": None,
                            "tokens": 0, "outcome": None,
                            "preemptions": 0, "replica": None,
                        }
                        self._shed_request(rid, on_death=True)
                        continue
                    t_abs = t_run0 + t_arr
                    # the schedule's instant, end to end: the plane
                    # row, the replica's queue entry, and the replica's
                    # stats row all charge the user-visible wait
                    self.stats[rid]["t_submit"] = t_abs
                    eng = self._assignment[rid].engine
                    eng._queue[-1].t_submit = t_abs
                    eng.stats[rid]["t_submit"] = t_abs
                    rtr = reqtracelib.active()
                    if rtr is not None:
                        rtr.restamp_submit(rid, t_abs)
            if not self._has_work():
                if not pending_arrivals:
                    break
                if max_rounds is not None:
                    break
                wait = pending_arrivals[0][0] - (time.perf_counter()
                                                 - t_run0)
                time.sleep(min(max(wait, 0.0), 0.005))
                continue
            if max_rounds is not None and rounds >= max_rounds:
                break
            rounds += 1
            self.rounds_total += 1
            progressed = False
            for r in self._round_order():
                if not r.alive:
                    continue
                if chaoslib.active() is not None \
                        and self._probe_replica_chaos(r):
                    progressed = True  # the death recovery moved work
                    continue
                with r.device_ctx():
                    if r.role == "prefill":
                        st = r.engine.service_round(decode=False)
                        progressed |= bool(st["admitted"])
                        progressed |= self._export_ready(r) > 0
                    else:
                        installed: list = []
                        pre = None
                        if r.pending_migrations:
                            def pre(overlapped, r=r, box=installed):
                                box.extend(
                                    self._install_pending(r, overlapped))
                        st = r.engine.service_round(pre_collect=pre)
                        progressed |= (bool(st["admitted"])
                                       or st["active"]
                                       or bool(installed))
                        if installed:
                            self._complete_migrations(r, installed)
                r.rounds += 1
                self.replica_rounds += 1
                progressed |= self._collect_finished(r) > 0
            self._update_gauges()
            self._emit_attainment()
            progressed |= self._autoscale_round()
            if not progressed and not pending_arrivals:
                queued = {r.name: r.engine.queue_depth
                          for r in self.replicas if r.alive}
                raise RuntimeError(
                    f"serving-plane deadlock: work remains but no "
                    f"replica can make progress (queues {queued}, "
                    f"pending migrations "
                    f"{[len(r.pending_migrations) for r in self.replicas]}"
                    ") — pools too small for the waiting requests?")
        total = time.perf_counter() - t_run0
        self._serve_s += total
        if self._mig_total_s > 0:
            self.last_kv_migration_overlap_frac = (
                self._mig_overlap_s / self._mig_total_s)
        if self._dma_total_s > 0:
            self.last_dma_migration_overlap_frac = (
                self._dma_overlap_s / self._dma_total_s)
        m = metricslib.get_metrics()
        if m.enabled:
            m.gauge("plane.migrations").set(self.migrations)
            if self.last_kv_migration_overlap_frac is not None:
                m.gauge("plane.kv_migration_overlap_frac").set(
                    self.last_kv_migration_overlap_frac)
            if self.last_dma_migration_overlap_frac is not None:
                m.gauge("plane.dma_migration_overlap_frac").set(
                    self.last_dma_migration_overlap_frac)
            m.gauge("plane.migration_bytes_per_round").set(
                self.migration_bytes_per_round)
        if self.slo is not None:
            self.last_slo = slolib.attainment(self.stats, self.slo,
                                              self._serve_s)
            if m.enabled:
                tot = self.last_slo["total"]
                m.gauge("plane.tok_s").set(tot["tok_s"])
                m.gauge("plane.goodput_tok_s").set(
                    tot["goodput_tok_s"])
                if self.replica_rounds:
                    m.gauge("plane.goodput_per_replica_round").set(
                        self.goodput_per_replica_round or 0.0)
        return self.finished

    @property
    def goodput_per_replica_round(self) -> float | None:
        """SLO-attained tokens per (live replica × plane round) — the
        EFFICIENCY headline of the elastic trajectory: a plane that
        holds attainment by over-provisioning pays for it here, one
        that sheds pays in the numerator. Gated via
        ``detail.goodput_per_replica_round`` (harness/regress.py).
        None until a run with ``slo=`` completed."""
        if self.last_slo is None or not self.replica_rounds:
            return None
        tot = self.last_slo["total"]
        good_tokens = tot["goodput_tok_s"] * self.last_slo["wall_s"]
        return good_tokens / self.replica_rounds

    @property
    def migration_bytes_per_round(self) -> float:
        """Σ dispatched KV-payload bytes per plane round — the
        dataplane-pressure headline the transport tier exists to hide:
        the SAME bytes cross whichever transport resolved, so this
        number is transport-invariant and regress-gated
        (``detail.migration_bytes_per_round``) as a workload-shape
        pin rather than a speed score. 0.0 before any round ran."""
        return self.migration_bytes / max(1, self.rounds_total)
