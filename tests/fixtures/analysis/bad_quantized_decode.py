"""Known-bad: quantized-decode hazards, minimized.

The round-13 quantization paths (``DEFAULT_DISPATCH_CRITICAL`` names
them) run INSIDE the traced decode step or on its dispatch edge — the
hazard class is a host readback over a SCALE: scales are tiny (D times
smaller than the cache), which makes "just peek at one" look cheap,
but the peek syncs the whole in-flight chunk on the quantized bytes
the scale rides with. Lines carrying ``EXPECT: <rule>`` markers are
the golden findings tests/test_analysis.py asserts, line-exact.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _quantize_rows(x):
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    # "validating" the dynamic range on host mid-dispatch: the
    # float() forces the whole upstream chunk to resolve
    peak = float(jnp.max(amax))  # EXPECT: host-sync-in-dispatch
    scale = jnp.maximum(amax / 127.0, 1e-8 * peak)
    q = jnp.clip(jnp.round(x / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequant(cache, scale):
    # a host snapshot of the scale rows — np.asarray on CPU is a
    # zero-copy view AND a sync; the dequant belongs in the einsum
    # stream, not on the host
    s = np.asarray(scale)  # EXPECT: host-sync-in-dispatch
    return cache.astype(jnp.float32) * jnp.asarray(s)[..., None]


def _scale_write(pool, page_ids, offset, rows):
    # "confirming" the scale landed stalls the chunk the write was
    # enqueued behind
    pool = pool.at[page_ids, :, 0, offset].set(rows)
    jax.block_until_ready(pool)  # EXPECT: host-sync-in-dispatch
    return pool
