"""Interop suite — TPU rebuild of ``sycl_omp_ze_interopt`` (C10).

The reference proves two runtimes can share device memory zero-copy: it
extracts Level-Zero handles from the OpenMP runtime, wraps them as SYCL
objects, then asserts that buffers allocated by either runtime are
readable by the other without copies (interop_omp_ze_sycl.cpp:16-101).

The TPU-native equivalents:

- :mod:`~.native` — the C++ support library (native/hpcpat.cpp) bound
  via ctypes: aligned allocator, analytic validators, stats engine,
  ring planner. The "foreign runtime" whose memory Python/JAX must use.
- :mod:`~.zero_copy` — the pointer-sharing proofs: native buffer ↔
  numpy ↔ JAX (dlpack) ↔ torch, each direction asserted zero-copy by
  *pointer identity*, the airtight version of the reference's
  write-here-read-there asserts (:81-101).

apps/interop_app.py runs the full proof chain as a self-validating
benchmark.
"""

from hpc_patterns_tpu.interop import native  # noqa: F401
