"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Standard flash-attention dataflow, TPU-shaped:

- grid = (batch·heads, T/BLOCK_Q): one program per query block per head;
  Pallas auto-pipelines each program's HBM→VMEM block loads against the
  previous program's compute (the same DMA/compute overlap the
  concurrency suite measures, here for free from the grid).
- K/V for the whole (small) sequence sit in VMEM per program; the kernel
  walks K/V blocks with ``lax.fori_loop``, maintaining the online
  softmax state (m, l, acc) in f32 — numerically identical to the
  two-pass softmax (same accumulator as parallel/ring_attention, which
  runs this dataflow *across chips*).
- block matmuls hit the MXU via ``jnp.dot(..., preferred_element_type=
  f32)``; bf16 inputs stay bf16 into the MXU.
- causal masking skips nothing but masks with a finite -1e30 (inf-free,
  like ring_attention), and whole K/V blocks strictly above the diagonal
  are skipped via the loop bound — half the FLOPs for causal.
- backward (Dao 2023 §B): Δ = rowsum(dO ⊙ O), then two blockwise passes
  — dQ over K blocks, dK/dV over Q blocks — recomputing P from the
  forward's saved per-row logsumexp. O(block) VMEM in both directions.

Single-device kernel: under a mesh, distribute with
parallel.ring_attention / ulysses and let each rank call this locally
(mesh=None path of models.transformer).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _causal_mask(s, q_start, k_start):
    """Mask score block ``s`` so position (i, j) survives iff the global
    key index k_start+j is at or before the global query index q_start+i.
    Shared by the forward and both backward kernels — the mask must be
    identical or the recomputed P diverges from the forward's."""
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _kv_block_bound(q_start, block_q, block_k, n_kv, causal):
    """Number of K/V blocks a query block must visit: all of them, or —
    causal — only blocks starting at or before the query block's end
    (strictly-above-diagonal blocks contribute nothing)."""
    if not causal:
        return n_kv
    return jnp.minimum((q_start + block_q - 1) // block_k + 1, n_kv)


def _kernel(q_ref, k_ref, v_ref, o_ref, *lse_ref, block_k: int,
            scale: float, causal: bool):
    # q_ref: (BLOCK_Q, D); k_ref/v_ref: (T, D); o_ref: (BLOCK_Q, D);
    # optional lse_ref: (BLOCK_Q, 1) per-row logsumexp for the backward
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    n_kv = t // block_k
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    q_start = qi * block_q

    def body(ki, state):
        m, l, acc = state
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, ki * block_k)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        rescale = jnp.exp(m - m_new)
        l_new = l * rescale + p.sum(axis=-1, keepdims=True)
        acc_new = acc * rescale + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    n_iter = _kv_block_bound(q_start, block_q, block_k, n_kv, causal)
    m, l, acc = lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    if lse_ref:
        lse_ref[0][:] = m + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, scale: float, causal: bool):
    # One program per query block: walk K/V blocks, accumulate dQ.
    # dS = P * (dO·Vᵀ − Δ); dQ = scale · dS·K, with P recomputed from the
    # saved per-row logsumexp (no (T,T) matrix ever materialized).
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    n_kv = t // block_k
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # (BLOCK_Q, 1)
    delta = delta_ref[:]  # (BLOCK_Q, 1)

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start, ki * block_k)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    n_iter = _kv_block_bound(q_start, block_q, block_k, n_kv, causal)
    dq = lax.fori_loop(0, n_iter, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                dk_ref, dv_ref, *, block_q: int, scale: float, causal: bool):
    # One program per K/V block: walk query blocks, accumulate dK and dV.
    # dV = Pᵀ·dO; dK = scale · dSᵀ·Q. Causal: query blocks strictly above
    # this K block see none of it — start the walk at the diagonal.
    block_k, d = k_ref.shape
    t = q_ref.shape[0]
    n_q = t // block_q
    ki = pl.program_id(1)
    k_start = ki * block_k

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(qi, state):
        dk, dv = state
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[pl.ds(qi * block_q, block_q), :]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, k_start)
        p = jnp.exp(s - lse)
        dv_new = dv + jnp.dot(p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jnp.dot(ds.T, q_blk, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    start = k_start // block_q if causal else 0
    dk, dv = lax.fori_loop(
        start, n_q, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal=causal, scale=scale,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret, with_residuals=False)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    # residuals stay in kernel layout (B·H, T, D) — the backward consumes
    # them directly, so the fwd's transposes aren't repeated
    out, residuals = _flash_forward(q, k, v, causal=causal, scale=scale,
                                    block_q=block_q, block_k=block_k,
                                    interpret=interpret, with_residuals=True)
    return out, residuals


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    qr, kr, vr, outr, lse = residuals
    return _flash_backward(qr, kr, vr, outr, lse, g, causal=causal,
                           scale=scale, block_q=block_q, block_k=block_k,
                           interpret=interpret)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def _flash_backward(
    qr, kr, vr, outr, lse, g, *,
    causal: bool,
    scale: float | None,
    block_q: int,
    block_k: int,
    interpret: bool | None,
):
    B, T, H, D = g.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    dor = jnp.einsum("bthd->bhtd", g).reshape(B * H, T, D)
    delta = jnp.sum(
        dor.astype(jnp.float32) * outr.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (B·H, T, 1) — trailing unit dim keeps TPU block shapes legal

    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    blk_q = row((None, block_q, D), lambda bh, i: (bh, i, 0))
    blk_k = row((None, block_k, D), lambda bh, i: (bh, i, 0))
    full = row((None, T, D), lambda bh, i: (bh, 0, 0))
    vec_q = row((None, block_q, 1), lambda bh, i: (bh, i, 0))
    vec_full = row((None, T, 1), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, scale=float(scale),
                          causal=causal),
        grid=(B * H, T // block_q),
        in_specs=[blk_q, full, full, blk_q, vec_q, vec_q],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), qr.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, scale=float(scale),
                          causal=causal),
        grid=(B * H, T // block_k),
        in_specs=[full, full, vec_full, vec_full, blk_k, blk_k],
        out_specs=(blk_k, blk_k),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, T, D), kr.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), vr.dtype),
        ),
        interpret=interpret,
    )(qr, dor, lse, delta, kr, vr)

    back = lambda x: x.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Softmax attention over (batch, seq, heads, head_dim) inputs.

    Numerically equal to parallel.ring_attention.full_attention (the
    oracle in tests); O(block) VMEM instead of the (T, T) score matrix.
    Sequence length must divide by the block sizes (pad upstream — the
    model keeps T a multiple of 128). Differentiable: custom VJP whose
    backward is two blockwise Pallas kernels (dQ pass, dK/dV pass)
    recomputing P from the forward's saved logsumexp — O(block) VMEM in
    both directions.
    """
    return _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret",
                     "with_residuals"),
)
def _flash_forward(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    with_residuals: bool = False,
):
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"seq {T} must divide by blocks ({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # (B, T, H, D) -> (B*H, T, D): one grid row per (batch, head)
    qr = jnp.einsum("bthd->bhtd", q).reshape(B * H, T, D)
    kr = jnp.einsum("bthd->bhtd", k).reshape(B * H, T, D)
    vr = jnp.einsum("bthd->bhtd", v).reshape(B * H, T, D)

    kernel = functools.partial(
        _kernel, block_k=block_k, scale=float(scale), causal=causal,
    )
    blk_q = pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
    full = pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0),
                        memory_space=pltpu.VMEM)
    out_specs = [blk_q]
    out_shape = [jax.ShapeDtypeStruct((B * H, T, D), q.dtype)]
    if with_residuals:
        # the lse write is skipped entirely on the primal (inference) path
        out_specs.append(
            pl.BlockSpec((None, block_q, 1), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
        )
        out_shape.append(jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32))

    results = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[blk_q, full, full],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(qr, kr, vr)
    outr = results[0]
    out = outr.reshape(B, H, T, D).transpose(0, 2, 1, 3)  # -> (B, T, H, D)
    if with_residuals:
        return out, (qr, kr, vr, outr, results[1])
    return out, None
