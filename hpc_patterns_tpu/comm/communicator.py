"""Array-level communicator: mesh axis ≙ MPI communicator.

The reference's miniapp mains wire device buffers to MPI calls per rank
(allreduce-mpi-sycl.cpp:88-207). Here one process drives all local TPU
devices, so the per-rank view is created by ``shard_map``: a
:class:`Communicator` binds a mesh axis and exposes collectives over
global ``jax.Array``\\ s whose leading dimension is sharded on that axis —
row r of the global array is rank r's buffer, exactly the miniapp's
``VA/VB/VC`` per-rank layout.

Every operation jit-compiles a ``shard_map`` closure (cached per shape/
dtype/algorithm); on TPU the collectives run on HBM shards over ICI with
no host staging.
"""

from __future__ import annotations

import os
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpc_patterns_tpu.analysis import runtime as analysis_runtime
from hpc_patterns_tpu.comm import collectives, ring
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.topology import shard_map

Algorithm = Literal["collective", "ring", "ring_chunked"]


def _ready_in_span(result, op: str = "collective", seq: int | None = None,
                   axis: str | None = None):
    """Block before an open span exits so it measures collective
    completion, not async dispatch — the shard_map call returns an
    unready array. Only when a span actually records (metrics, trace
    mirroring, or the flight recorder); the disabled path stays fully
    async. With a recorder, the dispatch→completion window also lands
    as a ``comm.<op>`` slice on the device track, separating wire time
    from the host time around it; ``seq`` (the per-communicator
    collective counter) rides in the slice args so the cross-rank merge
    (harness/collect.py) can match the N ranks' windows of the SAME
    collective and measure its skew.

    Every eager collective is ALSO fingerprinted into the per-rank
    schedule hash chain (analysis/runtime.py) before the wait —
    whenever anything can consume the chain: a live flight recorder
    (the chain rides trace snapshots to the cross-rank merge) or a
    launcher-exported ``HPCPAT_TRACE_DIR`` (the per-record progress
    file is what names which collective a hung rank is stuck in, so
    it must engage even when the child wasn't run with ``--trace``).
    Reading ``.shape``/``.dtype`` off the unready array does not
    block, and with neither consumer present nothing is recorded —
    the disabled path stays fully async and byte-identical."""
    m = metricslib.get_metrics()
    rec = tracelib.active()
    if seq is not None and (
            rec is not None
            or analysis_runtime.ENV_TRACE_DIR in os.environ):
        analysis_runtime.record_collective(
            op, seq, shape=getattr(result, "shape", None),
            dtype=str(getattr(result, "dtype", "")) or None, axis=axis)
    if not (m.enabled or m.mirror_traces or rec is not None):
        return result
    if rec is not None:
        attrs = None if seq is None else {"seq": seq}
        t_disp = rec.mark_dispatch(f"comm.{op}", args=attrs)
        # jaxlint: disable=host-sync-in-dispatch — measures completion,
        # not dispatch (PR 1 review decision); only reached with a
        # recorder/metrics active, the disabled path stays fully async
        jax.block_until_ready(result)
        rec.mark_complete(f"comm.{op}", t_disp, args=attrs)
    else:
        # jaxlint: disable=host-sync-in-dispatch — same contract as
        # above: the recording span must not exit before the wire time
        # it claims to measure has elapsed
        jax.block_until_ready(result)
    return result


def _inject_chaos(seq: int) -> None:
    """Chaos injection, straggler site — called by every collective
    method BEFORE the shard_map closure is even built, so the injected
    delay precedes the dispatch itself: the straggler's device work for
    collective ``seq`` genuinely starts late (the other ranks stretch
    waiting for it), and the skew evidence in the cross-rank merge is
    the real perturbation, not an artifact of marker placement. One
    cached-config read when no chaos is active."""
    if chaoslib.active() is not None:
        chaoslib.maybe_inject("collective", seq)


def record_collective_bandwidth(op: str, nbytes: int, seconds: float,
                                **attrs) -> None:
    """Per-collective bandwidth gauge + latency histogram in the
    process-wide metrics registry (no-op when disabled): the
    observability layer's view of the BASELINE bandwidth metrics, so a
    sweep's ``kind=metrics`` snapshot carries the same numbers the
    per-point ``kind=result`` records do. ``attrs`` become gauges too
    (e.g. ``busbw_gbps=...`` for the ring-normalized form)."""
    m = metricslib.get_metrics()
    if not m.enabled or seconds <= 0:
        return
    m.gauge(f"comm.{op}.bandwidth_gbps").set(nbytes / seconds / 1e9)
    m.histogram(f"comm.{op}.s").observe(seconds)
    for key, value in attrs.items():
        m.gauge(f"comm.{op}.{key}").set(value)

# allreduce algorithm table: library collective vs hand-built rings —
# the comparison the reference exists to make (SURVEY.md §2.3(b)).
_ALLREDUCE = {
    "collective": lambda x, axis: collectives.allreduce(x, axis, "sum"),
    "ring": ring.ring_allreduce,
    # chunk over the trailing (data) axis — the leading axis is the
    # 1-row rank dimension inside shard_map
    "ring_chunked": lambda x, axis: ring.ring_allreduce_chunked(
        x, axis, scatter_axis=x.ndim - 1
    ),
}


class Communicator:
    """Collectives over one named axis of a mesh.

    ``Communicator(mesh, "x")`` plays the role of ``MPI_COMM_WORLD`` in
    the miniapps; ``size`` is ``MPI_Comm_size``. Arrays passed in must
    have a leading dimension equal to ``size`` (one row per rank); they
    are sharded onto the axis automatically if not already.
    """

    def __init__(self, mesh: Mesh, axis: str = "x"):
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        # jitted rank_filled initializers by (n, dtype): sweeps call it
        # once per point, and a fresh jax.jit per call re-traces every
        # time (jaxlint: recompile-hazard)
        self._rank_filled_cache: dict = {}
        # per-communicator collective counter: every eager collective
        # call takes the next value, and since all ranks of an SPMD
        # program issue the identical collective sequence, (span name,
        # seq) identifies THE SAME collective across ranks — what the
        # cross-rank trace merge fans its skew arrows over. Incremented
        # unconditionally (one integer add; the disabled trace path
        # stays byte-identical in recorded output).
        self._seq = 0

    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def row_sharding(self, ndim: int, memory_kind: str | None = None) -> NamedSharding:
        """Sharding that puts row r on rank r (leading dim over the axis).

        ``memory_kind`` maps the reference's USM allocator axis
        (``-H/-D``, allreduce-mpi-sycl.cpp:104-131) onto JAX memory
        kinds: ``"pinned_host"`` ≙ host USM, ``"device"``/None ≙ device
        USM (HBM)."""
        spec = P(self.axis, *([None] * (ndim - 1)))
        if memory_kind is None:
            return NamedSharding(self.mesh, spec)
        return NamedSharding(self.mesh, spec, memory_kind=memory_kind)

    def shard(self, x, memory_kind: str | None = None) -> jax.Array:
        """Place a (size, ...) array with one row per rank — the analog of
        each rank allocating + initializing its device buffer
        (allreduce-mpi-sycl.cpp:154-164)."""
        x = jnp.asarray(x)
        if x.shape[0] != self.size:
            raise ValueError(
                f"leading dim {x.shape[0]} != communicator size {self.size}"
            )
        return jax.device_put(x, self.row_sharding(x.ndim, memory_kind))

    def _shmap(self, fn, x, out_specs=None):
        spec = P(self.axis, *([None] * (jnp.ndim(x) - 1)))
        out = out_specs if out_specs is not None else spec
        mapped = shard_map(fn, mesh=self.mesh, in_specs=spec, out_specs=out)
        return jax.jit(mapped)

    # -- collectives over (size, n) arrays --------------------------------

    def allreduce(self, x, algorithm: Algorithm = "collective") -> jax.Array:
        """Elementwise sum across ranks; every row of the result holds the
        sum (MPI_Allreduce semantics, allreduce-mpi-sycl.cpp:61-67 for
        ``"collective"``; the :173-182 hand ring for ``"ring"``;
        two-phase bandwidth-optimal ring for ``"ring_chunked"``)."""
        impl = _ALLREDUCE[algorithm]
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.allreduce", algorithm=algorithm):
            return _ready_in_span(
                self._shmap(lambda local: impl(local, self.axis), x)(x),
                op=f"allreduce.{algorithm}", seq=seq, axis=self.axis)

    def jit_allreduce(self, x, algorithm: Algorithm = "collective"):
        """The compiled allreduce closure for ``x``'s shape — what a
        benchmark should time (compile excluded per SURVEY.md §7(d))."""
        impl = _ALLREDUCE[algorithm]
        return self._shmap(lambda local: impl(local, self.axis), x)

    def pingpong(self, x) -> jax.Array:
        """Pairwise even/odd exchange: row r swaps with row r^1 — the
        pt2pt ping-pong config of BASELINE.json."""
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.pingpong"):
            return _ready_in_span(self.jit_pingpong(x)(x),
                                  op="pingpong", seq=seq,
                                  axis=self.axis)

    def jit_pingpong(self, x):
        """Compiled pairwise-exchange closure (for timing loops)."""
        return self._shmap(lambda l: ring.pairwise_exchange(l, self.axis), x)

    def sendrecv_ring(self, x, shift: int = 1) -> jax.Array:
        """One ring hop: row r moves to row (r+shift) % size
        (SendRecvRing, allreduce-mpi-sycl.cpp:43-59)."""
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.sendrecv_ring", shift=shift):
            return _ready_in_span(self._shmap(
                lambda l: ring.ring_shift(l, self.axis, shift), x)(x),
                op="sendrecv_ring", seq=seq, axis=self.axis)

    def all_gather(self, x) -> jax.Array:
        """Every rank receives every row: (size, n) -> (size, size, n)."""
        fn = lambda l: collectives.all_gather(l, self.axis, tiled=False).squeeze(1)[None]
        spec = P(self.axis, None, *([None] * (jnp.ndim(x) - 1)))
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.all_gather"):
            return _ready_in_span(self._shmap(fn, x, out_specs=spec)(x),
                                  op="all_gather", seq=seq,
                                  axis=self.axis)

    def reduce_scatter(self, x) -> jax.Array:
        """(size, size*n) rows -> (size, n): rank r gets chunk r of the sum."""
        fn = lambda l: collectives.reduce_scatter(l, self.axis, scatter_axis=jnp.ndim(x) - 1)
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.reduce_scatter"):
            return _ready_in_span(self._shmap(
                fn, x,
                out_specs=P(self.axis, *([None] * (jnp.ndim(x) - 1))))(x),
                op="reduce_scatter", seq=seq, axis=self.axis)

    def all_to_all(self, x) -> jax.Array:
        """Row r's chunk c goes to row c's chunk r (MPI_Alltoall)."""
        fn = lambda l: collectives.all_to_all(
            l, self.axis, split_axis=jnp.ndim(x) - 1, concat_axis=jnp.ndim(x) - 1
        )
        seq = self._next_seq()
        _inject_chaos(seq)
        with metricslib.span("comm.all_to_all"):
            return _ready_in_span(self._shmap(fn, x)(x),
                                  op="all_to_all", seq=seq,
                                  axis=self.axis)

    # -- miniapp-style buffer init ---------------------------------------

    def rank_filled(self, n: int, dtype="float32") -> jax.Array:
        """The miniapp's ``Initialize``: rank r's buffer filled with r
        (allreduce-mpi-sycl.cpp:33-41), so the allreduce oracle is
        ``size*(size-1)/2`` (:192-204). Built shard-wise (no host
        materialization of the global array)."""

        fill = self._rank_filled_cache.get((n, str(dtype)))
        if fill is None:

            def init(_):
                r = ring.axis_index(self.axis)
                return jnp.full((1, n), r, dtype=dtype)

            spec = P(self.axis, None)
            fill = jax.jit(
                shard_map(init, mesh=self.mesh, in_specs=spec,
                          out_specs=spec)
            )
            self._rank_filled_cache[(n, str(dtype))] = fill
        token = self.shard(np.zeros((self.size, 1), np.int8))
        return fill(token)

    def expected_allreduce_value(self) -> float:
        """The analytic oracle: Σ ranks = size(size-1)/2."""
        return self.size * (self.size - 1) / 2
