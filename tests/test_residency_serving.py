"""Tiered-memory serving (EngineCore + memory/residency.py): the
byte-exact capacity oracle.

THE claim of round 11, in the repo's standard form: an engine whose
HBM page pool is too small to hold its streams' KV — fronting a
host-resident pool through the residency manager, with cold rows
paged out at chunk boundaries and swapped rows prefetched back under
the decode chunk — emits TOKEN-IDENTICAL streams to an all-HBM engine
(greedy AND sampled), with preemption-and-resume and cross-engine
migration composing on top (an exported bundle gathers pages from
whichever tier holds them). Everything else (demand rules, windows,
the slow_host_transfer chaos site, reservation bookkeeping) is pinned
around that.
"""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.memory import (
    ColdAfterNPolicy,
    LRUPolicy,
    PriorityAwarePolicy,
    ResidencyManager,
)
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.serving import ContinuousBatcher, EngineCore

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_seq=128, dtype="float32",
                        decode_attn="gather")
PAGE = 8
PROMPT_LEN, BUDGET = 8, 24


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def reqs():
    rng = np.random.RandomState(3)
    return [(rng.randint(0, CFG.vocab, size=PROMPT_LEN)
             .astype(np.int32), BUDGET) for _ in range(5)]


PPS = ContinuousBatcher.pages_needed(PROMPT_LEN, BUDGET, PAGE)


def _engine(params, pool_rows, mgr=None, slots=5, **kw):
    return ContinuousBatcher(
        params, CFG, slots=slots, pool_pages=pool_rows * PPS,
        pages_per_seq=PPS, page_size=PAGE, chunk=4, residency=mgr,
        **kw)


@pytest.fixture(scope="module")
def base(params, reqs):
    """The all-HBM oracle outputs, greedy: {req index: tokens}."""
    eng = _engine(params, 5)
    ids = [eng.submit(p, b) for p, b in reqs]
    got = eng.run()
    return {i: got[s] for i, s in enumerate(ids)}


class TestTieredOracle:
    def test_greedy_token_identical_under_rotation(self, params, reqs,
                                                   base):
        # 2-row HBM pool under a 5-row working set, deterministic
        # cold-after-N rotation: real paging, byte-exact output
        mgr = ResidencyManager(host_blocks=5 * PPS,
                               policy=ColdAfterNPolicy(2))
        eng = _engine(params, 2, mgr)
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        for i, s in enumerate(ids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        assert mgr.swap_outs > 0 and mgr.swap_ins > 0
        assert not eng._swapped and not eng._prefetching

    def test_lru_same_class_completes_without_thrash(self, params,
                                                     reqs, base):
        # pool holds ONE row; same-class arrivals wait for completions
        # (the no-manager behavior) instead of evict/pull-back cycling
        mgr = ResidencyManager(host_blocks=8 * PPS, policy=LRUPolicy())
        eng = _engine(params, 1, mgr, slots=4)
        ids = [eng.submit(p, b) for p, b in reqs[:4]]
        got = eng.run()
        for i, s in enumerate(ids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        assert mgr.swap_outs == 0  # nothing demanded paging

    def test_sampled_token_identical(self, params, reqs):
        kw = dict(temperature=0.8, top_k=8, seed=5)
        full = _engine(params, 5, **kw)
        fids = [full.submit(p, b) for p, b in reqs]
        want = full.run()
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=ColdAfterNPolicy(2))
        eng = _engine(params, 2, mgr, **kw)
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        for i, s in enumerate(ids):
            np.testing.assert_array_equal(want[fids[i]], got[s],
                                          err_msg=f"sampled seq {i}")
        # the sampled key state crossed the tier boundary and back
        assert mgr.swap_outs > 0

    def test_urgent_arrival_pages_out_background(self, params, reqs,
                                                 base):
        # soft preemption: a priority-0 arrival displaces a priority-1
        # resident via the HOST tier — no re-prefill, tokens preserved
        # (preempt stays OFF: the manager alone must serve the urgent
        # class; with preempt=True the hard path may fire first at a
        # run boundary, which the host-tier-full test covers)
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=PriorityAwarePolicy())
        eng = _engine(params, 2, mgr, slots=3)
        sids = [eng.submit(p, b, priority=1) for p, b in reqs[:3]]
        eng.run(max_rounds=3)
        hi = eng.submit(reqs[3][0], reqs[3][1], priority=0)
        got = eng.run()
        for i, s in enumerate(sids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        np.testing.assert_array_equal(base[3], got[hi])
        assert mgr.swap_outs > 0
        preempts = sum(st["preemptions"] for st in eng.stats.values())
        assert preempts == 0  # paging, not re-prefill, served class 0

    def test_hard_preemption_composes_when_host_tier_full(
            self, params, reqs, base):
        # host pool smaller than one row: the manager cannot help, so
        # the round-8 preemption machinery fires — and the resumed
        # victim is still byte-exact
        mgr = ResidencyManager(host_blocks=2, policy=LRUPolicy())
        eng = _engine(params, 2, mgr, slots=3, preempt=True)
        sids = [eng.submit(p, b, priority=1) for p, b in reqs[:2]]
        eng.run(max_rounds=3)
        hi = eng.submit(reqs[3][0], reqs[3][1], priority=0)
        got = eng.run()
        for i, s in enumerate(sids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        np.testing.assert_array_equal(base[3], got[hi])
        assert sum(st["preemptions"]
                   for st in eng.stats.values()) >= 1
        assert mgr.swap_outs == 0

    def test_migration_bundles_gather_across_tiers(self, params, reqs,
                                                   base):
        # one row exported from the HOST tier (swapped out), one from
        # HBM — both install on an all-HBM engine and finish exactly
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=ColdAfterNPolicy(1))
        src = EngineCore(params, CFG, slots=3, pool_pages=2 * PPS,
                         pages_per_seq=PPS, page_size=PAGE, chunk=4,
                         residency=mgr)
        for i in range(3):
            src.submit(reqs[i][0], reqs[i][1], seq_id=i)
        for _ in range(20):
            src.service_round()
            if src._swapped and any(s.active for s in src._slots):
                break
        assert src._swapped and any(s.active for s in src._slots)
        host_sid = next(iter(src._swapped))
        b_host = src.export_swapped(host_sid)
        res_slot = next(i for i, s in enumerate(src._slots)
                        if s.active)
        hbm_sid = src._slots[res_slot].seq_id
        b_hbm = src.export_migration(res_slot)
        assert src.stats[host_sid]["outcome"] == "migrated"
        dst = EngineCore(params, CFG, slots=4, pool_pages=4 * PPS,
                         pages_per_seq=PPS, page_size=PAGE, chunk=4)
        dst.install_migration(b_host)
        dst.install_migration(b_hbm)
        while dst.has_work():
            dst.service_round()
        np.testing.assert_array_equal(base[host_sid],
                                      dst.finished[host_sid])
        np.testing.assert_array_equal(base[hbm_sid],
                                      dst.finished[hbm_sid])

    def test_export_swapped_rejects_unknown_and_resident(self, params,
                                                         reqs):
        mgr = ResidencyManager(host_blocks=8 * PPS)
        eng = _engine(params, 2, mgr)
        sid = eng.submit(reqs[0][0], reqs[0][1])
        with pytest.raises(ValueError, match="not swapped out"):
            eng.export_swapped(sid)
        plain = _engine(params, 2)
        with pytest.raises(ValueError, match="not swapped out"):
            plain.export_swapped(0)


class TestResidencyScheduling:
    def test_draft_engines_refuse_residency(self, params):
        from hpc_patterns_tpu.models.transformer import init_params as ip

        dcfg = TransformerConfig(vocab=64, d_model=16, n_heads=2,
                                 n_layers=1, d_ff=32, max_seq=128,
                                 dtype="float32", decode_attn="gather")
        with pytest.raises(ValueError, match="do not page"):
            ContinuousBatcher(
                params, CFG, slots=2, pool_pages=2 * PPS,
                pages_per_seq=PPS, page_size=PAGE,
                draft_params=ip(jax.random.PRNGKey(1), dcfg),
                draft_cfg=dcfg,
                residency=ResidencyManager(host_blocks=4))

    def test_duplicate_seq_id_rejected_while_swapped(self, params,
                                                     reqs):
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=ColdAfterNPolicy(1))
        eng = EngineCore(params, CFG, slots=3, pool_pages=2 * PPS,
                         pages_per_seq=PPS, page_size=PAGE, chunk=4,
                         residency=mgr)
        for i in range(3):
            eng.submit(reqs[i][0], reqs[i][1], seq_id=i)
        for _ in range(20):
            eng.service_round()
            if eng._swapped:
                break
        assert eng._swapped
        sid = next(iter(eng._swapped))
        with pytest.raises(ValueError, match="already queued"):
            eng.submit(reqs[0][0], reqs[0][1], seq_id=sid)

    def test_windows_gauges_and_overlap_measured(self, params, reqs,
                                                 base):
        # the observability contract lands WITH the subsystem: the
        # flight recorder shows mem.prefetch/mem.evict device windows,
        # the registry carries the mem.* gauges, and the manager's
        # overlap fraction is a real measurement in [0, 1]
        rec = tracelib.configure(enabled=True)
        metricslib.configure(enabled=True, mirror_traces=False)
        try:
            mgr = ResidencyManager(host_blocks=5 * PPS,
                                   policy=ColdAfterNPolicy(2))
            eng = _engine(params, 2, mgr)
            ids = [eng.submit(p, b) for p, b in reqs]
            got = eng.run()
            for i, s in enumerate(ids):
                np.testing.assert_array_equal(base[i], got[s])
            wins = [ev for ev in rec.events
                    if ev[0] == "X" and ev[1] == "device"]
            names = {ev[2] for ev in wins}
            assert "mem.prefetch" in names and "mem.evict" in names
            assert "serve.chunk" in names
            # prefetch windows carry the payload size
            pf = [ev for ev in wins if ev[2] == "mem.prefetch"]
            assert all(ev[6]["bytes"] > 0 for ev in pf)
            reg = metricslib.get_metrics()
            assert reg.gauge("mem.prefetch_bytes").last > 0
            assert reg.gauge("mem.hbm_pages").n > 0
            frac = mgr.prefetch_overlap_frac
            assert frac is not None and 0.0 <= frac <= 1.0
        finally:
            tracelib.configure(enabled=False)
            metricslib.configure(enabled=False)

    def test_slow_host_transfer_widens_prefetch_window_and_goodput_gates(
            self, params, reqs, base):
        # the chaos satellite: a seeded slow_host_transfer delay must
        # (1) actually fire at the host_transfer site, (2) show up as
        # a WIDENED mem.prefetch window — the delay sits inside the
        # window it claims to — and (3) leave the SLO rollup usable
        # (goodput still computed, never above raw tok/s)
        delay_s = 0.08
        targets = slolib.targets_from_classes([
            type("C", (), {"priority": 0, "ttft_slo_s": 30.0,
                           "tpot_slo_s": 5.0})()])
        rec = tracelib.configure(enabled=True)
        chaoslib.configure(f"slow_host_transfer:delay_ms="
                           f"{int(delay_s * 1e3)}")
        try:
            mgr = ResidencyManager(host_blocks=5 * PPS,
                                   policy=ColdAfterNPolicy(2))
            eng = _engine(params, 2, mgr, slo=targets)
            ids = [eng.submit(p, b) for p, b in reqs]
            got = eng.run()
            for i, s in enumerate(ids):
                np.testing.assert_array_equal(base[i], got[s])
            fired = [e for e in chaoslib.injections()
                     if e["site"] == "host_transfer"]
            # jaxlint: disable=record-kind-drift — chaos injection
            # events are not RunLog records; their kind field is the
            # chaos fault kind, written dynamically by
            # record_injection
            assert fired and all(e["kind"] == "slow_host_transfer"
                                 for e in fired)
            pf = [ev for ev in rec.events
                  if ev[0] == "X" and ev[1] == "device"
                  and ev[2] == "mem.prefetch"]
            assert pf and max(ev[5] for ev in pf) >= delay_s
            tot = eng.last_slo["total"]
            assert 0.0 <= tot["goodput_tok_s"] <= tot["tok_s"] + 1e-9
        finally:
            chaoslib.reset()
            tracelib.configure(enabled=False)
            metricslib.configure(enabled=False)

    def test_balance_sizes_eviction_to_the_highwater_constraint(
            self, params, reqs, base):
        # a fresh urgent head blocked by admit_highwater (not by raw
        # pages) must still trigger paging sized to the BINDING
        # constraint — otherwise the head queues behind a cap that
        # eviction was supposed to lift (regression pin for the
        # round-11 review finding)
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=PriorityAwarePolicy())
        eng = _engine(params, 4, mgr, slots=4, admit_highwater=0.5)
        sids = [eng.submit(p, b, priority=1) for p, b in reqs[:2]]
        eng.run(max_rounds=2)
        hi = eng.submit(reqs[3][0], reqs[3][1], priority=0)
        got = eng.run()
        for i, s in enumerate(sids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        np.testing.assert_array_equal(base[3], got[hi])
        # the cap (0.5 * 4 rows = 2 resident rows) blocked the head on
        # highwater while raw pages were plentiful: only the
        # highwater-aware shortfall evicts here
        assert mgr.swap_outs > 0

    def test_slot_bound_urgent_head_pages_out_a_resident(
            self, params, reqs, base):
        # the SLOT is the binding constraint (pages ample): the
        # balance pass must still page a less-urgent resident out —
        # one victim frees a whole slot — or the urgent head waits
        # behind pages it cannot use (regression pin)
        mgr = ResidencyManager(host_blocks=8 * PPS,
                               policy=PriorityAwarePolicy())
        eng = _engine(params, 4, mgr, slots=2)
        sids = [eng.submit(p, b, priority=1) for p, b in reqs[:2]]
        eng.run(max_rounds=2)
        hi = eng.submit(reqs[3][0], reqs[3][1], priority=0)
        got = eng.run()
        for i, s in enumerate(sids):
            np.testing.assert_array_equal(base[i], got[s],
                                          err_msg=f"seq {i}")
        np.testing.assert_array_equal(base[3], got[hi])
        assert mgr.swap_outs > 0

    def test_prefetch_reservation_blocks_admission_theft(self, params,
                                                         reqs):
        # a staged pull's pages/slot are spoken for: _admissible must
        # refuse to hand them to a fresh admission mid-flight
        mgr = ResidencyManager(host_blocks=8 * PPS)
        eng = _engine(params, 2, mgr, slots=2)
        eng.submit(reqs[0][0], reqs[0][1], seq_id=0)
        for _ in range(3):
            eng.service_round()
        # one row active: a second same-size request would admit
        assert eng._admissible(PPS, fresh=True)
        # fabricate a staged pull occupying PPS pages + one slot
        eng._prefetching.append(
            (type("B", (), {"n_pages": PPS, "seq_id": 99})(), None,
             (0.0, 0, 0, 0.0, {})))
        try:
            assert eng._reserved_prefetch_pages() == PPS
            assert not eng._admissible(PPS, fresh=True)
            assert not eng.migration_admissible(PPS)
        finally:
            eng._prefetching.clear()

    def test_highwater_counts_reserved_prefetch_pages_as_used(
            self, params, reqs):
        # a staged pull WILL occupy its reserved pages at install: the
        # fresh-admission high-water math must count them as used, or
        # an admission squeaking under the mark breaches the headroom
        # once the swap-in seats (regression pin)
        mgr = ResidencyManager(host_blocks=8 * PPS)
        eng = _engine(params, 3, mgr, slots=3,
                      admit_highwater=2 * PPS / (3 * PPS))
        eng.submit(reqs[0][0], reqs[0][1], seq_id=0)
        for _ in range(2):
            eng.service_round()
        # one row resident; without a reservation a same-size fresh
        # request fits under the 2-row mark
        assert eng._admissible(PPS, fresh=True)
        eng._prefetching.append(
            (type("B", (), {"n_pages": PPS, "seq_id": 99})(), None,
             (0.0, 0, 0, 0.0, {})))
        try:
            # raw pages and slots still suffice — only the high-water
            # accounting of the reserved pages can refuse this
            assert not eng._admissible(PPS, fresh=True)
            assert eng._admissible(PPS, fresh=False)
        finally:
            eng._prefetching.clear()
