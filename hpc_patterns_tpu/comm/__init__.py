"""Distributed communication backend — TPU-native analog of the
reference's GPU-aware MPICH layer (SURVEY.md §2.3).

The reference passes device-resident buffers straight to
``MPI_Send/Recv/Allreduce`` (allreduce-mpi-sycl.cpp:173-182) over ranks
created by ``mpirun``. Here the "communicator" is a named axis of a
``jax.sharding.Mesh``; collectives are XLA collectives over ICI/DCN that
operate directly on HBM-resident sharded arrays — the TPU meaning of
"GPU-aware" (no host staging).

Two API levels:

- :mod:`hpc_patterns_tpu.comm.ring` + :mod:`~.collectives` +
  :mod:`~.fused` — *rank-local* functions used **inside**
  ``shard_map``: each takes the local shard and an axis name, exactly
  like the reference's per-rank functions take a device buffer and a
  communicator. ``fused`` is the device-initiated tier: Pallas kernels
  that run the ring schedule in-kernel over ``make_async_remote_copy``
  and overlap each hop with the consuming compute (docs/comm.md).
- :class:`~hpc_patterns_tpu.comm.communicator.Communicator` — array-level
  API over global ``jax.Array``\\ s: builds the ``shard_map`` for you, the
  analog of the miniapp main()s wiring buffers to MPI calls.
"""

from hpc_patterns_tpu.comm import collectives, fused, ring  # noqa: F401
from hpc_patterns_tpu.comm.communicator import Communicator  # noqa: F401
