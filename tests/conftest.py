"""Test configuration: force an 8-device virtual CPU mesh.

The reference tests on real hardware only (mpirun -np 4, SURVEY.md
section 4); the gap it leaves — hardware-free multi-device testing — is
closed here with XLA's host-platform device-count override, so every
distributed code path runs as 8-way SPMD on CPU.

Must run before any jax import, hence module-level env mutation in
conftest (pytest imports conftest first).
"""

import os
import sys
from pathlib import Path

# The axon TPU plugin (sitecustomize in PYTHONPATH) force-registers the
# real chip at interpreter start, before conftest runs — so jax is already
# imported; retarget it to CPU via config (works as long as no backend has
# been initialized yet).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache: shard_map compiles dominate suite wall
# time; warm reruns skip them entirely (first/cold run is unchanged).
# The directory is keyed by the HOST CPU's feature set: XLA:CPU loads
# AOT cache entries compiled on a different machine with only a
# warning ("could lead to execution errors such as SIGILL"), and a
# stale cross-machine cache did exactly that — reproducible SIGABRTs
# mid-suite (round 5; fresh cache = 18/18 green on the same tests).


def _cpu_fingerprint() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib

                    return hashlib.sha1(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return platform.machine() or "unknown"


# the jaxlib version joins the key: XLA:CPU loads entries from a
# different build with only a warning, and version drift risks more
# than the SIGILLs the CPU-flags fingerprint was added for. (Round 6
# note: six serving-test failures that vanished with a fresh cache
# looked like cache corruption but were a serving bug — a zero-copy
# np.asarray view of a buffer the engine then DONATED; cache-loaded
# executables honor the donation in place. Fixed in serving.py; the
# version keying stays as cheap defense-in-depth.)
import jaxlib  # noqa: E402

_cache_dir = (Path(__file__).resolve().parent.parent / ".cache"
              / f"jax-{_cpu_fingerprint()}-{jax.__version__}"
                f"-{jaxlib.__version__}")
jax.config.update("jax_compilation_cache_dir", str(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import pytest  # noqa: E402


def _markexpr_selects_slow(markexpr: str) -> bool:
    """True when the ``-m`` expression can select a slow-marked item —
    evaluated with pytest's own expression engine, so parenthesized and
    oddly-spaced forms (``not (slow)``, ``not  slow``) resolve the same
    way pytest's selection will, instead of a regex approximation."""
    if not markexpr:
        return False
    try:
        from _pytest.mark.expression import Expression

        expr = Expression.compile(markexpr)
        # Two conditions, both required:
        # 1. satisfiable by SOME slow-marked item — modeled as an item
        #    marked only 'slow' and one marked 'slow' plus everything
        #    else, so conjunctions like "slow and tpu" count;
        # 2. the expression actually MENTIONS 'slow' — the tier is
        #    explicit opt-in, so "not tpu" (satisfiable by a slow-only
        #    item, but not asking for slow) keeps the fast tier.
        names = set()

        def matcher(name, extra):
            names.add(name)
            return name == "slow" or extra

        sat = any(
            bool(expr.evaluate(lambda n, e=extra: matcher(n, e)))
            for extra in (False, True)
        )
        return sat and "slow" in names
    except Exception:
        # unparseable expression (pytest will error on it anyway):
        # keep the skip wiring out of the way
        return "slow" in markexpr


def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="include the slow tier (multi-process launches, big-model "
             "pipeline/MoE oracles); default tier targets < 5 min",
    )


def pytest_collection_modifyitems(config, items):
    # two-tier suite: `pytest -q` = fast tier (< 5 min on the 8-device
    # CPU mesh); `pytest -q --slow` (or `-m slow`) adds the rest. CI
    # runs both: `pytest -q && pytest -q -m slow`.
    # `-m slow` (and any expression a slow-marked item satisfies)
    # disables the skip; `-m "not slow"` and expressions that merely
    # contain the substring don't
    markexpr = config.getoption("-m") or ""
    if config.getoption("--slow") or _markexpr_selects_slow(markexpr):
        return
    skip = pytest.mark.skip(reason="slow tier (run with --slow or -m slow)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _poison_donated_serving(request):
    """Donation-poison harness (analysis/runtime.py): wraps the serving
    engine's donating jit entry points so a zero-copy host view of a
    donated buffer — the PR 2 "poisoned cache" bug class — fails
    LOUDLY on the CPU mesh instead of passing by backend luck (fresh
    CPU executables don't honor donations; cache-loaded ones do).

    Always on for tests/test_serving.py (the engine's oracle suite is
    exactly where an aliasing regression would otherwise hide) and
    tests/test_prefix_cache.py (a shared page aliased into a donated
    pool would corrupt EVERY reader at once — the highest-stakes
    surface for this bug class);
    ``HPC_PATTERNS_POISON_DONATED=1`` extends it to the whole suite."""
    if not (os.environ.get("HPC_PATTERNS_POISON_DONATED") == "1"
            or request.node.module.__name__ in ("test_serving",
                                                "test_prefix_cache")):
        yield
        return
    from hpc_patterns_tpu.analysis.runtime import install_serving_poison

    uninstall = install_serving_poison()
    try:
        yield
    finally:
        uninstall()


# Every live compiled executable keeps its JIT'd code pages mapped, and
# one full-suite process now compiles enough of them to exhaust the
# kernel's per-process map budget (vm.max_map_count, default 65530):
# the next mmap inside XLA's compiler fails and the process segfaults
# in backend_compile — observed at ~65k maps, ~85% through the fast
# tier, landing on whichever test happens to compile at that point.
# Dropping the jit caches unmaps retired executables (measured: 200
# small compiles cost ~600 maps; clear_caches + gc returns ~95% of
# them), and the persistent compile cache above makes the few
# re-compiles that follow cheap. The threshold leaves ~20k headroom —
# more than the heaviest single module allocates — so the guard fires
# at most a handful of times per run and never mid-test.
_MAP_PRESSURE_LIMIT = 45_000


def _memory_map_count() -> int:
    try:
        with open("/proc/self/maps") as f:
            return sum(1 for _ in f)
    except OSError:
        # non-Linux (no /proc): guard disabled — the platforms this
        # repo tests on are Linux, and macOS has no equivalent cap
        return 0


@pytest.fixture(autouse=True)
def _jax_map_pressure_guard():
    yield
    if _memory_map_count() > _MAP_PRESSURE_LIMIT:
        import gc

        jax.clear_caches()
        gc.collect()


@pytest.fixture(scope="session")
def mesh8():
    from hpc_patterns_tpu import topology

    return topology.make_mesh({"x": 8})


@pytest.fixture(scope="session")
def mesh_dp_sp_tp():
    from hpc_patterns_tpu import topology

    return topology.make_mesh({"dp": 2, "sp": 2, "tp": 2})
