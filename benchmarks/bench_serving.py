"""Continuous batching vs static batching: serving throughput.

Usage: python benchmarks/bench_serving.py [--n=N] [--slots=S] [--chunk=K]

The capacity story measured: a stream of N requests with VARIED
generation budgets served (a) statically — batches of ``slots`` rows
padded to the longest budget in the batch, every row paying the
longest row's wall clock — vs (b) the ContinuousBatcher, where a
finished row's pages free immediately and the next request enters at
the following chunk boundary.

Oracle on every run (benchmark-IS-the-test): the engine's per-sequence
tokens must equal standalone paged_generate before any number is
reported. Prints one summary line per mode plus the ratio.

On-chip protocol note: the engine's host loop pays a tunnel round trip
per chunk; ``--chunk`` amortizes it (the dispatch-amortization
discipline of benchmarks/bench_decode.py). Static batching runs its
whole scan in one dispatch — the comparison is honest serving reality
for both.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import ContinuousBatcher
from hpc_patterns_tpu.models.transformer import init_params


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def main():
    on_tpu = jax.default_backend() == "tpu"
    n = arg("n", 32 if on_tpu else 6)
    slots = arg("slots", 8 if on_tpu else 2)
    chunk = arg("chunk", 16 if on_tpu else 4)
    page_size = arg("page", 256 if on_tpu else 8)
    prompt_len = arg("prompt", 512 if on_tpu else 8)
    max_budget = arg("budget", 512 if on_tpu else 10)
    cfg = TransformerConfig(
        vocab=arg("vocab", 32768 if on_tpu else 64),
        d_model=arg("d", 1024 if on_tpu else 32),
        n_heads=arg("heads", 8 if on_tpu else 4),
        n_layers=arg("layers", 8 if on_tpu else 2),
        d_ff=arg("ff", 4096 if on_tpu else 64),
        max_seq=prompt_len + max_budget,
        dtype="bfloat16" if on_tpu else "float32",
        kv_cache_dtype=arg("cache", "compute", str),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(7)
    # budgets spread 1/4..4/4 of max: the static batch pays max, the
    # engine pays each row's own
    reqs = []
    for _ in range(n):
        prompt = rng.randint(0, cfg.vocab, size=prompt_len).astype(np.int32)
        budget = int(rng.choice([max(1, max_budget // 4),
                                 max(1, max_budget // 2), max_budget]))
        reqs.append((prompt, budget))
    pages_per_seq = -(-(prompt_len + max_budget) // page_size)
    total_tokens = sum(b for _, b in reqs)

    # --- static batching: group into batches of `slots`, pad budgets to
    # the batch max (the whole batch runs the longest row's scan)
    def run_static():
        outs = {}
        for i in range(0, n, slots):
            batch = reqs[i:i + slots]
            prompts = jnp.asarray(np.stack([p for p, _ in batch]))
            run_len = max(b for _, b in batch)
            toks = paged_generate(params, prompts, cfg, run_len,
                                  page_size=page_size)
            toks = np.asarray(toks)
            for j, (_, b) in enumerate(batch):
                outs[i + j] = toks[j, :b]
        return outs

    def run_engine():
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=slots * pages_per_seq,
            pages_per_seq=pages_per_seq, page_size=page_size, chunk=chunk,
        )
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[sid] for i, sid in enumerate(ids)}

    # warmup (compiles) then timed runs
    for fn in (run_static, run_engine):
        fn()
    t0 = time.perf_counter()
    static_out = run_static()
    t_static = time.perf_counter() - t0
    t0 = time.perf_counter()
    engine_out = run_engine()
    t_engine = time.perf_counter() - t0

    # oracle before any number is believed
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size))[0]
        np.testing.assert_array_equal(engine_out[i], want,
                                      err_msg=f"engine seq {i}")
        np.testing.assert_array_equal(static_out[i], want[:len(static_out[i])],
                                      err_msg=f"static seq {i}")
    print(f"serving: n={n} slots={slots} chunk={chunk} "
          f"prompt={prompt_len} budgets<=%d tokens={total_tokens}"
          % max_budget)
    print(f"  static  : {t_static:.3f}s  "
          f"{total_tokens / t_static:,.1f} tok/s")
    print(f"  engine  : {t_engine:.3f}s  "
          f"{total_tokens / t_engine:,.1f} tok/s")
    print(f"  engine/static speedup: {t_static / t_engine:.3f}x "
          "(oracle-exact)")


if __name__ == "__main__":
    main()
