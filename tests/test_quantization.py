"""Quantized decode (round 13): the paged_flash kernel parity battery,
the precision-law oracles, and the quantized-pool round trips.

Three claim tiers, one file:

- **route parity** (interpret mode): ``decode_attn="paged_flash"``
  (ops/paged_attention.py) reproduces the gather route — BITWISE on
  compute-dtype (f32/bf16) pools, tight tolerance on quantized
  (int8/fp8) ones, across page counts, partial last pages, permuted
  tables, ragged positions, bucket rungs, and tp shards;
- **the precision law** (models/quantization.py): token identity
  cannot hold ACROSS precisions, so quantized KV and int8 weights are
  pinned by teacher-forced greedy top-1 agreement + TV-distance
  bounds — and the oracle has teeth (a broken dequant fails it);
- **round trips**: quantized pools survive preemption-and-resume,
  migration (wire codec bit-identical, scales included), and the
  residency tier — with the byte accounting showing the capacity win
  (pushes move the QUANTIZED bytes, ~0.53x a bf16 pool).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import (
    _paged_attend_gather,
    _quantize_rows,
    init_paged_cache,
    paged_generate,
    paged_tail_prefill,
)
from hpc_patterns_tpu.models.quantization import (
    precision_law,
    quantize_weights_int8,
)
from hpc_patterns_tpu.models.serving import ContinuousBatcher, EngineCore
from hpc_patterns_tpu.models.transformer import (
    QUANT_SCALE_SUFFIX,
    matmul_weight,
)
from hpc_patterns_tpu.ops.paged_attention import paged_attention_decode

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32", decode_attn="gather")


def _setup(**over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _quantized_pools(key, n_pool, Hkv, P, D, kv_dtype):
    """Random pools in the requested storage dtype, with the per-row
    scale pools the quantized family carries (None for compute)."""
    kk, kv = jax.random.split(key)
    k = jax.random.normal(kk, (n_pool, Hkv, P, D), jnp.float32)
    v = jax.random.normal(kv, (n_pool, Hkv, P, D), jnp.float32)
    if kv_dtype in ("float32", "bfloat16"):
        dt = jnp.dtype(kv_dtype)
        return k.astype(dt), v.astype(dt), None, None
    qk, sk = _quantize_rows(k.reshape(-1, D), kv_dtype)
    qv, sv = _quantize_rows(v.reshape(-1, D), kv_dtype)
    return (qk.reshape(n_pool, Hkv, P, D),
            qv.reshape(n_pool, Hkv, P, D),
            sk.reshape(n_pool, Hkv, 1, P),
            sv.reshape(n_pool, Hkv, 1, P))


class TestPagedFlashKernelParity:
    """The interpret-mode parity battery: the exact-softmax kernel vs
    ``_paged_attend_gather`` on identical pools. Compute dtypes assert
    BITWISE equality (the kernel mirrors the gather math term for
    term); quantized dtypes are held to tight tolerance — the contract
    tier, since the dequant multiply order is the one place a backend
    may legally differ."""

    CFG = TransformerConfig(**BASE)

    def _battery(self, kv_dtype, pages, pos, *, permute=False, B=2,
                 Hkv=2, H=4, D=8, P=16):
        key = jax.random.PRNGKey(hash((kv_dtype, pages)) % (2 ** 31))
        q = jax.random.normal(key, (B, H, D), jnp.float32)
        kp, vp, ks, vs = _quantized_pools(
            jax.random.fold_in(key, 1), B * pages, Hkv, P, D, kv_dtype)
        ids = np.arange(B * pages, dtype=np.int32)
        if permute:
            ids = np.random.default_rng(3).permutation(ids)
        table = jnp.asarray(ids.reshape(B, pages), jnp.int32)
        cfg = dataclasses.replace(self.CFG, n_kv_heads=Hkv)
        scale = 1.0 / D ** 0.5
        want = _paged_attend_gather(q, kp, vp, ks, vs, table, pos, cfg,
                                    scale)
        got = paged_attention_decode(q, kp, vp, table, pos,
                                     k_scale_pool=ks, v_scale_pool=vs,
                                     scale=scale)
        if kv_dtype in ("float32", "bfloat16"):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
        else:
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want), atol=1e-6)

    @pytest.mark.parametrize("kv_dtype", ["float32", "bfloat16",
                                          "int8", "fp8"])
    @pytest.mark.parametrize("pages,pos", [
        (1, 9),     # single partial page
        (4, 37),    # mid-table, partial last live page
    ])
    def test_matches_gather_scalar_pos(self, kv_dtype, pages, pos):
        self._battery(kv_dtype, pages, jnp.int32(pos))

    @pytest.mark.parametrize("pages,pos", [
        (1, 0),     # single page, first position
        (4, 63),    # exactly full table
        (7, 40),    # live prefix well short of the allocation
    ])
    def test_matches_gather_grid_edges(self, pages, pos):
        # the grid-geometry edges need one dtype (the clamp/mask logic
        # is dtype-blind; the dtype sweep above covers the dequant)
        self._battery("float32", pages, jnp.int32(pos))

    @pytest.mark.parametrize("kv_dtype", ["float32", "int8", "fp8"])
    def test_matches_gather_ragged_and_permuted(self, kv_dtype):
        # ragged per-row positions over a PERMUTED table: each row
        # clamps/masks by its own fill, pages anywhere in the pool
        self._battery(kv_dtype, 4, jnp.array([5, 50], jnp.int32),
                      permute=True)

    def test_guards(self):
        q = jnp.zeros((2, 4, 8), jnp.float32)
        kp = jnp.zeros((8, 2, 16, 8), jnp.float32)
        table = jnp.zeros((2, 4), jnp.int32)
        sc = jnp.zeros((8, 2, 1, 16), jnp.float32)
        with pytest.raises(ValueError, match="refuses"):
            paged_attention_decode(q, kp, kp, table, jnp.int32(0),
                                   k_scale_pool=sc, v_scale_pool=sc)
        with pytest.raises(ValueError, match="needs"):
            paged_attention_decode(q, kp.astype(jnp.int8),
                                   kp.astype(jnp.int8), table,
                                   jnp.int32(0))
        with pytest.raises(ValueError, match="come together"):
            paged_attention_decode(q, kp.astype(jnp.int8),
                                   kp.astype(jnp.int8), table,
                                   jnp.int32(0), k_scale_pool=sc)
        with pytest.raises(ValueError, match="table rows"):
            paged_attention_decode(q, kp, kp, table[:1], jnp.int32(0))

    def test_mask_constant_matches_flash_routes(self):
        # the kernel cannot import ring_attention's constant (circular
        # via comm.ring -> ops) so it respells it; the bitwise
        # route-parity contract requires the spellings never drift
        from hpc_patterns_tpu.ops.paged_attention import (
            _NEG_INF as kernel_neg_inf,
        )
        from hpc_patterns_tpu.parallel.ring_attention import (
            _NEG_INF as flash_neg_inf,
        )

        assert kernel_neg_inf == flash_neg_inf


class TestPagedFlashRoute:
    """End to end through ``paged_decode_step``: swapping
    ``decode_attn`` between "gather" and "paged_flash" must not change
    a token — the prefill bytes are identical (paged_flash prefills on
    the einsum route like gather) and the kernel mirrors the step
    math."""

    @pytest.mark.parametrize("kv_dtype", ["compute", "int8", "fp8"])
    def test_token_identical_to_gather(self, kv_dtype):
        cfg, params = _setup(kv_cache_dtype=kv_dtype)
        pf = dataclasses.replace(cfg, decode_attn="paged_flash")
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                                    cfg.vocab, jnp.int32)
        want = np.asarray(paged_generate(params, prompt, cfg, 8,
                                         page_size=8))
        got = np.asarray(paged_generate(params, prompt, pf, 8,
                                        page_size=8))
        np.testing.assert_array_equal(got, want)

    def test_sampled_draws_identical_to_gather(self):
        cfg, params = _setup(kv_cache_dtype="int8")
        pf = dataclasses.replace(cfg, decode_attn="paged_flash")
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                                    cfg.vocab, jnp.int32)
        key = jax.random.PRNGKey(5)
        want = np.asarray(paged_generate(
            params, prompt, cfg, 6, page_size=8, key=key,
            temperature=0.7, top_k=16))
        got = np.asarray(paged_generate(
            params, prompt, pf, 6, page_size=8, key=key,
            temperature=0.7, top_k=16))
        np.testing.assert_array_equal(got, want)

    def test_engine_rung_coverage_oracle(self):
        # the serving route: a bucket ladder spreads admissions over
        # rungs (partial pages, varied table spans) and every served
        # sequence must equal standalone decode under the SAME config
        cfg, params = _setup(kv_cache_dtype="int8",
                             decode_attn="paged_flash")
        rng = np.random.RandomState(4)
        reqs = [(rng.randint(0, cfg.vocab,
                             size=int(rng.choice([5, 9, 14])))
                 .astype(np.int32), int(rng.choice([3, 7])))
                for _ in range(4)]
        eng = ContinuousBatcher(
            params, cfg, slots=2, pool_pages=12, pages_per_seq=6,
            page_size=8, chunk=2, prompt_buckets=(8, 16))
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        for i, (p, b) in enumerate(reqs):
            want = np.asarray(paged_generate(
                params, jnp.asarray(p)[None], cfg, b, page_size=8))[0]
            np.testing.assert_array_equal(got[ids[i]], want,
                                          err_msg=f"seq {i}")

    def test_tp_sharded_token_exact(self, mesh_dp_sp_tp):
        # the shard_map manual partition (whole kv-head blocks per
        # rank) over the paged_flash kernel — tokens identical to the
        # unsharded run, quantized pool included
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params = _setup(n_kv_heads=2, kv_cache_dtype="int8",
                             decode_attn="paged_flash")
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                                    cfg.vocab, jnp.int32)
        want = np.asarray(paged_generate(params, prompt, cfg, 6,
                                         page_size=8))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        got = np.asarray(jax.device_get(paged_generate(
            p_sh, prompt, cfg, 6, page_size=8, mesh=mesh_dp_sp_tp)))
        np.testing.assert_array_equal(got, want)


class TestPrecisionLaw:
    """The cross-precision contract: teacher-forced greedy agreement
    and TV-distance bounds per precision — and proof the oracle can
    actually fail."""

    PROMPTS = np.arange(3 * 12, dtype=np.int32).reshape(3, 12) % 60

    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_kv_precision_within_bounds(self, kv_dtype):
        cfg, params = _setup()
        qcfg = dataclasses.replace(cfg, kv_cache_dtype=kv_dtype)
        law = precision_law(params, cfg, params, qcfg, self.PROMPTS,
                            steps=4)
        law.check()
        assert law.steps == 4

    def test_weight_quant_within_bounds(self):
        cfg, params = _setup()
        qp = quantize_weights_int8(params)
        law = precision_law(params, cfg, qp, cfg, self.PROMPTS,
                            steps=4)
        law.check()

    def test_composed_kv_and_weights_within_bounds(self):
        cfg, params = _setup()
        qcfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
        qp = quantize_weights_int8(params)
        precision_law(params, cfg, qp, qcfg, self.PROMPTS,
                      steps=4).check()

    def test_oracle_has_teeth(self):
        # a broken dequant path (scales silently doubled) must FAIL
        # the law — otherwise the gate is a rubber stamp
        cfg, params = _setup()
        qp = quantize_weights_int8(params)
        broken = dict(qp)
        layers = dict(qp["layers"])
        layers["wqkv" + QUANT_SCALE_SUFFIX] = (
            layers["wqkv" + QUANT_SCALE_SUFFIX] * 2.0)
        broken["layers"] = layers
        law = precision_law(params, cfg, broken, cfg, self.PROMPTS,
                            steps=4)
        with pytest.raises(AssertionError, match="precision law"):
            law.check()

    def test_guards(self):
        cfg, params = _setup()
        with pytest.raises(ValueError, match="max_seq"):
            precision_law(params, cfg, params, cfg,
                          np.zeros((1, 60), np.int32), steps=8)


class TestQuantizedWeights:
    def test_structure_and_dequant_bound(self):
        cfg, params = _setup()
        qp = quantize_weights_int8(params)
        for name in ("wqkv", "wo", "w1", "w2"):
            w = qp["layers"][name]
            s = qp["layers"][name + QUANT_SCALE_SUFFIX]
            assert w.dtype == jnp.int8
            assert s.shape == w.shape[:1] + w.shape[2:]  # (L, d_out)
            # per-channel symmetric quantization error <= scale / 2
            orig = np.asarray(params["layers"][name], np.float32)
            deq = np.asarray(w, np.float32) * np.asarray(s)[:, None, :]
            assert np.all(np.abs(deq - orig)
                          <= np.asarray(s)[:, None, :] * 0.5 + 1e-7)
        assert qp["lm_head"].dtype == jnp.int8
        assert qp["embed"].dtype == params["embed"].dtype  # not a GEMM
        # dequant-at-use lands in the compute dtype
        got = matmul_weight(qp["layers"], "wo", jnp.float32)
        assert got.dtype == jnp.float32

    def test_accessor_is_identity_for_plain_params(self):
        cfg, params = _setup()
        w = matmul_weight(params["layers"], "wo", jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(w), np.asarray(params["layers"]["wo"],
                                      np.float32))

    def test_moe_refused(self):
        cfg = TransformerConfig(**{**BASE, "n_experts": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="MoE"):
            quantize_weights_int8(params)

    def _manual_dequant(self, qp):
        """The tree matmul_weight would produce at every site, with the
        scale keys dropped — running it through the model must then be
        IDENTICAL to running the quantized tree (same values feed the
        same dots), which catches any site still on the raw
        ``.astype`` spelling (it would apply ~±127 int8 magnitudes)."""
        layers = dict(qp["layers"])
        for name in ("wqkv", "wo", "w1", "w2"):
            layers[name] = matmul_weight(layers, name, jnp.float32)
            del layers[name + QUANT_SCALE_SUFFIX]
        deq = dict(qp, layers=layers)
        deq["lm_head"] = matmul_weight(deq, "lm_head", jnp.float32)
        del deq["lm_head" + QUANT_SCALE_SUFFIX]
        return deq

    def test_every_matmul_site_dequantizes(self):
        # the training-layer forward (wqkv/wo/w1/w2 + lm_head), the
        # chunked loss head, and the ragged-extend step (speculative
        # verification reads its logits) all serve the quantized tree
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_extend_step,
            paged_prefill,
        )
        from hpc_patterns_tpu.models.transformer import forward, loss_fn

        cfg, params = _setup()
        qp = quantize_weights_int8(params)
        deq = self._manual_dequant(qp)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0,
                                    cfg.vocab)
        np.testing.assert_array_equal(
            np.asarray(forward(qp, tokens, cfg)),
            np.asarray(forward(deq, tokens, cfg)))
        chunked = dataclasses.replace(cfg, loss_chunk=4)
        np.testing.assert_array_equal(
            np.asarray(loss_fn(qp, tokens, chunked)),
            np.asarray(loss_fn(deq, tokens, chunked)))
        prompt = tokens[:, :8]
        chunk = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        la = lb = None
        for p, store in ((qp, "a"), (deq, "b")):
            ca = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
            _, ca = paged_prefill(p, prompt, cfg, ca, 8)
            logits, _ = paged_extend_step(
                p, ca, jnp.array([8, 8], jnp.int32), chunk, cfg)
            la, lb = (logits, lb) if store == "a" else (la, logits)
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_pp_refuses_quantized_tree(self):
        from hpc_patterns_tpu.models.pp import pp_loss_and_grads

        cfg, params = _setup()
        qp = quantize_weights_int8(params)
        with pytest.raises(ValueError, match="int8-quantized"):
            pp_loss_and_grads(qp, jnp.zeros((2, 8), jnp.int32), cfg,
                              None, microbatches=1)


class TestQuantizedRoundTrips:
    """Preemption, migration, and the residency tier with quantized
    pools: the scales travel WITH their pages through every detach/
    attach path, bit-identically."""

    def _standalone(self, params, cfg, prompt, max_new):
        return np.asarray(paged_generate(
            params, jnp.asarray(prompt, jnp.int32)[None, :], cfg,
            max_new, page_size=8))[0]

    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_preempt_resume_token_exact(self, kv_dtype):
        cfg, params = _setup(kv_cache_dtype=kv_dtype)
        eng = ContinuousBatcher(
            params, cfg, slots=2, pool_pages=4, pages_per_seq=4,
            page_size=8, chunk=2, preempt=True,
            prompt_buckets=(8, 16, 24, 32))
        pA = np.arange(5, dtype=np.int32)
        pB = np.arange(8, dtype=np.int32) + 7
        a = eng.submit(pA, 20, priority=1)  # takes all 4 pages
        eng.run(max_rounds=3)
        b = eng.submit(pB, 4, priority=0)   # starved -> evicts A
        got = eng.run()
        assert eng.stats[a]["preemptions"] == 1
        np.testing.assert_array_equal(
            got[a], self._standalone(params, cfg, pA, 20))
        np.testing.assert_array_equal(
            got[b], self._standalone(params, cfg, pB, 4))

    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_migration_wire_roundtrip_bit_identical(self, kv_dtype):
        from hpc_patterns_tpu.serving_plane.migration import (
            bundle_from_wire,
            bundle_to_wire,
        )

        cfg, params = _setup(kv_cache_dtype=kv_dtype)
        donor = EngineCore(params, cfg, slots=1, pool_pages=6,
                           pages_per_seq=6, page_size=8, chunk=2,
                           prompt_buckets=(16,))
        prompt = np.arange(9, dtype=np.int32)
        donor.submit(prompt, 6)
        donor.service_round(decode=False)
        bundle = donor.export_migration(donor.exportable_slots()[0])
        # the wire carries dtype + scales: every payload leaf (int8 or
        # fp8 values AND the f32 scale pools) round-trips bit-exact
        assert {"k", "v", "k_scale", "v_scale"} <= set(
            bundle.pages_payload)
        wire = bundle_to_wire(bundle)
        back = bundle_from_wire(wire)
        for name, arrs in bundle.pages_payload.items():
            for a0, a1 in zip(arrs, back.pages_payload[name]):
                a0 = np.asarray(jax.device_get(a0))
                assert a0.dtype == a1.dtype
                np.testing.assert_array_equal(a0.view(np.uint8),
                                              a1.view(np.uint8),
                                              err_msg=name)
        dest = EngineCore(params, cfg, slots=1, pool_pages=6,
                          pages_per_seq=6, page_size=8, chunk=2,
                          prompt_buckets=(16,))
        dest.install_migration(back)
        while dest.has_work():
            dest.service_round()
        np.testing.assert_array_equal(
            dest.finished[bundle.seq_id],
            self._standalone(params, cfg, prompt, 6))

    def test_residency_moves_quantized_bytes(self):
        # the compound win the residency tier inherits: pushes move
        # the QUANTIZED bytes, so host-tier traffic (and with it the
        # prefetch windows) shrinks to ~0.53x of bf16 — asserted from
        # the manager's own byte counters on the SAME schedule
        from hpc_patterns_tpu.memory import (
            ColdAfterNPolicy,
            ResidencyManager,
        )

        def run_tier(kv_dtype):
            # a real head_dim (64): the per-page ratio is
            # 0.5 + itemsize(scale)/(2·head_dim), so a toy head_dim
            # would hide the win behind the scale-pool overhead
            cfg, params = _setup(
                d_model=64, n_heads=1,
                kv_cache_dtype=kv_dtype,
                **({"dtype": "bfloat16"} if kv_dtype == "compute"
                   else {}))
            mgr = ResidencyManager(host_blocks=64,
                                   policy=ColdAfterNPolicy(2))
            eng = ContinuousBatcher(
                params, cfg, slots=2, pool_pages=8, pages_per_seq=4,
                page_size=8, chunk=2, prompt_buckets=(8, 16),
                residency=mgr)
            rng = np.random.RandomState(5)
            reqs = [(rng.randint(0, cfg.vocab, size=7)
                     .astype(np.int32), 12) for _ in range(4)]
            ids = [eng.submit(p, b) for p, b in reqs]
            got = eng.run()
            for i, (p, b) in enumerate(reqs):
                np.testing.assert_array_equal(
                    got[ids[i]], self._standalone(params, cfg, p, b))
            return eng, mgr

        eng_q, mgr_q = run_tier("int8")
        eng_b, mgr_b = run_tier("compute")  # bf16 pool
        assert mgr_q.swap_outs > 0, "cap forced no paging"
        # per-page accounting: the quantized page is ~0.53x the bf16
        # page (values halve, f32 scales ride at D-times smaller)
        frac = eng_q._page_nbytes / eng_b._page_nbytes
        assert frac <= 0.55, frac
        # and the transfer pipeline moved quantized bytes, not a
        # dequantized copy — same schedule, same block counts
        assert mgr_q.swap_outs == mgr_b.swap_outs
        assert mgr_q.evict_bytes <= 0.55 * mgr_b.evict_bytes
        if mgr_b.prefetch_bytes:
            assert (mgr_q.prefetch_bytes
                    <= 0.55 * mgr_b.prefetch_bytes)


class TestRefusalsAndProbe:
    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_tail_prefill_refusal_stays_loud(self, kv_dtype):
        # satellite pin: the sharing path keeps refusing quantized
        # pools, and the message names the knob and the reason
        cfg, params = _setup(kv_cache_dtype=kv_dtype)
        cache = init_paged_cache(cfg, 1, 4, 8)
        with pytest.raises(ValueError) as ei:
            paged_tail_prefill(params, jnp.zeros((1, 8), jnp.int32),
                               cfg, cache, 8, 1)
        msg = str(ei.value)
        assert "kv_cache_dtype" in msg and kv_dtype in msg
        assert "docs/quantization.md" in msg

    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_prefix_cache_refusal_stays_loud(self, kv_dtype):
        cfg, params = _setup(kv_cache_dtype=kv_dtype)
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            EngineCore(params, cfg, slots=1, pool_pages=4,
                       pages_per_seq=4, page_size=8,
                       prompt_buckets=(8,), prefix_cache=True)

    def test_config_accepts_and_rejects(self):
        TransformerConfig(**{**BASE, "kv_cache_dtype": "fp8"})
        TransformerConfig(**{**BASE, "decode_attn": "paged_flash"})
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            TransformerConfig(**{**BASE, "kv_cache_dtype": "int4"})
        with pytest.raises(ValueError, match="decode_attn"):
            TransformerConfig(**{**BASE, "decode_attn": "paged"})

    def test_supports_fp8_probe_is_cached_bool(self):
        from hpc_patterns_tpu import dtypes

        got = dtypes.supports_fp8()
        assert isinstance(got, bool)
        assert dtypes.supports_fp8() is got  # memoized

    def test_kv_dtype_resolver_shared_definition(self):
        from hpc_patterns_tpu import dtypes
        from hpc_patterns_tpu.harness.cli import (
            KV_DTYPE_CHOICES,
            resolve_kv_cache_dtype,
        )

        assert KV_DTYPE_CHOICES == ("f32", "bf16", "int8", "fp8")
        assert resolve_kv_cache_dtype("f32") == ("float32", "compute")
        assert resolve_kv_cache_dtype("bf16") == ("bfloat16",
                                                  "compute")
        assert resolve_kv_cache_dtype("int8") == (None, "int8")
        # the degrade path: a backend without fp8 lands on int8 WITH a
        # note (never a deep XLA error)
        notes = []
        prev = dtypes._FP8_SUPPORT
        try:
            dtypes._FP8_SUPPORT = False
            assert resolve_kv_cache_dtype(
                "fp8", note=notes.append) == (None, "int8")
            assert notes and "degrading" in notes[0]
            dtypes._FP8_SUPPORT = True
            assert resolve_kv_cache_dtype("fp8") == (None, "fp8")
        finally:
            dtypes._FP8_SUPPORT = prev
        with pytest.raises(Exception, match="kv-dtype"):
            resolve_kv_cache_dtype("int4")
