"""Known-bad: Perfetto device-subtrack allocation drift. Two
TRACK_BANDS entries overlap (spinup starts inside migration's
width), a module hand-picks a track base integer instead of going
through the registry, a ``track_band()`` reference names a band the
registry never declared, and a literal ``track=`` argument lands
outside every declared band."""

TRACK_BANDS: dict[str, tuple[int, int]] = {
    "decode": (0, 1),
    "migration": (64, 8),  # EXPECT: track-band-collision
    "spinup": (70, 8),  # EXPECT: track-band-collision
}


def track_band(name):
    return TRACK_BANDS[name]


# the pre-registry idiom: a hand-picked base that collides the day
# someone widens a neighbouring band
MIG_TRACK_BASE = 90  # EXPECT: track-band-collision

MEM_TRACK_BASE, MEM_TRACKS = track_band("residency")  # EXPECT: track-band-collision


def mark(rec, t0):
    rec.mark_dispatch("migrate", t0, track=200)  # EXPECT: track-band-collision
