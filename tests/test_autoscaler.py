"""The elastic serving plane (serving_plane/autoscaler.py).

Two tiers. The CONTROLLER battery is jax-free and instant: the
Autoscaler is a pure function of its signal sequence, so hysteresis
(no flap at a steady boundary load), cooldown, the min/max clamps,
and determinism (same signals -> same decision log) pin directly.
The PLANE battery drives real engines on the tiny test model: an
involuntary replica death resumes every in-flight stream on survivors
byte-exact — greedy AND sampled (the checkpointed per-row key state)
— with a warm spin-up backfilling capacity, and a voluntary
scale-down DRAINS: queued work re-routes, in-flight rows migrate
through the PR 9 export/install path, nothing sheds."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import EngineCore
from hpc_patterns_tpu.serving_plane.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    ElasticServingPlane,
    Signals,
    WarmParamPool,
)
from hpc_patterns_tpu.serving_plane.router import Replica, ServingPlane


def sig(round_no, replicas, queued, *, attained=0, judged=0):
    return Signals(round=round_no, replicas=replicas, queued=queued,
                   active=0, attained=attained, judged=judged)


class TestAutoscalerPolicy:
    """The pure controller: jax-free, instant."""

    def test_scales_up_on_queue_pressure(self):
        a = Autoscaler(AutoscalerPolicy(min_replicas=1, max_replicas=4,
                                        up_queue=2.0, window=2))
        assert a.observe(sig(1, 2, 2)).action == "hold"  # mean 1.0
        assert a.observe(sig(2, 2, 10)).action == "up"   # mean 3.0

    def test_no_flap_at_steady_boundary_load(self):
        # pressure sitting EXACTLY on either threshold holds forever:
        # up only fires strictly above up_queue, down strictly below
        # down_queue — the hysteresis band is the no-flap guarantee
        p = AutoscalerPolicy(min_replicas=1, max_replicas=4,
                             up_queue=2.0, down_queue=1.0,
                             cooldown_rounds=0, window=1)
        a = Autoscaler(p)
        for r in range(20):
            assert a.observe(sig(r, 2, 4)).action == "hold"  # == up
        for r in range(20, 40):
            assert a.observe(sig(r, 2, 2)).action == "hold"  # == down
        # and anywhere inside the band holds too
        for r in range(40, 60):
            assert a.observe(sig(r, 2, 3)).action == "hold"

    def test_down_requires_empty_queue_and_recovered_attainment(self):
        p = AutoscalerPolicy(min_replicas=1, max_replicas=4,
                             down_queue=1.0, down_attainment=0.95,
                             cooldown_rounds=0, window=1)
        a = Autoscaler(p)
        # queue empty but attainment below the recovery bar: hold
        # (capacity is only returned once the SLO recovered)
        d = a.observe(sig(1, 3, 0, attained=8, judged=10))
        assert d.action == "up"  # 0.8 < up_attainment 0.9
        a2 = Autoscaler(p)
        d = a2.observe(sig(1, 3, 0, attained=10, judged=10))
        assert d.action == "down"

    def test_cooldown_blocks_consecutive_actions(self):
        p = AutoscalerPolicy(min_replicas=1, max_replicas=8,
                             up_queue=1.0, cooldown_rounds=3, window=1)
        a = Autoscaler(p)
        assert a.observe(sig(1, 2, 20)).action == "up"
        # pressure stays high, but the cooldown holds the next 3
        for r in range(2, 5):
            d = a.observe(sig(r, 3, 20))
            assert d.action == "hold" and "cooldown" in d.reason
        assert a.observe(sig(5, 3, 20)).action == "up"

    def test_min_clamp_outranks_cooldown(self):
        # a death below the floor must be replaceable THIS round, not
        # after waiting out the cooldown of the action that preceded it
        p = AutoscalerPolicy(min_replicas=2, max_replicas=4,
                             up_queue=1.0, cooldown_rounds=5, window=1)
        a = Autoscaler(p)
        assert a.observe(sig(1, 2, 20)).action == "up"
        d = a.observe(sig(2, 1, 0))  # replica died below min
        assert d.action == "up" and "min_replicas" in d.reason

    def test_max_clamp(self):
        p = AutoscalerPolicy(min_replicas=1, max_replicas=2,
                             up_queue=1.0, cooldown_rounds=0, window=1)
        a = Autoscaler(p)
        for r in range(10):
            assert a.observe(sig(r, 2, 50)).action == "hold"

    def test_attainment_drop_scales_up_without_queues(self):
        p = AutoscalerPolicy(min_replicas=1, max_replicas=4,
                             up_attainment=0.9, cooldown_rounds=0,
                             window=2)
        a = Autoscaler(p)
        d = a.observe(sig(1, 2, 0, attained=1, judged=4))
        assert d.action == "up" and "attainment" in d.reason

    def test_deterministic_given_signal_sequence(self):
        # the replay contract: the same signal trajectory produces the
        # same decision log, bit for bit
        p = AutoscalerPolicy(min_replicas=1, max_replicas=4,
                             up_queue=2.0, down_queue=0.5,
                             cooldown_rounds=2, window=3)
        rng = np.random.RandomState(3)
        trail = [sig(r, int(rng.randint(1, 5)), int(rng.randint(0, 12)),
                     attained=int(rng.randint(0, 4)), judged=3)
                 for r in range(40)]
        a, b = Autoscaler(p), Autoscaler(p)
        for s in trail:
            a.observe(s)
            b.observe(s)
        assert a.decisions == b.decisions

    def test_policy_validation(self):
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscalerPolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="hysteresis"):
            AutoscalerPolicy(up_queue=1.0, down_queue=1.0)
        with pytest.raises(ValueError, match="attainment"):
            AutoscalerPolicy(up_attainment=0.99, down_attainment=0.9)
        with pytest.raises(ValueError, match="window"):
            AutoscalerPolicy(window=0)


BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")
ENG = dict(slots=2, pool_pages=8, pages_per_seq=4, page_size=8,
           chunk=2)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**BASE)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _standalone(params, cfg, prompt, max_new, **kw):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8, **kw))[0]


def _elastic(cfg, params, *, n_replicas=2, policy=None, **skw):
    pool = WarmParamPool(params)
    factory = lambda p: EngineCore(p, cfg, **ENG, **skw)  # noqa: E731
    return ElasticServingPlane(
        [Replica(EngineCore(params, cfg, **ENG, **skw), name=f"r{i}")
         for i in range(n_replicas)],
        engine_factory=factory, warm_pool=pool,
        autoscaler=Autoscaler(policy or AutoscalerPolicy(
            min_replicas=n_replicas, max_replicas=n_replicas + 1,
            up_queue=1.5, cooldown_rounds=2)),
        slo={0: slolib.SLOTarget()})


class TestElasticPlane:
    def test_death_resume_byte_exact_greedy_and_spinup(self, setup):
        cfg, params = setup
        rng = np.random.RandomState(1)
        reqs = [(rng.randint(0, 64, size=6).astype(np.int32), 6)
                for _ in range(4)]
        chaoslib.configure("die:replica=1,at=1,site=replica_round")
        try:
            plane = _elastic(cfg, params)
            ids = [plane.submit(p, m) for p, m in reqs]
            got = plane.run()
            died = [e for e in chaoslib.injections()
                    if e["kind"] == "die"]
        finally:
            chaoslib.reset()
        assert died and died[0]["rank"] == 1  # the replica ordinal
        assert plane.deaths == ["r1"]
        assert plane.shed_on_death == 0 and plane.resumed
        # the min-clamp replaced the dead replica on WARM params and
        # the spin-up span was measured
        assert len(plane.spinup_s) >= 1
        assert all(s > 0 for s in plane.spinup_s)
        for rid, (p, m) in zip(ids, reqs):
            assert plane.stats[rid]["outcome"] == "ok"
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m),
                err_msg=f"rid {rid}")

    def test_death_resume_byte_exact_sampled_key_checkpoint(self, setup):
        # the PR 9 remainder: an INVOLUNTARY death resumes sampled
        # streams byte-exact because the plane checkpoints each row's
        # post-chunk key state every round — the resume seeds
        # _admit_row with it, exactly like a preemption snapshot
        cfg, params = setup
        skw = dict(temperature=0.8, top_k=8, seed=0)
        rng = np.random.RandomState(5)
        reqs = [(rng.randint(0, 64, size=6).astype(np.int32), 8)
                for _ in range(4)]
        chaoslib.configure("die:replica=0,at=1,site=replica_round")
        try:
            plane = _elastic(cfg, params, **skw)
            ids = [plane.submit(p, m) for p, m in reqs]
            got = plane.run()
        finally:
            chaoslib.reset()
        assert plane.deaths == ["r0"] and plane.resumed
        assert plane.shed_on_death == 0
        key_src = plane.replicas[1].engine
        for rid, (p, m) in zip(ids, reqs):
            assert plane.stats[rid]["outcome"] == "ok"
            np.testing.assert_array_equal(
                got[rid],
                _standalone(params, cfg, p, m,
                            key=key_src.request_key(rid),
                            temperature=0.8, top_k=8),
                err_msg=f"rid {rid}")
        # teeth: the resumed streams must include a row that had
        # already emitted tokens (a fresh re-run would diverge there
        # without the key checkpoint)
        assert any(plane.stats[r]["preemptions"] > 0
                   for r in plane.resumed)

    def test_scale_down_drains_by_migration_nothing_sheds(self, setup):
        # a voluntary drain: the victim stops receiving routing, its
        # in-flight rows EXPORT to survivors (PR 9 path), and it
        # retires once empty — byte-exact, zero shed
        cfg, params = setup
        rng = np.random.RandomState(9)
        reqs = [(rng.randint(0, 64, size=6).astype(np.int32), 12)
                for _ in range(3)]
        plane = _elastic(
            cfg, params, n_replicas=3,
            policy=AutoscalerPolicy(min_replicas=1, max_replicas=3,
                                    up_queue=50.0, down_queue=49.0,
                                    cooldown_rounds=0, window=1))
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        assert plane.drained and plane.retired
        assert plane.shed_on_death == 0
        assert plane.migrations >= 1  # in-flight rows moved, not shed
        for rid, (p, m) in zip(ids, reqs):
            assert plane.stats[rid]["outcome"] == "ok"
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m),
                err_msg=f"rid {rid}")

    def test_drain_never_strands_a_role(self, setup):
        # the last prefill-capable replica is not a drain candidate
        cfg, params = setup
        pool = WarmParamPool(params)
        plane = ElasticServingPlane(
            [Replica(EngineCore(params, cfg, **ENG), name="p",
                     role="prefill"),
             Replica(EngineCore(params, cfg, **ENG), name="d",
                     role="decode")],
            engine_factory=lambda p: EngineCore(p, cfg, **ENG),
            warm_pool=pool,
            autoscaler=Autoscaler(AutoscalerPolicy(
                min_replicas=1, max_replicas=2, up_queue=50.0,
                down_queue=49.0, cooldown_rounds=0, window=1)),
            slo={0: slolib.SLOTarget()})
        rid = plane.submit(np.arange(5, dtype=np.int32), 3)
        got = plane.run()
        assert not plane.drained  # neither role may be stranded
        np.testing.assert_array_equal(
            got[rid],
            _standalone(params, cfg, np.arange(5, dtype=np.int32), 3))

    def test_spinup_window_recorded_under_trace(self, setup):
        from hpc_patterns_tpu.harness import trace as tracelib

        cfg, params = setup
        rng = np.random.RandomState(11)
        reqs = [(rng.randint(0, 64, size=6).astype(np.int32), 6)
                for _ in range(4)]
        from hpc_patterns_tpu.serving_plane.autoscaler import (
            SPINUP_TRACK_BASE,
            SPINUP_TRACKS,
        )

        tracelib.configure(enabled=True)
        chaoslib.configure("die:replica=1,at=1,site=replica_round")
        try:
            plane = _elastic(cfg, params)
            for p, m in reqs:
                plane.submit(p, m)
            plane.run()
            events = list(tracelib.active().events)
        finally:
            chaoslib.reset()
            tracelib.configure(enabled=False)
        assert len(plane.spinup_s) >= 1
        # each spin-up is one dispatch→completion window on the
        # spinup subtrack band (between migration 64.. and mem 80..)
        wins = [e for e in events
                if e[0] == "X" and e[2] == "plane.spinup"]
        assert len(wins) == len(plane.spinup_s)
        lo = tracelib.TID_DEVICE + SPINUP_TRACK_BASE
        assert all(lo <= e[4] < lo + SPINUP_TRACKS for e in wins)
        assert all(e[5] > 0 for e in wins)  # a real measured span

    def test_warm_pool_is_residency_backed(self, setup):
        cfg, params = setup
        pool = WarmParamPool(params)
        # the parked copy lives in the HOST tier of a real manager
        assert pool.manager.host_blocks_used() > 0
        before = pool.manager.prefetch_bytes
        payload, handle = pool.pull()
        jax.block_until_ready(payload)
        pool.complete(handle)
        assert pool.manager.prefetch_bytes > before
        # pulled bytes are the parked bytes, exactly
        for a, b in zip(jax.tree.leaves(payload),
                        jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
