"""Report CLI smoke (harness/report.py) + the no-op guard.

The fixture is a checked-in two-snapshot sweep log
(tests/fixtures/report_fixture.jsonl) with known bucket counts, so the
aggregation rules — counters sum, gauges last-wins with min/max across
snapshots, histograms merge — are pinned against a stable input, and a
bucket-layout change cannot slip through unnoticed.
"""

import json
from pathlib import Path

import pytest

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import report
from hpc_patterns_tpu.harness.metrics import bucket_index, bucket_value

FIXTURE = Path(__file__).parent / "fixtures" / "report_fixture.jsonl"


@pytest.fixture(autouse=True)
def _fresh_registry():
    yield
    metricslib.configure(enabled=False)


class TestReportFixture:
    def test_aggregate_merges_snapshots(self):
        agg = report.aggregate(report.load_records([FIXTURE]))
        assert agg["n_snapshots"] == 2
        assert agg["results"] == (1, 1)
        # counters sum across snapshots
        assert agg["counters"]["train.steps"] == 30
        # gauges: last value from the later snapshot, min/max across
        g = agg["gauges"]["train.loss"]
        assert g.last == 3.2 and g.min == 3.2 and g.max == 6.9
        # histograms merge bucket counts: 50x1ms + 45x10ms + 5x100ms
        h = agg["histograms"]["span.measure.timed"]
        assert h.count == 100
        assert h.percentile(50) == bucket_value(bucket_index(0.001))
        assert h.percentile(95) == bucket_value(bucket_index(0.01))
        assert h.percentile(100) == 0.1

    def test_cli_smoke(self, capsys):
        rc = report.main([str(FIXTURE)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "merged 2 metrics snapshot(s)" in out
        assert "1 SUCCESS / 1 FAILURE" in out
        assert "train.steps" in out and "30" in out
        assert "span.measure.timed" in out

    def test_histogram_table_carries_p99(self, capsys):
        # SLO accounting judges tails; the per-phase table must show
        # them (p50/p95/p99/max since round 8). The fixture's
        # 50x1ms + 45x10ms + 5x100ms merge puts p99 in the 100ms
        # bucket where p95 still reads 10ms — the tail IS the signal
        agg = report.aggregate(report.load_records([FIXTURE]))
        h = agg["histograms"]["span.measure.timed"]
        # rank 99 lands in the 100ms bucket (95 at 10ms) — clamped to
        # the observed max per the percentile contract
        assert h.percentile(99) == 0.1
        assert h.percentile(99) > 2 * h.percentile(95)
        assert report.PERCENTILES == (50.0, 95.0, 99.0)
        rc = report.main([str(FIXTURE)])
        out = capsys.readouterr().out
        assert rc == 0 and "p99" in out

    def test_cli_no_metrics_records(self, tmp_path, capsys):
        # a plain runlog (no --metrics run) still gets a result summary
        path = tmp_path / "plain.jsonl"
        path.write_text(json.dumps(
            {"kind": "result", "name": "x", "success": True}) + "\n")
        rc = report.main([str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no kind=metrics snapshots" in out

    def test_kind_analysis_record_is_surfaced(self, tmp_path, capsys):
        # the jaxlint verdict (analysis --log) renders next to the
        # runtime rollups — one line per record, rule counts included
        path = tmp_path / "gate.jsonl"
        path.write_text("\n".join([
            json.dumps({"kind": "result", "name": "x", "success": True}),
            json.dumps({"kind": "analysis", "ok": False, "findings": 2,
                        "suppressed": 6, "baselined": 0, "files": 67,
                        "by_rule": {"donation-alias": 2}}),
        ]) + "\n")
        agg = report.aggregate(report.load_records([path]))
        assert agg["analyses"][0]["findings"] == 2
        rc = report.main([str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "analysis: FINDINGS — 2 finding(s)" in out
        assert "donation-alias=2" in out and "6 suppressed" in out

    def test_kind_trace_merged_record_is_surfaced(self, tmp_path,
                                                  capsys):
        # the launcher's cross-rank rollup (launch.py --trace-out /
        # harness.collect --log) renders as one digest line: rank
        # count, matched collectives, worst skew, straggler
        path = tmp_path / "merged.jsonl"
        path.write_text(json.dumps({
            "kind": "trace_merged", "num_processes": 2, "ranks": [0, 1],
            "n_ranks": 2, "n_events": 36, "n_matched": 3,
            "n_unmatched": 0,
            "align": {"method": "sync", "offsets_s": {},
                      "drift_bound_s": 0.0, "wall_disagreement_s": 0.0,
                      "residual_s": 0.0},
            "skew": {"allreduce.ring": {"n": 3,
                                        "max_start_skew_s": 0.000966,
                                        "mean_start_skew_s": 0.0005,
                                        "max_dur_skew_s": 0.0014}},
            "stragglers": {"0": {"last": 2, "of": 3},
                           "1": {"last": 1, "of": 3}},
            "busy": {"0": {"busy_frac": 0.5, "bubble_frac": 0.5,
                           "window_s": 1.0}},
            "out": "merged.json",
        }) + "\n")
        rc = report.main([str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "trace_merged: 2 rank(s), 3 collective(s) matched" in out
        assert "clock align: sync" in out
        assert "max start skew 0.966 ms (allreduce.ring)" in out
        assert "straggler rank 0 (2/3 last)" in out
        assert "merged.json" in out

    def test_trace_merged_schedule_verdict_is_rendered(self, tmp_path,
                                                       capsys):
        # the desync check travels in the trace_merged record; the
        # digest line must say at a glance whether the ranks PROVABLY
        # ran the same collective program — and name the break if not
        def rec(schedule):
            return {
                "kind": "trace_merged", "n_ranks": 2, "n_matched": 0,
                "n_unmatched": 0, "num_processes": 2, "ranks": [0, 1],
                "n_events": 0,
                "align": {"method": "sync"}, "skew": {},
                "stragglers": {}, "busy": {}, "schedule": schedule,
            }

        path = tmp_path / "merged.jsonl"
        path.write_text("\n".join([
            json.dumps(rec({"verdict": "consistent", "n_collectives": 5,
                            "n_ranks_recorded": 2, "digest": "ab12"})),
            json.dumps(rec({"verdict": "divergent", "n_collectives": 3,
                            "n_ranks_recorded": 2,
                            "first_divergence": {
                                "index": 17,
                                "ranks": {"0": {"op": "allreduce",
                                                "seq": 17},
                                          "1": {"op": "sendrecv_ring",
                                                "seq": 17}}}})),
        ]) + "\n")
        assert report.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "schedules consistent (5 collectives)" in out
        assert "SCHEDULE DIVERGENCE at #17" in out

    def test_cli_empty_input_fails(self, tmp_path, capsys):
        path = tmp_path / "empty.jsonl"
        path.write_text("")
        assert report.main([str(path)]) == 2
        capsys.readouterr()

    def test_layout_mismatch_skips_histograms(self, tmp_path, capsys):
        # a snapshot written under a different bucket layout cannot have
        # its bucket counts merged (indices mean different values);
        # counters/gauges are layout-independent and still merge
        records = report.load_records([FIXTURE])
        old = json.loads(json.dumps(
            next(r for r in records if r.get("kind") == "metrics")))
        old["bucket_layout"] = {"lo_decade": -6, "hi_decade": 3,
                                "per_decade": 8}
        path = tmp_path / "mixed.jsonl"
        path.write_text("".join(json.dumps(r) + "\n"
                                for r in records + [old]))
        agg = report.aggregate(report.load_records([path]))
        assert agg["n_snapshots"] == 3
        assert agg["n_layout_skipped"] == 1
        # histograms hold only the two current-layout snapshots
        assert agg["histograms"]["span.measure.timed"].count == 100
        # counters still summed across all three
        assert agg["counters"]["train.steps"] == 30 + old["counters"][
            "train.steps"]
        assert "different bucket layout" in report.format_report(agg)
        capsys.readouterr()

    def test_load_records_skips_truncated_line(self, tmp_path):
        # a crashed run can truncate its final record mid-write
        path = tmp_path / "torn.jsonl"
        path.write_text(json.dumps({"kind": "result", "success": True})
                        + '\n{"kind": "metr')
        records = report.load_records([path])
        assert len(records) == 1


class TestNoopGuard:
    def test_disabled_metrics_add_zero_records(self, tmp_path, capsys):
        """The tier-1 protection: without --metrics, an instrumented
        run writes exactly the records it always wrote — the registry
        is inert and no kind=metrics snapshot appears."""
        from hpc_patterns_tpu.harness.runlog import RunLog
        from hpc_patterns_tpu.harness.timing import measure
        from hpc_patterns_tpu.models.train import record_step_metrics

        m = metricslib.configure(enabled=False)
        log = RunLog(tmp_path / "run.jsonl")
        measure(lambda: None, repetitions=2, warmup=1, label="guard")
        record_step_metrics(0, 1.0, 0.1, 64)
        with metricslib.span("phase"):
            pass
        log.emit(kind="result", name="guard", success=True)
        records = [json.loads(l) for l in
                   (tmp_path / "run.jsonl").read_text().splitlines()]
        assert [r["kind"] for r in records] == ["result"]
        snap = m.snapshot()
        assert snap["counters"] == snap["gauges"] == snap["histograms"] == {}
        capsys.readouterr()

    def test_run_instrumented_disabled_emits_nothing(self, tmp_path):
        import argparse

        from hpc_patterns_tpu.apps import common
        from hpc_patterns_tpu.harness.runlog import RunLog

        path = tmp_path / "app.jsonl"
        args = argparse.Namespace(metrics=False, log=str(path))

        def fake_app(a):
            RunLog(a.log).emit(kind="result", name="app", success=True)
            return 0

        assert common.run_instrumented(fake_app, args) == 0
        kinds = [json.loads(l)["kind"]
                 for l in path.read_text().splitlines()]
        assert kinds == ["result"]

    def test_run_instrumented_enabled_appends_snapshot(self, tmp_path):
        import argparse

        from hpc_patterns_tpu.apps import common
        from hpc_patterns_tpu.harness.runlog import RunLog

        path = tmp_path / "app.jsonl"
        args = argparse.Namespace(metrics=True, log=str(path))

        def fake_app(a):
            log = RunLog(a.log)
            metricslib.get_metrics().counter("app.work").inc(7)
            log.emit(kind="result", name="app", success=True)
            return 0

        assert common.run_instrumented(fake_app, args) == 0
        records = [json.loads(l)
                   for l in path.read_text().splitlines()]
        assert [r["kind"] for r in records] == ["result", "metrics"]
        assert records[1]["counters"]["app.work"] == 7
        # and report aggregates the app log end to end
        agg = report.aggregate(records)
        assert agg["counters"]["app.work"] == 7


class TestBudgetRecords:
    def test_kind_slo_budget_renders_the_breach_table(self, tmp_path,
                                                      capsys):
        # budget.publish writes one kind=slo_budget record per
        # breached (class, axis, segment); the report renders them as
        # the per-class table, severity-sorted within a class
        path = tmp_path / "run.jsonl"
        path.write_text("\n".join([
            json.dumps({"kind": "slo_budget", "priority": 1,
                        "axis": "ttft", "segment": "queued",
                        "share": 0.5, "allowance_s": 0.25,
                        "n": 4, "breached": 1, "worst_s": 0.41,
                        "worst_seq_id": 9}),
            json.dumps({"kind": "slo_budget", "priority": 0,
                        "axis": "tpot", "segment": "prefetch_wait",
                        "share": 0.35, "allowance_s": 0.037,
                        "n": 5, "breached": 4, "worst_s": 0.133,
                        "worst_seq_id": 3}),
        ]) + "\n")
        agg = report.aggregate(report.load_records([path]))
        assert len(agg["budgets"]) == 2
        rc = report.main([str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert ("slo budget breaches: 2 "
                "(class axis segment: worst/allowance, count)") in out
        # class 0 sorts first; fields land in the labeled columns
        rows = [ln for ln in out.splitlines()
                if "prefetch_wait" in ln or "queued" in ln]
        assert "prefetch_wait" in rows[0] and "queued" in rows[1]
        assert "133ms" in rows[0] and "37ms" in rows[0]
        assert "4/5" in rows[0]
        assert "41" in rows[1].replace("410ms", "410")

    def test_no_budget_records_no_table(self, capsys):
        rc = report.main([str(FIXTURE)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo budget breaches" not in out
