"""Device-side KV migration (comm/migration_dma.py): the paired
remote-DMA transport's own contracts, below the plane-level oracle in
tests/test_serving_plane.py — reachability verdicts, the per-slab VMEM
gate, byte-exact transfer with destination residency at every pool
dtype, the install-side acceptance check, and the one-compile-per-
geometry cache."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.comm import migration_dma
from hpc_patterns_tpu.comm.migration_dma import (
    MigrationDmaError,
    dma_reachable,
    recv_migration,
    send_migration,
)
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.serving import EngineCore

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")
ENG = dict(slots=2, pool_pages=8, pages_per_seq=4, page_size=8,
           chunk=2)


def _bundle(device, **over):
    """One exportable bundle with its engine pinned to ``device``."""
    cfg = TransformerConfig(**{**BASE, **over})
    with jax.default_device(device):
        params = jax.device_put(init_params(jax.random.PRNGKey(0), cfg),
                                device)
        eng = EngineCore(params, cfg, **ENG)
        eng.submit(np.arange(5, dtype=np.int32), 4)
        eng.service_round(decode=False)
        return eng.export_migration(eng.exportable_slots()[0])


class TestReachability:
    def test_verdicts(self):
        d0, d1 = jax.devices()[:2]
        assert dma_reachable(d0, d1) == (True, "")
        ok, reason = dma_reachable(None, d1)
        assert not ok and "no committed device" in reason
        ok, reason = dma_reachable(d0, d0)
        assert not ok and "share one device" in reason

    def test_send_refuses_unreachable_pair(self):
        d0 = jax.devices()[0]
        b = _bundle(d0)
        with pytest.raises(MigrationDmaError, match="not DMA-reachable"):
            send_migration(b, d0, d0)


class TestTransfer:
    @pytest.mark.parametrize(
        "over", [{}, {"dtype": "bfloat16"},
                 {"kv_cache_dtype": "int8"}, {"kv_cache_dtype": "fp8"}],
        ids=["f32", "bf16", "int8", "fp8"])
    def test_payload_byte_exact_and_dst_resident(self, over):
        # every payload array (quantized pools ship their scale pools
        # as extra keys) arrives byte-identical AND committed to dst
        d0, d1 = jax.devices()[:2]
        b = _bundle(d0, **over)
        out = send_migration(b, d0, d1)
        assert out.transport == "dma"
        assert set(out.pages_payload) == set(b.pages_payload)
        for name, arrs in b.pages_payload.items():
            for a, a2 in zip(arrs, out.pages_payload[name]):
                assert a2.devices() == {d1}, f"{name} not on dst"
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(a2), err_msg=name)
        # cursor/key metadata rides untouched
        assert out.pos == b.pos and out.limit == b.limit
        np.testing.assert_array_equal(np.asarray(out.key),
                                      np.asarray(b.key))

    def test_exchange_cache_one_entry_per_geometry(self):
        d0, d1 = jax.devices()[:2]
        migration_dma._XFER_CACHE.clear()
        b = _bundle(d0)
        send_migration(b, d0, d1)
        n = len(migration_dma._XFER_CACHE)
        assert n >= 1
        b2 = _bundle(d0)
        send_migration(b2, d0, d1)  # same pool geometry: all hits
        assert len(migration_dma._XFER_CACHE) == n

    def test_vmem_gate_refuses_oversized_slab(self):
        d0, d1 = jax.devices()[:2]
        big = jnp.zeros(
            (1, migration_dma._VMEM_LIMIT // 8 + 16), jnp.float32)
        with pytest.raises(MigrationDmaError, match="VMEM"):
            migration_dma._transfer_array(
                jax.device_put(big, d0), d0, d1,
                page_chunk=migration_dma.PAGE_CHUNK, interpret=True)


class TestRecvAcceptance:
    def test_accepts_dma_bundle_on_dst(self):
        d0, d1 = jax.devices()[:2]
        out = send_migration(_bundle(d0), d0, d1)
        assert recv_migration(out, d1) is out

    def test_rejects_wrong_transport_and_wrong_device(self):
        d0, d1, d2 = jax.devices()[:3]
        b = _bundle(d0)
        with pytest.raises(MigrationDmaError, match="transport"):
            recv_migration(b, d1)  # never crossed the DMA pair
        out = send_migration(b, d0, d1)
        with pytest.raises(MigrationDmaError, match="not resident"):
            recv_migration(out, d2)  # landed on d1, installer is d2
        with pytest.raises(MigrationDmaError, match="no committed"):
            recv_migration(out, None)
