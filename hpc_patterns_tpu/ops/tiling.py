"""Shared Pallas tiling/lowering helpers.

Every Pallas call site in the tree had grown its own copy of three
decisions — how to shrink a requested block to fit an off-size length,
when to fall back to interpret mode, and how to spell
``CompilerParams`` across the ``TPUCompilerParams`` rename
(``ops/fused_mlp.py``, ``ops/flash_attention.py``, and now
``comm/fused.py``). One module owns them so a kernel added tomorrow
cannot disagree with the kernels that exist today.

The module also owns the **collective-id registry**
(:func:`collective_id`): every remote-DMA kernel that may run
concurrently with another must carry a distinct ``collective_id`` —
same-id kernels share barrier/DMA state on chip, and a collision hangs
or corrupts silently (interpret mode never exercises it). The ids used
to be hand-numbered 0-4 across ``comm/fused.py`` and
``parallel/ring_attention.py`` by convention; the registry assigns
them by NAME, so a collision is impossible by construction, and
pallaslint's ``collective-id-collision`` rule flags any site that
bypasses it with a magic number.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

#: name -> collective_id. Seeded with the historical 0-4 assignment so
#: the wire ids of the shipped kernels never move; new names derive
#: their id from the NAME itself (below), so every host of an SPMD job
#: computes the same id regardless of which kernel warms up first.
#: Names are dotted module-ish paths — the registry's job is
#: distinctness, the name's job is greppability.
_COLLECTIVE_IDS: dict[str, int] = {
    "comm.fused.permute": 0,
    "comm.fused.allreduce": 1,
    "comm.fused.allgather_matmul": 2,
    "parallel.ring_attention.kshift": 3,
    "parallel.ring_attention.vshift": 4,
    # the serving plane's device-side KV handoff (comm/migration_dma):
    # seeded so the exchange kernel's wire id is stable across hosts
    # from day one, like the original five
    "comm.fused.migration": 5,
}

#: new ids live in [_ID_FLOOR, _ID_FLOOR + _ID_SPAN): above the seeded
#: block, inside int32 (the CompilerParams field), with enough space
#: that name-hash collisions are a rename away from impossible
_ID_FLOOR = 16
_ID_SPAN = (1 << 20) - _ID_FLOOR


def _derived_id(name: str) -> int:
    import hashlib

    digest = hashlib.sha256(name.encode()).digest()
    return _ID_FLOOR + int.from_bytes(digest[:8], "big") % _ID_SPAN


def collective_id(name: str) -> int:
    """The registered ``collective_id`` for ``name``. Unseeded names
    get a name-derived id — a pure function of the string, so ids
    agree across hosts/processes whatever order kernels first run in
    (order-dependent assignment would be the cross-host wire mismatch
    this registry exists to prevent). Two kernels that may run
    concurrently simply register distinct names; nobody ever picks an
    integer. A hash collision between two registered names raises
    loudly (rename one) instead of silently sharing barrier state."""
    if name not in _COLLECTIVE_IDS:
        new_id = _derived_id(name)
        taken = {v: k for k, v in _COLLECTIVE_IDS.items()}
        if new_id in taken:
            raise ValueError(
                f"collective_id hash collision: {name!r} and "
                f"{taken[new_id]!r} both derive id {new_id} — rename "
                f"one (any change to the string re-rolls the id)")
        _COLLECTIVE_IDS[name] = new_id
    return _COLLECTIVE_IDS[name]


def registered_collective_ids() -> dict[str, int]:
    """Snapshot of the registry (tests assert distinctness and the
    pinned historical assignments)."""
    return dict(_COLLECTIVE_IDS)

# CompilerParams was TPUCompilerParams before the pallas.tpu rename;
# bind whichever this jax build exports
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

#: kwargs the older TPUCompilerParams class rejects — dropped with a
#: best-effort retry so one call shape serves both jax generations
_OPTIONAL_PARAMS = ("collective_id", "has_side_effects")


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` tolerant of the class rename
    AND of fields the older class lacks (``collective_id`` /
    ``has_side_effects`` are required for remote-DMA kernels on newer
    builds but unknown to some 0.4.x ones)."""
    kwargs = dict(kwargs)
    while True:
        try:
            return _COMPILER_PARAMS_CLS(**kwargs)
        except TypeError:
            for name in _OPTIONAL_PARAMS:
                if name in kwargs:
                    del kwargs[name]
                    break
            else:
                raise


def default_interpret() -> bool:
    """The tree-wide interpret default: compiled on TPU, interpreted
    everywhere else (the 8-device CPU mesh the test suite runs on)."""
    return jax.default_backend() != "tpu"


def fit_block_divisor(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= ``cap``: an off-size length
    gets a smaller even tile instead of a raw ValueError mid-trace.
    Always succeeds (1 divides everything; tiny blocks are slow, not
    wrong — Mosaic pads unaligned tiles). The fused-MLP fitting rule."""
    for b in range(min(cap, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def fit_block_pow2(block: int, n: int, *, floor: int = 128) -> int:
    """Clamp ``block`` to ``n`` and halve until it divides, floored at
    ``floor`` (the TPU lane width — smaller blocks would break tiling
    and waste the MXU). Lengths that no floor-multiple divides still
    fail the caller's validation — pad upstream. The flash-attention
    fitting rule (streamed kernels want big blocks; grid-step overhead
    amortizes over them)."""
    block = min(block, n)
    while n % block and block >= 2 * floor:
        block //= 2
    return block
