"""Dtype traits — TPU-native analog of the reference's
``aurora.mpich.miniapps/src/include/mpi_datatype.hpp`` (C9 in SURVEY.md).

The reference maps C++ scalar types to MPI datatypes via a trait template
with 10 specializations and an ``MPI_BYTE`` default (mpi_datatype.hpp:24-51).
XLA collectives are dtype-generic already, so the TPU equivalent is a
registry of *supported, tested* dtypes with their collective/compute
properties (bf16 is the MXU-native type; integer allreduce must be exact),
plus the same "default = bytes" escape hatch: any unlisted dtype is handled
by bitcasting to uint8 words, like the reference's MPI_BYTE default.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DtypeTraits:
    dtype: jnp.dtype
    itemsize: int
    exact_sum: bool  # integer/exact accumulate: validation uses ==, not isclose
    mxu_native: bool  # preferred MXU input type
    tolerance: float  # allreduce validation tolerance (reference: 1e-6,
    # allreduce-mpi-sycl.cpp:197)


def _t(dt, exact, mxu, tol) -> DtypeTraits:
    dt = jnp.dtype(dt)
    return DtypeTraits(dt, dt.itemsize, exact, mxu, tol)


# The 10 scalar specializations of mpi_datatype.hpp:28-51 map onto these.
REGISTRY: dict[str, DtypeTraits] = {
    "float32": _t(jnp.float32, False, False, 1e-6),
    "float16": _t(jnp.float16, False, False, 1e-2),
    "bfloat16": _t(jnp.bfloat16, False, True, 1e-2),
    "float64": _t(jnp.float64, False, False, 1e-12),  # x64 mode only
    "int8": _t(jnp.int8, True, False, 0.0),
    "int16": _t(jnp.int16, True, False, 0.0),
    "int32": _t(jnp.int32, True, False, 0.0),
    "int64": _t(jnp.int64, True, False, 0.0),  # x64 mode only
    "uint8": _t(jnp.uint8, True, False, 0.0),
    "uint32": _t(jnp.uint32, True, False, 0.0),
}


def get_traits(dtype) -> DtypeTraits:
    """Traits for ``dtype``; unlisted dtypes get the byte-default treatment
    (exact, bytewise), mirroring the reference's MPI_BYTE fallback
    (mpi_datatype.hpp:24-26)."""
    name = jnp.dtype(dtype).name
    if name in REGISTRY:
        return REGISTRY[name]
    dt = jnp.dtype(dtype)
    return DtypeTraits(dt, dt.itemsize, True, False, 0.0)


def validate_allreduce(result: np.ndarray, expected_scalar, dtype) -> bool:
    """The analytic-oracle check: every element equals the closed-form
    expected value (allreduce-mpi-sycl.cpp:192-204)."""
    traits = get_traits(dtype)
    if traits.exact_sum:
        # Compare in the original (integer) dtype — a float64 cast would
        # lose precision past 2**53 and false-PASS wrong int64 results.
        arr = np.asarray(result)
        return bool(np.all(arr == arr.dtype.type(expected_scalar)))
    arr = np.asarray(result, dtype=np.float64)
    expected = float(expected_scalar)
    bound = traits.tolerance + 1e-6 * abs(expected)  # atol + rtol form
    return bool(np.all(np.abs(arr - expected) <= bound))
