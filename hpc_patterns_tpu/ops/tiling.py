"""Shared Pallas tiling/lowering helpers.

Every Pallas call site in the tree had grown its own copy of three
decisions — how to shrink a requested block to fit an off-size length,
when to fall back to interpret mode, and how to spell
``CompilerParams`` across the ``TPUCompilerParams`` rename
(``ops/fused_mlp.py``, ``ops/flash_attention.py``, and now
``comm/fused.py``). One module owns them so a kernel added tomorrow
cannot disagree with the kernels that exist today.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

# CompilerParams was TPUCompilerParams before the pallas.tpu rename;
# bind whichever this jax build exports
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

#: kwargs the older TPUCompilerParams class rejects — dropped with a
#: best-effort retry so one call shape serves both jax generations
_OPTIONAL_PARAMS = ("collective_id", "has_side_effects")


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` tolerant of the class rename
    AND of fields the older class lacks (``collective_id`` /
    ``has_side_effects`` are required for remote-DMA kernels on newer
    builds but unknown to some 0.4.x ones)."""
    kwargs = dict(kwargs)
    while True:
        try:
            return _COMPILER_PARAMS_CLS(**kwargs)
        except TypeError:
            for name in _OPTIONAL_PARAMS:
                if name in kwargs:
                    del kwargs[name]
                    break
            else:
                raise


def default_interpret() -> bool:
    """The tree-wide interpret default: compiled on TPU, interpreted
    everywhere else (the 8-device CPU mesh the test suite runs on)."""
    return jax.default_backend() != "tpu"


def fit_block_divisor(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= ``cap``: an off-size length
    gets a smaller even tile instead of a raw ValueError mid-trace.
    Always succeeds (1 divides everything; tiny blocks are slow, not
    wrong — Mosaic pads unaligned tiles). The fused-MLP fitting rule."""
    for b in range(min(cap, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def fit_block_pow2(block: int, n: int, *, floor: int = 128) -> int:
    """Clamp ``block`` to ``n`` and halve until it divides, floored at
    ``floor`` (the TPU lane width — smaller blocks would break tiling
    and waste the MXU). Lengths that no floor-multiple divides still
    fail the caller's validation — pad upstream. The flash-attention
    fitting rule (streamed kernels want big blocks; grid-step overhead
    amortizes over them)."""
    block = min(block, n)
    while n % block and block >= 2 * floor:
        block //= 2
    return block
