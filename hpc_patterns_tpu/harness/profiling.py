"""Profiler hookup behind ``--enable_profiling`` (SURVEY.md §5).

The reference accepts ``--enable_profiling`` and sets the SYCL queue
profiling property, but never reads the per-event data — the flag only
checks that overlap survives profiling overhead (sycl_con.cpp:47-52,
run.sh:10-12). The TPU build keeps the flag and its overhead-check role,
and *actually produces artifacts*: a ``jax.profiler`` trace directory
(TensorBoard/XProf-loadable) per run.
"""

from __future__ import annotations

import contextlib
import tempfile

import jax


@contextlib.contextmanager
def maybe_trace(enabled: bool, logdir: str | None = None):
    """Trace the enclosed region with ``jax.profiler`` when ``enabled``.

    Yields the trace directory (or None when disabled), so callers can
    surface it in the run log — the upgrade over the reference's
    write-only property.

    Inside the traced region, metric spans (harness/metrics.py) mirror
    into ``jax.profiler.TraceAnnotation`` regardless of whether the
    registry records, so the XProf timeline and the JSONL snapshot name
    the same phases.
    """
    if not enabled:
        yield None
        return
    from hpc_patterns_tpu.harness import metrics, trace

    logdir = logdir or tempfile.mkdtemp(prefix="hpcpat_trace_")
    m = metrics.get_metrics()
    prev = m.mirror_traces
    m.mirror_traces = True
    rec = trace.active()
    t0 = rec.mark_dispatch("profiler.trace",
                           {"logdir": logdir}) if rec else 0.0
    try:
        with jax.profiler.trace(logdir):
            yield logdir
    finally:
        # restore in a finally so an exception inside the traced
        # region can't leave the registry permanently mirroring every
        # span into TraceAnnotations (tested by
        # tests/test_trace.py::test_maybe_trace_restores_on_raise).
        # Restored on the CAPTURED registry object: if the region
        # installed a fresh one (metrics.configure), that registry
        # owns its own mirror_traces and is left alone.
        m.mirror_traces = prev
        if rec:
            # the profiler region lands on the flight-recorder device
            # track too, so a timeline shows when XProf was active
            rec.mark_complete("profiler.trace", t0, {"logdir": logdir})
