"""Prefetch loader tests + pipeline-parallel TRAINING (gradient) test."""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.topology import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu import parallel
from hpc_patterns_tpu.utils.data import PrefetchLoader, synthetic_tokens


class TestPrefetchLoader:
    def test_yields_all_batches_in_order(self):
        batches = [np.full((4,), i, np.float32) for i in range(10)]
        out = list(PrefetchLoader(batches, depth=3))
        assert len(out) == 10
        for i, b in enumerate(out):
            assert float(b[0]) == i
            assert isinstance(b, jax.Array)

    def test_worker_error_propagates(self):
        def bad():
            yield np.zeros(2)
            raise RuntimeError("corrupt shard")

        with pytest.raises(RuntimeError, match="corrupt shard"):
            list(PrefetchLoader(bad()))

    def test_custom_placer(self):
        dev = jax.devices()[0]
        loader = PrefetchLoader(
            [np.zeros((2,), np.float32)], place=lambda b: jax.device_put(b, dev)
        )
        (out,) = list(loader)
        assert out.devices() == {dev}

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            PrefetchLoader([], depth=0)

    def test_synthetic_tokens_shapes(self):
        batches = list(synthetic_tokens(
            jax.random.PRNGKey(0), batch=2, seq=8, vocab=100, steps=3
        ))
        assert len(batches) == 3
        assert all(b.shape == (2, 8) for b in batches)
        assert all(0 <= b.min() and b.max() < 100 for b in batches)


class TestTokenFiles:
    def _file(self, tmp_path, n=1000, dtype="uint16"):
        from hpc_patterns_tpu.utils.data import write_token_file

        toks = np.arange(n)  # token value == file position
        path = tmp_path / "toks.bin"
        write_token_file(path, toks, dtype)
        return path, toks

    def test_memmap_windows_are_file_slices(self, tmp_path):
        from hpc_patterns_tpu.utils.data import memmap_tokens

        path, toks = self._file(tmp_path)
        for batch in memmap_tokens(path, batch=4, seq=16, steps=3, seed=1):
            assert batch.shape == (4, 16) and batch.dtype == np.int32
            for row in batch:
                # value == position, so a window is valid iff contiguous
                start = int(row[0])
                np.testing.assert_array_equal(row, toks[start:start + 16])

    def test_sequential_walk_covers_in_order(self, tmp_path):
        from hpc_patterns_tpu.utils.data import memmap_tokens

        path, toks = self._file(tmp_path)
        it = memmap_tokens(path, batch=2, seq=8, steps=2, sequential=True)
        a = next(it)
        np.testing.assert_array_equal(a[0], toks[0:8])
        np.testing.assert_array_equal(a[1], toks[8:16])

    def test_range_and_size_validation(self, tmp_path):
        from hpc_patterns_tpu.utils.data import (
            memmap_tokens,
            write_token_file,
        )

        with pytest.raises(ValueError, match="range"):
            write_token_file(tmp_path / "x.bin", [70000], "uint16")
        path, _ = self._file(tmp_path, n=10)
        with pytest.raises(ValueError, match="tokens"):
            next(memmap_tokens(path, batch=1, seq=32))
        with pytest.raises(ValueError, match="vocab"):
            next(memmap_tokens(path, batch=2, seq=4, vocab=5))

    def test_last_token_reachable(self, tmp_path):
        from hpc_patterns_tpu.utils.data import memmap_tokens

        # n == seq: exactly one window, covering the whole file
        path, toks = self._file(tmp_path, n=8)
        batch = next(memmap_tokens(path, batch=2, seq=8))
        np.testing.assert_array_equal(batch[0], toks)
        np.testing.assert_array_equal(batch[1], toks)


class TestAccumAndSchedules:
    @pytest.mark.slow  # two multi-step compiled train loops
    def test_accum_matches_big_batch(self):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.train import (
            init_train_state,
            make_train_step,
        )

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16, dtype="float32")
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64,
                                    "int32")
        p0, s0 = init_train_state(jax.random.PRNGKey(0), cfg)
        loss_a, pa, _ = make_train_step(cfg)(p0, s0, tokens)
        p1, s1 = init_train_state(jax.random.PRNGKey(0), cfg)
        loss_b, pb, _ = make_train_step(cfg, accum_steps=4)(p1, s1, tokens)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_cosine_schedule_validates(self):
        from hpc_patterns_tpu.models.train import make_optimizer

        with pytest.raises(ValueError, match="total_steps"):
            make_optimizer(schedule="cosine", warmup_steps=10, total_steps=5)
        make_optimizer(schedule="cosine", warmup_steps=2, total_steps=10)
        with pytest.raises(ValueError, match="schedule"):
            make_optimizer(schedule="linear")

    def test_accum_validation(self):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.train import make_train_step

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16, dtype="float32")
        with pytest.raises(ValueError, match="accum_steps"):
            make_train_step(cfg, accum_steps=0)


class TestPipelineTraining:
    @pytest.mark.slow  # two multi-step compiled training runs
    def test_pipeline_gradients_match_sequential(self, mesh8):
        """PP must work for training, not just inference: gradients
        through the ring handoffs equal the sequential model's."""
        M, B, F = 4, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (M, B, F))
        ws = jax.random.normal(jax.random.PRNGKey(1), (8, F, F)) / 4

        def stage(w, h):
            return jnp.tanh(jnp.dot(h, w))

        def seq_loss(ws):
            h = x
            for r in range(8):
                h = stage(ws[r], h)
            return jnp.mean(jnp.square(h))

        def pp_loss(ws):
            def local(x_all, w):
                outs = parallel.pipeline_forward(stage, w[0], x_all, "x")
                me = jax.lax.axis_index("x")
                # loss lives on the last stage; psum broadcasts it
                mine = jnp.where(me == 7, jnp.mean(jnp.square(outs)), 0.0)
                return jax.lax.psum(mine, "x")[None]

            per_rank = shard_map(
                local, mesh=mesh8,
                in_specs=(P(), P("x", None, None)),
                out_specs=P("x"),
            )(x, ws)
            return per_rank[0]

        want = jax.grad(seq_loss)(ws)
        got = jax.jit(jax.grad(pp_loss))(ws)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)
        # losses agree too
        assert float(pp_loss(ws)) == pytest.approx(float(seq_loss(ws)), rel=1e-5)
