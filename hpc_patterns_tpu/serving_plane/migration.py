"""KV-page migration transfer: the serving plane's handoff wire.

The bundle itself (cursors, sampling key state, gathered KV pages) is
built by :meth:`~hpc_patterns_tpu.models.serving.EngineCore.
export_migration` and consumed by :meth:`~hpc_patterns_tpu.models.
serving.EngineCore.install_migration`; this module owns what happens
BETWEEN the two engines:

- :func:`migrate_pages` — the in-process transfer: ``jax.device_put``
  of every page payload onto the destination replica's device,
  dispatched asynchronously so the copy flies while the destination's
  decode chunk computes (the ICI analog of the reference's
  hide-traffic-behind-compute pattern; replicas sharing one device
  pass through untouched — the copy would be a no-op).
- :func:`bundle_to_wire` / :func:`bundle_from_wire` — the byte codec
  the cross-process plane (``serving_plane/service.py``) ships over
  its sockets: raw little-endian buffers base64-wrapped in JSON, with
  shape/dtype alongside, so the decode side reconstructs bit-identical
  arrays (the disaggregation oracle crosses the wire intact).

``migrate_pages`` is a COLLECTIVE in the schedule-verifier sense: both
sides of a handoff fingerprint ``(kv_migration, seq)`` into their
hash chains (``analysis/runtime.py``), so a router/replica desync —
a bundle exported but never installed, or installed out of order —
is caught at merge time exactly like a diverged allreduce schedule.
shardlint knows the name (``_COLLECTIVE_NAMES``) for the same reason.
"""

from __future__ import annotations

import base64
from dataclasses import replace

import numpy as np

from hpc_patterns_tpu.harness import reqtrace
from hpc_patterns_tpu.models.serving import MigrationBundle


def migrate_pages(bundle: MigrationBundle, device=None) -> MigrationBundle:
    """Dispatch the KV-page transfer toward the destination replica.

    With ``device`` set (replicas on distinct devices), every payload
    array is ``jax.device_put`` onto it — an ASYNC copy that the
    destination's in-flight decode chunk hides; the returned bundle's
    payload holds the destination-resident futures. ``device=None``
    (replicas sharing a device) passes the bundle through — the
    install's scatter consumes the gathered arrays in place."""
    if device is None:
        return bundle
    import jax

    payload = {
        name: tuple(jax.device_put(a, device) for a in arrs)
        for name, arrs in bundle.pages_payload.items()
    }
    return replace(bundle, pages_payload=payload,
                   transport="device_put")


# ---------------------------------------------------------------------------
# wire codec (shared with the jax-free socket plane)
# ---------------------------------------------------------------------------

#: The fields a wire dict MUST carry — reading one with ``wire["k"]``
#: (absent-INTOLERANT) is legal only for names listed here; every
#: other field must be read with ``.get()`` or an ``in`` guard, so an
#: old donor's artifact never kills a new receiver (the round-17
#: ``transport`` / round-18 ``segments`` compatibility discipline).
#: contractlint's ``wire-field-compat`` enforces this statically. The
#: last three are the per-array codec's own envelope
#: (``_arr_to_wire``/``_arr_from_wire``).
REQUIRED_WIRE_FIELDS = (
    "seq_id", "prompt", "out", "prefix", "budget", "pos", "limit",
    "token", "key", "temp", "priority", "t_submit", "n_pages",
    "page_size", "payload",
    "shape", "dtype", "b64",
)


def _arr_to_wire(a) -> dict:
    a = np.asarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "b64": base64.b64encode(np.ascontiguousarray(a).tobytes())
            .decode("ascii")}


def _np_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype name, including the extended-precision
    family (bfloat16, float8_e4m3fn — quantized/bf16 KV pools cross
    the wire too): plain numpy only knows them once ``ml_dtypes`` has
    registered its types, and the socket plane's receiver may be a
    jax-free process that never imported it implicitly."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8_* with numpy

        return np.dtype(getattr(ml_dtypes, name))


def _arr_from_wire(d) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=_np_dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


def bundle_to_wire(bundle: MigrationBundle) -> dict:
    """JSON-able dict for the socket plane. Device payloads are read
    back here — the wire path IS the host-staged DCN analog; the
    in-process path never calls this."""
    return {
        "seq_id": int(bundle.seq_id),
        "prompt": _arr_to_wire(bundle.prompt),
        "out": [int(t) for t in bundle.out],
        "prefix": [int(t) for t in bundle.prefix],
        "budget": int(bundle.budget),
        "pos": int(bundle.pos), "limit": int(bundle.limit),
        "token": int(bundle.token),
        "key": _arr_to_wire(bundle.key),
        "temp": float(bundle.temp),
        "temp_override": bundle.temp_override,
        "priority": int(bundle.priority),
        "deadline_s": bundle.deadline_s,
        "t_submit": float(bundle.t_submit),
        "t_first": bundle.t_first,
        "preemptions": int(bundle.preemptions),
        "n_pages": int(bundle.n_pages),
        "page_size": int(bundle.page_size),
        "payload": {
            name: [_arr_to_wire(a) for a in arrs]
            for name, arrs in bundle.pages_payload.items()
        },
        "seq": int(bundle.seq),
        # prefix-resolution metadata (round 12): the page-aligned
        # pure-prompt span and the rung its bytes were computed at — a
        # sharing destination resolves the span against its own radix
        # index instead of installing those payload pages (the payload
        # still carries them: a cold cache materializes, byte-exact
        # either way — docs/prefix_cache.md)
        "rung": int(bundle.rung),
        "prefix_len": int(bundle.prefix_len),
        "transport": str(bundle.transport),
        # request-lifecycle history (harness/reqtrace.py): compact
        # [kind, t0, t1, meta] lists, already JSON — ALWAYS written
        # (null when the donor traced nothing), so absence below means
        # a legacy artifact, not a disabled tracer
        "segments": ([list(s) for s in bundle.segments]
                     if bundle.segments is not None else None),
    }


def bundle_from_wire(wire: dict) -> MigrationBundle:
    """Reconstruct a bundle bit-identically from its wire dict."""
    return MigrationBundle(
        seq_id=int(wire["seq_id"]),
        prompt=_arr_from_wire(wire["prompt"]),
        out=list(wire["out"]), prefix=list(wire["prefix"]),
        budget=int(wire["budget"]),
        pos=int(wire["pos"]), limit=int(wire["limit"]),
        token=int(wire["token"]),
        key=_arr_from_wire(wire["key"]),
        temp=float(wire["temp"]),
        temp_override=wire.get("temp_override"),
        priority=int(wire["priority"]),
        deadline_s=wire.get("deadline_s"),
        t_submit=float(wire["t_submit"]),
        t_first=wire.get("t_first"),
        preemptions=int(wire.get("preemptions") or 0),
        n_pages=int(wire["n_pages"]),
        page_size=int(wire["page_size"]),
        pages_payload={
            name: tuple(_arr_from_wire(a) for a in arrs)
            for name, arrs in wire["payload"].items()
        },
        seq=int(wire.get("seq", -1)),
        rung=int(wire.get("rung", 0)),
        prefix_len=int(wire.get("prefix_len", 0)),
        # pre-transport-field artifacts crossed a socket by definition
        transport=str(wire.get("transport", "wire")),
        # pre-segments-field artifacts decode to ONE untracked segment
        # (reqtrace.LEGACY_SEGMENTS): the donor-side life is a measured
        # unattributed span, not a silent gap; an explicit null means
        # the donor ran with tracing off
        segments=(tuple(tuple(s) for s in wire["segments"])
                  if wire.get("segments") is not None
                  else None if "segments" in wire
                  else reqtrace.LEGACY_SEGMENTS),
    )
