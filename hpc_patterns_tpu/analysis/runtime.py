"""Runtime complements to the static rules: donation poisoning, the
collective schedule verifier, and the strict-semaphore interpret shim.

Three helpers live here, each the belt-and-braces RUNTIME check behind
a static rule family:

**Donation poisoning** (:func:`poison_donated`, behind
``donation-alias``). The hazard (round 6's "poisoned cache"): on CPU a
freshly-built executable often does NOT honor a donation, so a
zero-copy host view of a donated input keeps reading stable values and
the bug passes every test — until a cache-loaded (or TPU) executable
honors the donation and mutates the view in place, corrupting whatever
bookkeeping was built on it. ``poison_donated`` removes the luck: it
wraps a jitted function and, after each call completes, overwrites
every donated input buffer that the executable did NOT alias into an
output with a sentinel byte pattern. Wiring: ``tests/conftest.py``
installs the wrappers around the serving engine's jitted entry points
for ``tests/test_serving.py`` (always) and for the whole suite under
``HPC_PATTERNS_POISON_DONATED=1``.

**Collective schedule verification** (:class:`CollectiveSchedule`,
behind ``collective-divergence``/``collective-order``). The hazard is
the reference suite's silent MPI deadlock: SPMD ranks disagreeing on
which collective comes next hang with no error. Statically the
shardlint rules forbid the divergence-shaped code; at runtime every
eager ``Communicator`` collective (and every recorder-traced
``harness.timing.measure`` repetition) is fingerprinted into a
per-rank hash chain over ``(op, seq, shape, dtype, axis)``. The
running digest is stamped into flight-recorder snapshots
(``harness/trace.py``) and cross-checked at merge time
(``harness/collect.py``): equal digests PROVE the rank schedules
matched; on mismatch the merge names the first divergent
``(rank, op, seq)``. Under ``apps/launch.py`` the chain additionally
persists a tiny per-rank progress file on every record, so a TIMED-OUT
rank's position is readable post-mortem — a hang reads as "rank 2 is
at allreduce#17, rank 0 at sendrecv_ring#17" instead of a dead tunnel.

**Strict semaphores** (:func:`strict_semaphores`, behind
``dma-sem-balance``/``dma-slot-reuse``). The hazard is PR 8's
chip-only class: interpret mode serializes DMAs and leaves semaphores
inert, so a double-waited send sem or an undrained DMA passes every
CPU test and deadlocks on silicon. Under the shim, every
``make_async_copy``/``make_async_remote_copy`` built while a
``pallas_call`` kernel body traces is counted — starts and waits per
semaphore channel, plus per-descriptor wait multiplicity — and the
ledger must balance exactly at kernel-body exit or the TEST fails
(:class:`SemaphoreBalanceError`), not the chip session. Wiring:
``tests/test_fused_comm.py`` installs it module-wide, so the whole
fused parity battery re-proves the sync protocol on every run.

This module is import-light on purpose (stdlib only; jax is imported
inside the poison helpers): the schedule verifier must be usable from
jax-free launcher children and from ``harness/trace.py``, whose
disabled path stays jax-free at import time.
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import json
import os
import threading
from collections import deque

#: sentinel byte: 0xAB patterns decode to huge-magnitude garbage in
#: every dtype we serve (int32 -1414812757, implausible floats), so a
#: poisoned read corrupts comparisons instead of looking plausible
SENTINEL_BYTE = 0xAB

#: env names mirroring ``topology.ENV_TRACE_DIR`` / ``ENV_PROCESS_ID``
#: — duplicated as literals so this module stays importable without
#: jax (topology imports jax at module scope); tests assert the pair
#: stays in sync with topology's constants.
ENV_TRACE_DIR = "HPCPAT_TRACE_DIR"
ENV_PROCESS_ID = "HPCPAT_PROCESS_ID"

#: chain entries retained per process (the digest always covers the
#: FULL history; the window only bounds what a snapshot can name)
SCHEDULE_WINDOW = 4096


# ---------------------------------------------------------------------------
# collective schedule verifier
# ---------------------------------------------------------------------------


class CollectiveSchedule:
    """Per-rank hash chain over collective fingerprints.

    ``record(op, seq, ...)`` folds one fingerprint into the running
    digest: ``digest_k = H(digest_{k-1} | op | seq | shape | dtype |
    axis)``. Two ranks of an SPMD program that issued the identical
    collective sequence therefore hold the identical digest — one
    string comparison at merge time proves N whole schedules matched —
    while the retained entry window lets a mismatch be localized to
    the first divergent ``(op, seq)``.
    """

    def __init__(self, *, window: int = SCHEDULE_WINDOW):
        self._lock = threading.Lock()
        self.window = window
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.n = 0
            self.digest = ""
            self.entries: deque = deque(maxlen=self.window)

    def record(self, op: str, seq: int, *, shape=None, dtype=None,
               axis=None, algorithm=None) -> dict:
        # ``algorithm`` joined the fingerprint with the fused-collective
        # route (PR 8): a rank running the host-driven path while its
        # peers run the in-kernel ring is a schedule divergence even
        # when (op, seq, shape) agree — the wire protocols differ.
        fp = (f"{op}|{int(seq)}|{tuple(shape) if shape is not None else ()}"
              f"|{dtype or ''}|{axis or ''}|{algorithm or ''}")
        with self._lock:
            digest = hashlib.sha256(
                f"{self.digest}\x1f{fp}".encode()).hexdigest()[:16]
            entry = {
                "i": self.n, "op": str(op), "seq": int(seq),
                "shape": list(shape) if shape is not None else None,
                "dtype": str(dtype) if dtype is not None else None,
                "axis": str(axis) if axis is not None else None,
                "algorithm": (str(algorithm) if algorithm is not None
                              else None),
                "digest": digest,
            }
            self.digest = digest
            self.entries.append(entry)
            self.n += 1
        return entry

    @property
    def last(self) -> dict | None:
        return self.entries[-1] if self.entries else None

    def snapshot(self) -> dict:
        """JSON-able chain state — the ``collectives`` field of a
        flight-recorder snapshot (``harness/trace.py``), cross-checked
        rank-against-rank by ``harness/collect.py``."""
        with self._lock:
            return {
                "n": self.n,
                "digest": self.digest,
                "window": self.window,
                "entries": [dict(e) for e in self.entries],
            }


_schedule = CollectiveSchedule()


def collective_schedule() -> CollectiveSchedule:
    """The process-wide chain (one per rank in a launch)."""
    return _schedule


def reset_collective_schedule() -> None:
    """Fresh chain — ``harness.trace.configure`` calls this so every
    instrumented run's chain starts at the same genesis on every rank."""
    _schedule.reset()


def _progress_path(trace_dir: str, process_id: int) -> str:
    return os.path.join(trace_dir, f"rank{process_id:05d}.sched.json")


def record_collective(op: str, seq: int, *, shape=None, dtype=None,
                      axis=None, algorithm=None) -> dict:
    """Fingerprint one collective into the process chain.

    Called at ISSUE time (before the wait): ``comm/communicator.py``
    records every eager collective — host-driven AND fused-kernel
    routes, with ``algorithm`` in the fingerprint so the fast path is
    never invisible to the verifier — and ``harness/timing.py`` every
    traced timed repetition. Under a launcher (``HPCPAT_TRACE_DIR``
    exported by ``apps/launch.py --trace-out``) each record also
    persists the chain head to ``rank<id>.sched.json`` — that write is
    what makes a HUNG rank diagnosable: the rank never reaches its
    trace-snapshot handoff, but the collective it is stuck in is
    already on disk for the launcher's timeout report."""
    entry = _schedule.record(op, seq, shape=shape, dtype=dtype, axis=axis,
                             algorithm=algorithm)
    trace_dir = os.environ.get(ENV_TRACE_DIR)
    if trace_dir:
        try:
            pid = int(os.environ.get(ENV_PROCESS_ID) or 0)
        except ValueError:
            pid = 0
        # payload built from THIS call's entry (not a re-read of the
        # shared chain head): concurrent recorders each write a
        # self-consistent (last, n, digest) triple
        payload = {
            "process_id": pid,
            "n": entry["i"] + 1,
            "digest": entry["digest"],
            "last": {"i": entry["i"], "op": entry["op"],
                     "seq": entry["seq"]},
        }
        path = _progress_path(trace_dir, pid)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            # write-then-rename: a rank killed mid-write (the timeout
            # path's proc.kill()) must not leave a truncated file —
            # the straggler whose position the hang report exists to
            # print is exactly the rank most likely to die mid-write
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            pass  # forensics are best-effort; never fail the collective
    return entry


# ---------------------------------------------------------------------------
# donation poisoning
# ---------------------------------------------------------------------------


def _buffer_ptrs(leaf) -> list[tuple[int, int]]:
    """(pointer, nbytes) per addressable shard; [] when the backend
    hides them (the helper is then inert, never wrong)."""
    out = []
    try:
        for shard in leaf.addressable_shards:
            db = shard.data
            out.append((db.unsafe_buffer_pointer(), db.nbytes))
    except Exception:  # noqa: BLE001 - best-effort probe
        return []
    return out


def poison_donated(fn, donate_argnums, *, sentinel: int = SENTINEL_BYTE):
    """Wrap jitted ``fn`` so donated inputs die loudly after each call.

    After ``fn(*args)`` completes (outputs blocked on), every jax leaf
    of each ``args[i]`` for ``i in donate_argnums`` is overwritten with
    ``sentinel`` bytes — unless the executable aliased that buffer into
    an output (donation honored: poisoning would corrupt the result;
    the aliasing itself already invalidates stale host views) or jax
    deleted it. The wrapper forwards ``__wrapped__``, so
    ``harness.trace.jit_cache_size`` / ``compile_watch`` (and through
    them ``serving.prefill_cache_size``) keep probing the real jit.

    ``wrapper.poison_count`` accumulates poisoned buffers — tests
    assert on it to prove the hook engaged rather than silently
    no-op'ing.
    """
    donate_argnums = tuple(donate_argnums)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        import jax

        out = fn(*args, **kwargs)
        leaves_out = jax.tree_util.tree_leaves(out)
        for leaf in leaves_out:
            jax.block_until_ready(leaf)
        out_ptrs = {
            ptr
            for leaf in leaves_out
            if isinstance(leaf, jax.Array)
            for ptr, _ in _buffer_ptrs(leaf)
        }
        for i in donate_argnums:
            if i >= len(args):
                continue
            for leaf in jax.tree_util.tree_leaves(args[i]):
                if not isinstance(leaf, jax.Array):
                    continue
                try:
                    if leaf.is_deleted():
                        continue
                except Exception:  # noqa: BLE001
                    continue
                for ptr, nbytes in _buffer_ptrs(leaf):
                    if ptr in out_ptrs or nbytes == 0:
                        continue
                    ctypes.memset(ptr, sentinel, nbytes)
                    wrapper.poison_count += 1
        return out

    wrapper.poison_count = 0
    # functools.wraps already set __wrapped__ = fn; make the contract
    # explicit since the trace probe depends on it
    wrapper.__wrapped__ = fn
    return wrapper


#: the serving engine's donating jit entry points and their donated
#: positions — MUST mirror the donate_argnums in models/serving.py
#: (tests/test_analysis.py asserts they stay in sync)
SERVING_POISON_TARGETS: dict[str, tuple[int, ...]] = {
    "_chunk_step": (1, 2, 3, 4, 5),
    "_spec_chunk": (2, 3, 4, 5, 6, 7),
    "_prefill_one": (3,),
    "_admit_row": (0, 1, 2, 3, 4),
    # the serving plane's KV-handoff install scatter (round 10): the
    # pool is donated — an aliased host view of it would be the exact
    # PR 2 bug class resurfacing on the migration path
    "_install_pages": (0,),
    # the prefix-sharing tail prefill (round 12): donates the pool like
    # _prefill_one — an aliased view of a SHARED page would corrupt
    # every reader at once, so the poison harness must cover it
    "_tail_prefill_one": (3,),
}


# ---------------------------------------------------------------------------
# strict-semaphore interpret shim
# ---------------------------------------------------------------------------


class SemaphoreBalanceError(AssertionError):
    """A kernel's DMA semaphore ledger failed to balance: a descriptor
    waited twice on one channel, or starts != waits at kernel exit.
    In interpret mode this is invisible (semaphores are inert
    arithmetic); on chip it is a deadlock or a race."""


class _KernelFrame:
    """Per-kernel-trace DMA accounting."""

    def __init__(self, name: str):
        self.name = name
        self.remote_starts = 0
        self.local_starts = 0
        self.send_waits = 0
        self.recv_waits = 0
        self.local_waits = 0
        # best-effort per-semaphore-slot counts: key -> [starts, waits]
        self.per_key: dict = {}
        self.keyed_ok = True

    def key_count(self, key, slot: int, delta: int) -> None:
        if key is None:
            self.keyed_ok = False
            return
        entry = self.per_key.setdefault(key, [0, 0])
        entry[slot] += delta

    def check(self) -> None:
        problems = []
        if self.remote_starts != self.send_waits:
            problems.append(
                f"{self.remote_starts} remote start(s) vs "
                f"{self.send_waits} send wait(s)")
        if self.remote_starts != self.recv_waits:
            problems.append(
                f"{self.remote_starts} remote start(s) vs "
                f"{self.recv_waits} recv wait(s)")
        if self.local_starts != self.local_waits:
            problems.append(
                f"{self.local_starts} local start(s) vs "
                f"{self.local_waits} wait(s)")
        if self.keyed_ok:
            for key, (starts, waits) in sorted(self.per_key.items()):
                if starts != waits:
                    problems.append(
                        f"sem {key}: {starts} signal(s), "
                        f"{waits} wait(s)")
        if problems:
            raise SemaphoreBalanceError(
                f"kernel {self.name!r}: DMA semaphore ledger did not "
                f"balance at kernel exit — " + "; ".join(problems)
                + ". Interpret mode hides this (semaphores are "
                "inert); on chip it deadlocks or races.")


def _sem_fingerprint(sem) -> tuple | None:
    """Best-effort stable identity for a semaphore operand at trace
    time: (base ref id, transform repr). None when the structure is
    unrecognizable — the ledger then falls back to channel totals."""
    try:
        base = getattr(sem, "ref", sem)
        transforms = getattr(sem, "transforms", ())
        return (id(base), str(transforms))
    except Exception:  # noqa: BLE001 - defensive: jax internals move
        return None


class _CountedDMA:
    """Proxy over a pallas async-copy descriptor: forwards everything,
    counts starts/waits, and fails fast on a per-descriptor
    double-wait (the PR 8 drain bug's exact shape)."""

    def __init__(self, real, frame: _KernelFrame, remote: bool,
                 send_key, recv_key):
        self._real = real
        self._frame = frame
        self._remote = remote
        self._send_key = send_key
        self._recv_key = recv_key
        self._send_waits = 0
        self._recv_waits = 0

    def start(self, *args, **kwargs):
        f = self._frame
        if self._remote:
            f.remote_starts += 1
            f.key_count(self._send_key, 0, 1)
            f.key_count(self._recv_key, 0, 1)
        else:
            f.local_starts += 1
            f.key_count(self._recv_key, 0, 1)
        return self._real.start(*args, **kwargs)

    def _count_wait(self, channel: str):
        f = self._frame
        if channel == "send":
            self._send_waits += 1
            f.send_waits += 1
            f.key_count(self._send_key, 1, 1)
            if self._send_waits > 1:
                raise SemaphoreBalanceError(
                    f"kernel {f.name!r}: descriptor send semaphore "
                    f"waited {self._send_waits} times — one signal "
                    f"per DMA; the second wait deadlocks on chip "
                    f"(the PR 8 drain double-wait)")
        else:
            self._recv_waits += 1
            if self._remote:
                f.recv_waits += 1
            else:
                f.local_waits += 1
            f.key_count(self._recv_key, 1, 1)
            if self._recv_waits > 1:
                raise SemaphoreBalanceError(
                    f"kernel {f.name!r}: descriptor recv semaphore "
                    f"waited {self._recv_waits} times — one signal "
                    f"per DMA; the second wait deadlocks on chip")

    def wait(self, *args, **kwargs):
        if self._remote:
            self._count_wait("send")
        self._count_wait("recv")
        return self._real.wait(*args, **kwargs)

    def wait_send(self, *args, **kwargs):
        self._count_wait("send")
        return self._real.wait_send(*args, **kwargs)

    def wait_recv(self, *args, **kwargs):
        self._count_wait("recv")
        return self._real.wait_recv(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._real, name)


class StrictSemaphores:
    """Context manager installing the strict-semaphore shim (module
    docstring). ``kernels_checked`` counts kernel traces that carried
    DMA activity — tests assert it is nonzero so the shim provably
    engaged (an already-warm trace cache would otherwise skip every
    kernel body; pair with ``jax.clear_caches()``)."""

    def __init__(self):
        self.kernels_checked = 0
        self._frames: list[_KernelFrame] = []
        self._originals: list[tuple] = []

    # -- patch targets ---------------------------------------------------

    def __enter__(self):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        shim = self

        real_local = pltpu.make_async_copy
        real_remote = pltpu.make_async_remote_copy
        real_call = pl.pallas_call

        def counted_local(*args, **kwargs):
            real = real_local(*args, **kwargs)
            frame = shim._frames[-1] if shim._frames else None
            if frame is None:
                return real
            sem = kwargs.get("sem", args[2] if len(args) > 2 else None)
            return _CountedDMA(real, frame, remote=False,
                               send_key=None,
                               recv_key=_sem_fingerprint(sem))

        def counted_remote(*args, **kwargs):
            real = real_remote(*args, **kwargs)
            frame = shim._frames[-1] if shim._frames else None
            if frame is None:
                return real
            send = kwargs.get("send_sem",
                              args[2] if len(args) > 2 else None)
            recv = kwargs.get("recv_sem",
                              args[3] if len(args) > 3 else None)
            return _CountedDMA(real, frame, remote=True,
                               send_key=_sem_fingerprint(send),
                               recv_key=_sem_fingerprint(recv))

        def checked_call(kernel, *args, **kwargs):
            if not callable(kernel):  # pragma: no cover - defensive
                return real_call(kernel, *args, **kwargs)
            name = getattr(kernel, "__name__", None) or getattr(
                getattr(kernel, "func", None), "__name__", "kernel")

            @functools.wraps(kernel if hasattr(kernel, "__name__")
                             else (lambda: None))
            def body(*refs, **kw):
                frame = _KernelFrame(name)
                shim._frames.append(frame)
                try:
                    out = kernel(*refs, **kw)
                finally:
                    shim._frames.pop()
                # balance asserted on the SUCCESS path only: an
                # exception unwinding through the body must surface
                # itself, not a secondary ledger complaint
                if (frame.remote_starts or frame.local_starts
                        or frame.send_waits or frame.recv_waits
                        or frame.local_waits):
                    shim.kernels_checked += 1
                    frame.check()
                return out

            return real_call(body, *args, **kwargs)

        self._originals = [
            (pltpu, "make_async_copy", real_local),
            (pltpu, "make_async_remote_copy", real_remote),
            (pl, "pallas_call", real_call),
        ]
        pltpu.make_async_copy = counted_local
        pltpu.make_async_remote_copy = counted_remote
        pl.pallas_call = checked_call
        return self

    def __exit__(self, *exc):
        for obj, attr, original in self._originals:
            setattr(obj, attr, original)
        self._originals = []
        return False


def strict_semaphores() -> StrictSemaphores:
    """The strict-semaphore interpret shim as a context manager::

        with strict_semaphores() as ledger:
            jax.clear_caches()       # force kernel re-traces
            run_the_parity_battery()
        assert ledger.kernels_checked > 0

    Every kernel body traced inside the context has its DMA semaphore
    ledger balance-checked at exit; imbalance raises
    :class:`SemaphoreBalanceError` in the TEST, not on the chip."""
    return StrictSemaphores()


def install_serving_poison():
    """Swap the serving module's jitted entry points for poisoned
    wrappers; returns an ``uninstall()`` restoring the originals.
    Import stays local so merely importing this module never drags the
    models package in."""
    from hpc_patterns_tpu.models import serving

    originals = {}
    for name, argnums in SERVING_POISON_TARGETS.items():
        originals[name] = getattr(serving, name)
        setattr(serving, name, poison_donated(originals[name], argnums))

    def uninstall():
        for name, fn in originals.items():
            setattr(serving, name, fn)

    return uninstall
