#!/bin/bash
# Round-5 re-grounding sequence (VERDICT items 1b, 2, 8 + serving rows).
# Ordering discipline: light jobs first, near-full-HBM jobs (65k/131k)
# LAST — they can crash the tunnel worker and degrade the session for
# everything after (round-4 lesson, memory: axon-env-quirks).
# Usage: bash benchmarks/reground_r5.sh [logfile]
set -u
set -o pipefail
LOG="${1:-benchmarks/r5_chip.log}"
cd "$(dirname "$0")/.."

# preflight: a hung tunnel blocks `import jax` in C — don't start a
# 16-step sequence whose every step would burn its full timeout
if ! timeout 90 python -c \
    "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; x=jnp.ones((128,128)); (x@x).block_until_ready()" \
    >/dev/null 2>&1; then
  echo "PREFLIGHT FAILED: TPU tunnel unresponsive ($(date +%H:%M:%S))" | tee -a "$LOG"
  exit 2
fi
echo "PREFLIGHT OK ($(date +%H:%M:%S))" | tee -a "$LOG"

run() {
  local name="$1"; shift
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$LOG"
  timeout 1200 "$@" 2>&1 | tee -a "$LOG"
  # the benchmark's status, not tee's: $? after a pipeline is the LAST
  # command's (always-0 tee), which masked failures/timeouts (ADVICE r5)
  local rc=${PIPESTATUS[0]}
  echo "--- rc=$rc ---" | tee -a "$LOG"
}

# 0. session health + headline (the driver-style capture, kept as a row;
#    since PR 8 the detail also carries fused_allreduce_gbps /
#    allreduce_overlap_frac — the device-initiated collective row)
run "bench.py headline" python bench.py

# 0b. fused-vs-host collective sweep (comm/fused.py): the default sweep
#     now races ring / ring_chunked / collective / FUSED per message
#     size — the busbw-vs-size curve that shows where the in-kernel
#     remote-DMA ring overtakes the host-driven paths. Light (compile +
#     a few reps per point); the oracle validates every point.
run "allreduce fused-vs-host sweep" python -m hpc_patterns_tpu.apps.allreduce_app \
  --sweep --min-p 20 -p 26 --repetitions 5 --warmup 2

# 1. T=2048 MFU row (the 73-75% config)
run "train T=2048 kv=2" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=2048", "--batch=8", "--remat=1", "--kv=2"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF

# 2. fused-MLP confirm at the headline (clean-session, item 8)
run "train T=2048 fused" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=2048", "--batch=8", "--remat=1", "--kv=2", "--mlp=fused"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF

# 3. decode absolutes at the 2k-prefix/16k-alloc regime + the paged
#    unroll sweep (item 2: gap target <= 1.2x of linear)
run "decode flash+gather" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2
run "decode paged auto-unroll" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2 --impl=paged
run "decode paged ppstep=1 (round-4 form)" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2 --impl=paged --ppstep=1
run "decode paged ppstep=2" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2 --impl=paged --ppstep=2
run "decode paged ppstep=8" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2 --impl=paged --ppstep=8
run "decode paged page=2048" python benchmarks/bench_decode.py --prompt=2048 --slack=14336 --kv=2 --impl=paged --page=2048

# 4. continuous batching vs static (item 3's chip row)
run "serving engine vs static" python benchmarks/bench_serving.py

# 4b. ROBUSTNESS row: the open-loop chaos/SLO scenario — bursty
#     two-class traffic under page pressure (preemption-and-resume) and
#     a seeded stalled-host injection; reports GOODPUT (SLO-attained
#     tok/s) next to raw tok/s and must still beat clean static. Its
#     headline keys (serving_goodput_tok_s, serving_degraded_bubble_
#     frac) are gated by harness/regress.py alongside serving_tok_s
#     when captured into a round; the oracle (preempted-and-resumed
#     rows byte-identical to standalone) runs before any number prints.
run "serving chaos/SLO scenario" python benchmarks/bench_serving.py --scenario

# 4c. SERVING-PLANE row (round 10): one open-loop stream through a
#     single engine, a 2-replica router plane, and the disaggregated
#     1-prefill/1-decode plane — per-chip replica placement on TPU
#     (each replica its own weight copy; KV migration a real
#     cross-device copy hidden behind the decode chunk). Headline keys
#     plane_goodput_tok_s / kv_migration_overlap_frac are captured by
#     bench.py and gated by harness/regress.py; the ladder is FIT from
#     the stream (serving.fit_bucket_ladder) and every leg is
#     oracle-exact before a number prints.
run "serving plane 2-replica + 1p/1d" python benchmarks/bench_serving.py --plane

# 4d. TIERED-MEMORY row (round 11): the same stream through an
#     all-HBM engine and a constrained engine whose HBM pool is capped
#     at ~40% of the working set, fronting a host-resident pool via
#     the residency manager (hpc_patterns_tpu/memory/) — cold rows
#     page to pinned_host at chunk boundaries, swapped rows prefetch
#     back with the pull dispatched before the decode chunk. The
#     oracle (token-identical to all-HBM, real eviction forced) runs
#     before any number prints; headline keys offload_goodput_tok_s /
#     prefetch_overlap_frac are captured by bench.py and gated by
#     harness/regress.py. On chip this is the first REAL-DMA-rate
#     measurement of the tier (the CPU smoke's host tier is a copy).
run "serving tiered HBM/host offload" python benchmarks/bench_serving.py --offload

# 4e. PREFIX-SHARING row (round 12): one shared-prefix open-loop
#     stream (template pool + conversation-tree turns) through a
#     private-pages engine and the sharing-aware arena
#     (prefix_cache=True — radix match at admission, refcounted
#     read-only page mapping, tail-only prefill). Token-identical to
#     private pages (oracle before any number, greedy; the sampled
#     oracle is tier-1), prefill_skip_frac asserted > 0.3 on the mix;
#     headline keys shared_goodput_tok_s / prefill_skip_frac are
#     captured by bench.py and gated by harness/regress.py. On chip
#     this is the first real-HBM capacity number for the dedup'd
#     arena AND the bitwise-parity check of the tail prefill on the
#     TPU toolchain (docs/prefix_cache.md — the parity contract is
#     backend-empirical; the in-run oracle fails loudly if the chip
#     compiler breaks it).
run "serving shared-prefix arena" python benchmarks/bench_serving.py --shared

# 4f. QUANTIZED-DECODE row (round 13): the stream served from int8 /
#     fp8 KV pools (one-byte pages + per-row scales) and, per
#     precision, the attention-route RACE — the quantized full config
#     runs the same stream on decode_attn="gather" vs "paged_flash"
#     (ops/paged_attention.py, exact-softmax gather-into-VMEM kernel)
#     at real VMEM limits. The interpret-mode ~10x per-grid-point
#     penalty that forced off-TPU serving onto the gather route is
#     exactly the number this leg replaces with a chip measurement.
#     Both precision oracles (token-identical within the precision;
#     teacher-forced greedy-agreement + TV-distance law across
#     precisions) run before any number prints; headline keys
#     quant_goodput_tok_s / kv_pool_bytes_frac / quant_bubble_frac
#     are captured by bench.py and gated by harness/regress.py.
#     fp8 degrades to int8 with a loud note on backends without
#     float8_e4m3fn support (dtypes.supports_fp8).
run "serving quantized kv int8 + route race" python benchmarks/bench_serving.py --quant --kv-dtype=int8
run "serving quantized kv fp8 + route race" python benchmarks/bench_serving.py --quant --kv-dtype=fp8
run "serving quantized kv+weights int8" python benchmarks/bench_serving.py --quant --kv-dtype=int8 --quant-weights
# the compound rows: quantized KV through the residency tier (double
# effective HBM) and the serving plane (half the migration bytes)
run "serving tiered offload @ int8 kv" python benchmarks/bench_serving.py --offload --kv-dtype=int8
run "serving plane @ int8 kv" python benchmarks/bench_serving.py --plane --kv-dtype=int8

# 4g. ELASTIC-PLANE row (round 14): a diurnal open-loop ramp under
#     seeded replica-death chaos through a FIXED 2-replica plane (the
#     death ends in shedding) and the autoscaled ElasticServingPlane
#     (serving_plane/autoscaler.py — SLO-feedback scale-up on WARM
#     residency-pulled params, checkpoint resume after the death,
#     drain-by-migration on the way down). On chip this is the first
#     real number for warm spin-up: the plane.spinup window's
#     host->HBM param paging at real DMA rates vs a real on-device
#     init_params (the CPU smoke's host tier is a same-memory copy),
#     and for goodput_per_replica_round at chip throughput. The
#     verdict is asserted in-run before any number prints: elastic
#     attainment strictly above static, every served stream
#     byte-exact greedy AND sampled (death-resumed rows included),
#     warm < cold. Headline keys elastic_slo_attainment /
#     goodput_per_replica_round are captured by bench.py and gated by
#     harness/regress.py.
run "serving elastic ramp under replica death" python benchmarks/bench_serving.py --elastic

# 4h. AUTOFIT row (round 16): observability becomes control. The --fit
#     leg records an untimed serving leg under the default config into
#     a run log, fits a versioned config from that trace
#     (harness/autofit.py: exact-DP bucket ladder from serve_admit
#     prompt lengths, residency prefetch depth from mem.prefetch
#     overlap, placement weights from per-replica busy/queue rollups,
#     autoscaler bands by offline replay), then A/Bs default-vs-fitted
#     on the SAME stream and pool geometry. The strict claim — fitted
#     expected padding < default — is asserted in-run before any
#     number prints, and both legs are oracle-exact vs paged_generate.
#     On chip this is the first real wall-clock number for the fitted
#     gain; fitted_goodput_tok_s / autofit_gain_frac are captured by
#     bench.py and gated by harness/regress.py. --fit-out persists the
#     chip-fitted config; the second leg replays it through the SAME
#     CLI path serve_app --autofit uses (load_fitted round trip), so
#     the artifact is proven consumable, not just writable.
run "serving autofit A/B (fit on chip trace)" \
  python benchmarks/bench_serving.py --fit --fit-out="${LOG%.log}_autofit.json"
run "serving autofit replay (chip-fitted config)" \
  python benchmarks/bench_serving.py --fit --autofit="${LOG%.log}_autofit.json"

# 4i. REQUEST-FORENSICS row (round 18): where every p99 went, on chip.
#     The chaos scenario's timed leg runs under request-scoped
#     lifecycle tracing (harness/reqtrace.py — always on for that
#     leg), with the coverage invariant asserted in-run (< 5%
#     untracked) before any number prints; --explain renders the
#     per-class tail-attribution table after the goodput row and
#     --explain-out persists the digest. On chip this is the first
#     attribution of a REAL p99: queued vs admit_wait vs prefill
#     shares at chip service rates, with the seeded stalls landing in
#     the bucket that names them. attribution_coverage_frac is gated
#     and ttft_p99_queue_share is captured per round by
#     harness/regress.py. The serve leg then proves the log-side
#     consumer: a kind=reqtrace record through --log, attributed
#     offline by `python -m hpc_patterns_tpu.harness.explain` — the
#     same digest the in-run table rendered, from the artifact.
run "serving tail attribution (chaos scenario)" \
  python benchmarks/bench_serving.py --scenario \
  --explain=1 --explain-out="${LOG%.log}_explain.json"
run "serve leg with reqtrace record" \
  python -m hpc_patterns_tpu.apps.serve_app --requests 24 --slots 4 \
  --budget 32 --prompt-len 48 --chunk 8 --prompt-mix \
  --explain --log "${LOG%.log}_reqtrace.jsonl"
run "explain from the run log" \
  python -m hpc_patterns_tpu.harness.explain "${LOG%.log}_reqtrace.jsonl"

# 4j. SEGMENT-BUDGET row (round 20): the attribution loop closed, on
#     chip. A seeded slow_host_transfer through a thrashing
#     2-resident tier must breach the prefetch_wait budget line and
#     NO other (run_slo_budget asserts the breach set in-run — chaos
#     lands in the bucket it was injected into), and --explain
#     renders the inter-token TPOT-tail table (the digest past
#     t_first) next to the step 4i TTFT table. tpot_p99_stall_share
#     and budget_breach_segments are the gated keys
#     (harness/regress.py); the --fit row above already asserted the
#     blamed segment's share strictly shrinks under the blame-fitted
#     residency, so this leg is the breach-side artifact.
run "serving segment budgets (seeded breach + TPOT tail)" \
  python benchmarks/bench_serving.py --slo-budget --explain=1

# 5. aligned speculative pair + gamma sweep + batched impls (item 4, 7)
run "make draft pair" python benchmarks/make_draft_pair.py --out=benchmarks/pair_r5
run "speculative aligned sweep" python benchmarks/bench_speculative.py --pair=benchmarks/pair_r5 --batched=8

# 6. T=32k long-context confirm (item 1b) + fused at 32k (item 8)
run "train T=32k split+chunk" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=32768", "--batch=1", "--remat=1", "--rp=split", "--chunk=4096", "--kv=2"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF
run "train T=32k fused" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=32768", "--batch=1", "--remat=1", "--rp=split", "--chunk=4096", "--kv=2", "--mlp=fused"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF

# 7. RISKY LAST: the OPEN 65k question — does rp=split fit at 65k on a
#    fresh session (expected ~115-120 TF/s) or does OOM confirm
#    rp=nothing (~102) stands? Then the rp=nothing confirm, then 131k.
run "train T=65k SPLIT+chunk (OPEN row)" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=65536", "--batch=1", "--remat=1", "--rp=split", "--chunk=4096", "--kv=2"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF
run "train T=65k rp=nothing confirm (round-3 2835ms row)" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=65536", "--batch=1", "--remat=1", "--rp=nothing", "--chunk=4096", "--kv=2"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF
run "train T=131k (round-3 reproduce cmd)" python - <<'EOF'
import sys; sys.argv = ["b", "--seq=131072", "--batch=1", "--remat=1", "--rp=nothing", "--chunk=4096", "--pos=rope", "--offload=1"]
sys.path.insert(0, "benchmarks"); import bench_train as bt; bt.main()
EOF

# 7b. multi-proc distributed trace: a 2-process allreduce under the
#     flight recorder — the merged Perfetto timeline and the cross-rank
#     skew/straggler rollup (kind=trace_merged) land next to the
#     round's log, so a slow round's collective skew is inspectable
#     rather than inferred (harness/collect.py; CPU mesh — the
#     cross-PROCESS path is what this leg exercises, not the chip)
run "multi-proc allreduce trace (2 ranks)" env JAX_PLATFORMS=cpu \
  python -m hpc_patterns_tpu.apps.launch -np 2 --cpu-devices-per-proc 2 \
  --trace-out "${LOG%.log}_multiproc.trace.json" \
  --log "${LOG%.log}_multiproc.jsonl" -- \
  python -m hpc_patterns_tpu.apps.allreduce_app -p 16 \
  --repetitions 5 --warmup 2 --trace

# 7c. the same 2-process traced capture on the FUSED route: the merged
#     timeline's comm.allreduce.fused windows + the per-rank bubble
#     rollup are the overlap evidence (the in-kernel ring shows as ONE
#     device window where the host-driven route shows dispatch gaps),
#     and the schedule verdict proves the fused fingerprints
#     (op|seq|shape|dtype|axis|algorithm) still chain identically
#     across ranks — the fast path is not blind to the verifier.
run "multi-proc FUSED allreduce trace (2 ranks)" env JAX_PLATFORMS=cpu \
  python -m hpc_patterns_tpu.apps.launch -np 2 --cpu-devices-per-proc 2 \
  --trace-out "${LOG%.log}_multiproc_fused.trace.json" \
  --log "${LOG%.log}_multiproc_fused.jsonl" -- \
  python -m hpc_patterns_tpu.apps.allreduce_app -p 16 --algorithm fused \
  --repetitions 5 --warmup 2 --trace

# 7d. LAUNCHED serving plane, real engines (round 10): router +
#     1 prefill + 1 decode replica as three OS processes; the merged
#     timeline shows the KV-handoff flow arrows between the replica
#     lanes (matched plane.kv_migration windows) and the schedule
#     verdict proves router and replicas agreed on the handoff order.
#     The stub tier of the same path runs in tier-1
#     (tests/test_launch.py::TestServingPlaneLaunch).
run "launched serving plane (1p/1d, real engines)" env JAX_PLATFORMS=cpu \
  python -m hpc_patterns_tpu.apps.launch -np 3 --timeout 300 \
  --trace-out "${LOG%.log}_plane.trace.json" \
  --log "${LOG%.log}_plane.jsonl" -- \
  python -m hpc_patterns_tpu.apps.plane_app --roles prefill,decode \
  --rdv "${LOG%.log}_plane_rdv" --requests 8 --trace

# 7e. DEVICE-SIDE KV migration (round 17): the 1p/1d plane with the
#     handoff routed over the fused paired remote-DMA kernel
#     (comm/migration_dma.py) instead of device_put — per-device
#     replica placement is forced, every served stream stays
#     oracle-exact, and the traced run's plane.kv_migration windows
#     carry algorithm="dma" in the schedule chain (the fingerprint
#     that catches a silent fallback; a fallback also warns loudly in
#     the row output). On the chip this is the ICI replica-to-replica
#     copy the transport tier exists for; the kind=trace snapshot in
#     the jsonl exports to Perfetto via `python -m
#     hpc_patterns_tpu.harness.trace` and the row prints
#     dma_migration_overlap_frac / migration_bytes_per_round — step
#     8's gate holds both from BENCH_rNN.json.
run "serving plane 1p/1d over DMA migration (traced)" \
  python benchmarks/bench_serving.py --plane --migration=dma --trace \
  "--log=${LOG%.log}_plane_dma.jsonl"

# 8. final health check + REGRESSION GATE: capture the closing round,
#    write it as the next BENCH_rNN.json, and compare its headline
#    numbers against the best prior round (harness.regress) — a
#    sequence that degraded the fast path now fails loudly instead of
#    appending a silently-worse round. The gate's stdout now also
#    carries the coverage-loss check: a gated key (serving_tok_s,
#    allreduce_busbw_gbps, ...) that a prior round measured and this
#    round silently lost is WARNED, not passed.
run "bench.py post-check + regression gate" python bench.py --gate
run "regress coverage-loss check (full trajectory)" \
  python -m hpc_patterns_tpu.harness.regress BENCH_r*.json

# 9. STATIC GATE: jaxlint over the package (hpc_patterns_tpu.analysis)
#    — the review-time counterpart of the bench gate. The round's
#    verdict lands as a kind=analysis record in the run log, where
#    harness.report surfaces it next to the metrics/trace rollups. A
#    dirty tree fails the sequence: donation-alias was the bug class
#    that cost round 6 its cache, and it is cheaper to catch here than
#    on a chip session. Rules self-register, so the shardlint family
#    (collective-divergence/-order, unchecked-permutation,
#    spec-mismatch) AND the pallaslint family (dma-sem-balance,
#    dma-slot-reuse, collective-id-collision, kernel-dtype-cast,
#    vmem-budget) AND the contractlint family (gate-key-orphan,
#    record-kind-drift, wire-field-compat, track-band-collision,
#    chaos-site-drift — whole-tree producer/consumer contracts over
#    the very gate keys step 8 judges) gate here with no script
#    change; the runtime halves are step 7b's "collective schedules
#    consistent" verdict and the strict-semaphore shim the fused
#    parity battery runs under. The producer/consumer tables behind
#    the contract rules are printable on demand
#    (--contract-report), the informational twin of the VMEM table
#    below.
#    --vmem-report logs the per-kernel VMEM budget table next to the
#    analysis record — read it BEFORE step 7c's compiled fused legs
#    (the kernels this round first lowers on real VMEM limits) and
#    before step 4f's paged_flash race: the paged gather-scratch row
#    is the grid-streaming decision number.
run "jaxlint static gate + vmem table" \
  python -m hpc_patterns_tpu.analysis --ci --vmem-report \
  --log "${LOG%.log}_analysis.jsonl"
echo "DONE $(date +%H:%M:%S)" | tee -a "$LOG"
