"""Allreduce miniapp: ring vs library collective on a TPU mesh.

TPU-native rebuild of the reference's three allreduce miniapps
(allreduce-mpi-sycl.cpp, allreduce-usm/map-mpi-omp-offload.cpp — C5–C7
in SURVEY.md). Reproduced semantics:

- ``-a`` switches from the hand ring to the library collective
  (allreduce-mpi-sycl.cpp:122-124 → here ``lax.psum``); additionally
  ``--algorithm ring_chunked`` selects the bandwidth-optimal two-phase
  ring the reference's teaching ring approximates.
- ``-p N`` → 2**N elements per rank, default 25 (:99,125-128).
- ``-H/-D/-S`` allocator axis → JAX memory kinds (:104-131); host kind
  falls back to device with a logged note when the backend lacks it.
- rank-valued init (:33-41), analytic oracle size(size−1)/2 validated
  elementwise on the host (:192-204), per-rank "Passed r" lines (:206).
- wall-clock timed region, MAX across processes (:170-190), min over
  repetitions; compile excluded by warm-up (SURVEY.md §7(d)).
- dtype axis via ``--dtype`` ≙ the typed CTest variants
  (mpi-sycl/CMakeLists.txt:4-5, float+int).

Reported: elapsed seconds, algorithm bandwidth, and ring-normalized bus
bandwidth (the BASELINE.json headline metric).
"""

from __future__ import annotations

import functools
import sys

import numpy as np

from hpc_patterns_tpu.harness.timing import blocking

from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.comm.communicator import record_collective_bandwidth
from hpc_patterns_tpu.dtypes import get_traits
from hpc_patterns_tpu.harness import RunLog, Verdict, correctness_verdict, measure
from hpc_patterns_tpu.harness.cli import (
    add_memory_kind_args,
    add_msg_size_args,
    add_sweep_args,
    base_parser,
)
from hpc_patterns_tpu.harness.timing import max_across_processes


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    add_msg_size_args(p)
    add_memory_kind_args(p)
    p.add_argument(
        "-a",
        "--allreduce",
        action="store_true",
        help="use the library collective (reference -a → MPI_Allreduce)",
    )
    p.add_argument(
        "--algorithm",
        default=None,
        choices=["ring", "ring_chunked", "collective", "fused"],
        help="explicit algorithm (overrides -a; default ring, like the "
             "reference; 'fused' = the device-initiated in-kernel "
             "remote-DMA ring, comm/fused.py)",
    )
    p.add_argument(
        "--world",
        type=int,
        default=-1,
        help="ranks (mesh size); -1 = all devices (mpirun -np analog)",
    )
    p.add_argument(
        "--sweep",
        action="store_true",
        help="sweep message sizes --min-p..-p for each algorithm "
             "(ring, ring_chunked, collective unless --algorithm/-a "
             "narrows it), emitting one validated JSONL result per "
             "point — the GB/s-vs-size curve of the BASELINE metric "
             "(reference protocol: allreduce-mpi-sycl.cpp:99,125-128)",
    )
    add_sweep_args(p)
    return p


def resolve_algorithm(args) -> str:
    if args.algorithm:
        return args.algorithm
    return "collective" if args.allreduce else "ring"


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    comm = common.make_communicator(args.backend, args.world, even=True)
    if args.sweep:
        return run_sweep(args, log, comm)
    return _run_point(args, log, comm, resolve_algorithm(args),
                      args.log2_elements)


def run_sweep(args, log, comm) -> int:
    """Message-size sweep per algorithm: every point is a full validated
    run (analytic oracle + "Passed r" lines), and every point emits a
    JSONL result record — together the busbw-vs-size curve. On world=1
    the ring degenerates to a copy and the bandwidths are NOT a
    collective measurement; the records carry the world size so readers
    can tell."""
    if args.min_p > args.log2_elements:
        log.print(f"ERROR: --min-p {args.min_p} > -p {args.log2_elements}")
        log.print("FAILURE")
        return 1
    if args.algorithm or args.allreduce:
        algorithms = [resolve_algorithm(args)]
    else:
        algorithms = ["ring", "ring_chunked", "collective", "fused"]
    n_ok = n_total = 0
    kind_cache: dict = {}  # memory-kind probe result, shared across points
    budget = _hbm_budget_bytes()
    for algorithm in algorithms:
        for p in range(args.min_p, args.log2_elements + 1):
            nbytes = (1 << p) * get_traits(args.dtype).itemsize
            if budget and 3 * nbytes > budget:
                # GB-scale guard: a point needs input + output + one
                # transient copy live (~3x). Skipping is LOUD — a curve
                # that silently stops reads as "measured everything"
                log.print(
                    f"skipped {algorithm} p={p}: ~{3 * nbytes >> 20} MiB "
                    f"working set exceeds HBM budget {budget >> 20} MiB"
                )
                continue
            n_total += 1
            code = _run_point(args, log, comm, algorithm, p,
                              kind_cache=kind_cache)
            n_ok += code == 0
    # n_total == 0 (every point skipped by the headroom guard) is a
    # FAILURE: a run that measured nothing must not read as green
    ok = n_ok == n_total and n_total > 0
    log.print(f"sweep: {n_ok}/{n_total} points passed "
              f"(world={comm.size}, p={args.min_p}..{args.log2_elements}, "
              f"algorithms={','.join(algorithms)})")
    log.print("SUCCESS" if ok else "FAILURE")
    return 0 if ok else 1


def _device_mismatches(shard_data, i: int, expected_scalar: float,
                       traits) -> int:
    """Elementwise oracle check for row ``i`` of a (rows, n) shard,
    reduced ON DEVICE to a mismatch count (same tolerance rule as
    dtypes.validate_allreduce). The row slice AND the elementwise
    compare happen inside one jit as a chunked scan, so the live
    transient is one chunk — a GB-scale point cannot afford a
    materialized row copy or a row-sized |diff| temp next to the
    input/output buffers (a 4 GiB point would need ~13 GiB)."""
    import jax
    import jax.numpy as jnp

    exact = traits.exact_sum
    tol = (0.0 if exact
           else traits.tolerance + 1e-6 * abs(float(expected_scalar)))
    n = shard_data.shape[-1]
    chunk = 1 << 24
    n_chunks = max(1, n // chunk)
    while n % n_chunks:
        n_chunks -= 1

    @functools.partial(jax.jit, static_argnums=(1,))
    def count(data, i):
        def body(c, piece):
            if exact:
                # integer dtypes compare exactly IN the integer dtype —
                # float promotion would round away small deltas
                bad = jnp.sum(piece != jnp.asarray(expected_scalar,
                                                   piece.dtype))
            else:
                bad = jnp.sum(jnp.abs(piece - float(expected_scalar)) > tol)
            return c + bad, None
        c, _ = jax.lax.scan(
            body, jnp.zeros((), jnp.int32),
            data[i].reshape(n_chunks, n // n_chunks),
        )
        return c

    return int(count(shard_data, i))


def _hbm_budget_bytes() -> int | None:
    """Per-device memory budget for the sweep's working-set guard:
    bytes_limit minus what is already in use, from the backend's own
    accounting. None when the backend doesn't report memory stats (then
    the sweep runs unguarded, as before)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        return limit - in_use if limit else None
    except Exception:  # noqa: BLE001 — stats are a best-effort guard
        return None


def _run_point(args, log, comm, algorithm: str, log2_elements: int,
               kind_cache: dict | None = None) -> int:
    world = comm.size
    n = 1 << log2_elements
    traits = get_traits(args.dtype)
    if algorithm == "ring_chunked" and n % world:
        # chunked ring needs size | n; pad up like any real collective would
        n += world - n % world

    memory_kind = None if args.memory_kind == "device" else args.memory_kind
    if kind_cache is not None and memory_kind is not None:
        # sweep mode: the probe outcome is invariant across points, so
        # resolve once instead of re-probing (and re-logging) 75 times
        memory_kind = kind_cache.get("kind", memory_kind)
    x = comm.rank_filled(n, traits.dtype)
    step = comm.jit_allreduce(x, algorithm)
    if memory_kind is not None:
        # probe by *executing* once: backends can advertise a memory kind
        # (addressable_memories) yet reject collectives on it
        try:
            xh = comm.shard(x, memory_kind)
            step_h = comm.jit_allreduce(xh, algorithm)
            import jax

            jax.block_until_ready(step_h(xh))
            x, step = xh, step_h
        except Exception as e:  # noqa: BLE001 — any backend rejection falls back
            log.print(
                f"note: memory kind {memory_kind!r} unsupported here "
                f"({type(e).__name__}); using device"
            )
            memory_kind = None
    if kind_cache is not None:
        kind_cache["kind"] = memory_kind

    result = measure(
        blocking(step, x), repetitions=args.repetitions, warmup=args.warmup,
        label=f"allreduce.{algorithm}",
    )
    elapsed = max_across_processes(result.min_s)

    # per-rank validation on addressable shards only: in a multi-process
    # launch (apps/launch.py) each process asserts its own ranks'
    # buffers, exactly as each MPI rank validates its own VC
    # (allreduce-mpi-sycl.cpp:192-206); the verdict is the cross-process
    # AND of the local ones (vacuously true for a process the even-trim
    # left without ranks — some other process owns every row)
    out = step(x)
    ok_local = True
    # GB-scale rows exceed what a host readback can move in one piece
    # (the tunneled backend hard-caps transfers); validate those with a
    # device-side elementwise comparison reduced to a mismatch count —
    # the same oracle, readback shrunk to one scalar. Small rows keep
    # the reference's host-side loop (allreduce-mpi-sycl.cpp:192-204).
    on_device = n * traits.itemsize > 256 << 20
    if on_device:
        import jax

        jax.block_until_ready(out)
        x.delete()  # free the input: validation only reads the output
        for shard in out.addressable_shards:
            lead = shard.index[0] if shard.index else slice(0, 1)
            start = lead.start or 0
            for i in range(shard.data.shape[0]):
                r = start + i
                bad = _device_mismatches(
                    shard.data, i, comm.expected_allreduce_value(), traits
                )
                log.print(f"Passed {r}" if bad == 0 else
                          f"rank {r}: {bad}/{n} elements wrong "
                          "(device-side oracle)")
                ok_local &= bad == 0
    else:
        for r, row in common.local_rows(out):
            v = correctness_verdict(np.asarray(row),
                                    comm.expected_allreduce_value(),
                                    dtype=traits.dtype, rank=r)
            log.print(f"Passed {r}" if v.success else v.messages[0])
            ok_local &= v.success
    ok = common.all_processes_agree(ok_local)
    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))

    nbytes = n * traits.itemsize
    busbw = common.allreduce_bus_bandwidth_gbps(nbytes, elapsed, world)
    record_collective_bandwidth(f"allreduce.{algorithm}", nbytes, elapsed,
                                busbw_gbps=busbw)
    log.result(
        f"allreduce[{algorithm}]",
        verdict,
        world=world,
        elements=n,
        dtype=traits.dtype.name,
        bytes_per_rank=nbytes,
        elapsed_s=elapsed,
        algbw_gbps=nbytes / elapsed / 1e9 if elapsed > 0 else float("inf"),
        busbw_gbps=busbw,
        memory_kind=memory_kind or "device",
    )
    log.print(
        f"{algorithm} world={world} n=2^{log2_elements} {traits.dtype.name}: "
        f"{elapsed * 1e3:.3f} ms, busbw {busbw:.2f} GB/s"
    )
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
