"""Runtime donation poisoning: make aliasing bugs fail loudly in tests.

The static ``donation-alias`` rule catches the shapes it can see; this
is the belt-and-braces RUNTIME check for the ones it can't. The hazard
(round 6's "poisoned cache"): on CPU a freshly-built executable often
does NOT honor a donation, so a zero-copy host view of a donated input
keeps reading stable values and the bug passes every test — until a
cache-loaded (or TPU) executable honors the donation and mutates the
view in place, corrupting whatever bookkeeping was built on it.

:func:`poison_donated` removes the luck: it wraps a jitted function
and, after each call completes, overwrites every donated input buffer
that the executable did NOT alias into an output with a sentinel byte
pattern. Any host view (or late host read) of a donated input now sees
garbage on EVERY backend — the same observable behavior a
donation-honoring executable produces, minus the chip session.

Wiring: ``tests/conftest.py`` installs the wrappers around the serving
engine's jitted entry points for ``tests/test_serving.py`` (always)
and for the whole suite under ``HPC_PATTERNS_POISON_DONATED=1``.

The buffer writes go through ``unsafe_buffer_pointer`` + ctypes —
test-harness territory, kept out of library code on purpose.
"""

from __future__ import annotations

import ctypes
import functools

import jax

#: sentinel byte: 0xAB patterns decode to huge-magnitude garbage in
#: every dtype we serve (int32 -1414812757, implausible floats), so a
#: poisoned read corrupts comparisons instead of looking plausible
SENTINEL_BYTE = 0xAB


def _buffer_ptrs(leaf) -> list[tuple[int, int]]:
    """(pointer, nbytes) per addressable shard; [] when the backend
    hides them (the helper is then inert, never wrong)."""
    out = []
    try:
        for shard in leaf.addressable_shards:
            db = shard.data
            out.append((db.unsafe_buffer_pointer(), db.nbytes))
    except Exception:  # noqa: BLE001 - best-effort probe
        return []
    return out


def poison_donated(fn, donate_argnums, *, sentinel: int = SENTINEL_BYTE):
    """Wrap jitted ``fn`` so donated inputs die loudly after each call.

    After ``fn(*args)`` completes (outputs blocked on), every jax leaf
    of each ``args[i]`` for ``i in donate_argnums`` is overwritten with
    ``sentinel`` bytes — unless the executable aliased that buffer into
    an output (donation honored: poisoning would corrupt the result;
    the aliasing itself already invalidates stale host views) or jax
    deleted it. The wrapper forwards ``__wrapped__``, so
    ``harness.trace.jit_cache_size`` / ``compile_watch`` (and through
    them ``serving.prefill_cache_size``) keep probing the real jit.

    ``wrapper.poison_count`` accumulates poisoned buffers — tests
    assert on it to prove the hook engaged rather than silently
    no-op'ing.
    """
    donate_argnums = tuple(donate_argnums)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        out = fn(*args, **kwargs)
        leaves_out = jax.tree_util.tree_leaves(out)
        for leaf in leaves_out:
            jax.block_until_ready(leaf)
        out_ptrs = {
            ptr
            for leaf in leaves_out
            if isinstance(leaf, jax.Array)
            for ptr, _ in _buffer_ptrs(leaf)
        }
        for i in donate_argnums:
            if i >= len(args):
                continue
            for leaf in jax.tree_util.tree_leaves(args[i]):
                if not isinstance(leaf, jax.Array):
                    continue
                try:
                    if leaf.is_deleted():
                        continue
                except Exception:  # noqa: BLE001
                    continue
                for ptr, nbytes in _buffer_ptrs(leaf):
                    if ptr in out_ptrs or nbytes == 0:
                        continue
                    ctypes.memset(ptr, sentinel, nbytes)
                    wrapper.poison_count += 1
        return out

    wrapper.poison_count = 0
    # functools.wraps already set __wrapped__ = fn; make the contract
    # explicit since the trace probe depends on it
    wrapper.__wrapped__ = fn
    return wrapper


#: the serving engine's donating jit entry points and their donated
#: positions — MUST mirror the donate_argnums in models/serving.py
#: (tests/test_analysis.py asserts they stay in sync)
SERVING_POISON_TARGETS: dict[str, tuple[int, ...]] = {
    "_chunk_step": (1, 2, 3, 4, 5),
    "_spec_chunk": (2, 3, 4, 5, 6, 7),
    "_prefill_one": (3,),
    "_admit_row": (0, 1, 2, 3, 4),
}


def install_serving_poison():
    """Swap the serving module's jitted entry points for poisoned
    wrappers; returns an ``uninstall()`` restoring the originals.
    Import stays local so merely importing this module never drags the
    models package in."""
    from hpc_patterns_tpu.models import serving

    originals = {}
    for name, argnums in SERVING_POISON_TARGETS.items():
        originals[name] = getattr(serving, name)
        setattr(serving, name, poison_donated(originals[name], argnums))

    def uninstall():
        for name, fn in originals.items():
            setattr(serving, name, fn)

    return uninstall
