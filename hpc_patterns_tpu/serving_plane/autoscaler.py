"""Elastic serving plane: SLO-feedback autoscaling with residency-
backed warm replica spin-up.

The plane had fixed replica counts, the loadgen produces diurnal and
bursty schedules, the SLO layer computes per-class attainment and
goodput, and chaos can kill replicas — but nobody closed the loop: a
diurnal ramp or a replica death ended in shedding, not adaptation.
This module closes it, in the first-touch spirit of the BLAS
offloading line (arxiv 2501.00279): the signals the observability
stack already records become the controller's inputs.

Three pieces:

- :class:`Autoscaler` — the DECISION half, deliberately pure: it
  observes one :class:`Signals` snapshot per plane round (queue
  pressure, sliding-window SLO attainment, live replica count) and
  emits one :class:`Decision` (``up`` / ``down`` / ``hold``) under an
  :class:`AutoscalerPolicy` with hysteresis bands (``up_queue`` >
  ``down_queue``; attainment must RECOVER past ``down_attainment``
  before a scale-down, not merely clear the scale-up bar), a cooldown
  between actions, and per-plane min/max clamps. No randomness, no
  clock: the same signal trajectory always yields the same decision
  log — which is what lets a chaos run replay against a fix
  (tests/test_autoscaler.py pins hysteresis/cooldown/clamp/
  determinism jax-free).
- :class:`WarmParamPool` — the WARM SPIN-UP half: replica weights
  parked ONCE in the host tier through the PR 10
  :class:`~hpc_patterns_tpu.memory.ResidencyManager` (the manager
  already streams params for training), so scaling up pages bytes
  back instead of re-running ``init_params``. Each spin-up is a
  ``plane.spinup`` device-track window (dispatch at the pull,
  completion when the new engine's state resolves) — the number the
  elastic bench proves is measurably smaller than a cold init.
- :class:`ElasticServingPlane` — the ACTUATION half over the PR 9
  router: scale-UP builds a new replica on warm params;
  scale-DOWN drains — the victim stops receiving routing, its queued
  requests re-route, its in-flight rows EXPORT to survivors through
  the existing ``export_migration``/``install_migration`` path
  (byte-exact; nothing sheds on a voluntary drain), and the replica
  retires only when empty. Involuntary death (the router's
  ``die:replica=N`` chaos) recovers from the plane's RESUME
  CHECKPOINT: per-row observed tokens plus — in sampled mode — the
  per-row PRNG key state snapshotted at each round boundary, so a
  dead replica's streams continue on survivors byte-exact, greedy
  AND sampled (the same contract preemption and migration already
  carry).

The robustness verdict lives in ``bench_serving --elastic``: a
diurnal ramp under replica-death chaos where this plane holds
per-class SLO attainment while the fixed plane demonstrably sheds,
with ``goodput_per_replica_round`` gated so the trajectory rewards
efficiency, not just peak (docs/serving_plane.md "Elastic plane").
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from dataclasses import fields as dataclasses_fields

import numpy as np

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.serving_plane.migration import migrate_pages
from hpc_patterns_tpu.serving_plane.router import Replica, ServingPlane

#: device-subtrack band for ``plane.spinup`` windows — declared in
#: harness/trace.py's TRACK_BANDS between the migration band and the
#: residency band, so a spin-up overlapping either never shares a
#: Chrome sync track with it
SPINUP_TRACK_BASE, SPINUP_TRACKS = tracelib.track_band("spinup")


def spinup_track(ordinal: int) -> int:
    """The device subtrack a replica spin-up's window lands on."""
    return SPINUP_TRACK_BASE + int(ordinal) % SPINUP_TRACKS


# ---------------------------------------------------------------------------
# the decision half (pure, jax-free)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AutoscalerPolicy:
    """The control law's knobs.

    ``up_queue``/``down_queue``: queue-pressure thresholds in QUEUED
    REQUESTS PER LIVE REPLICA, averaged over the signal window. The
    gap between them IS the hysteresis band: scale up only STRICTLY
    above ``up_queue``, scale down only STRICTLY below ``down_queue``
    — a steady load sitting on either boundary holds (no flap).
    ``up_attainment``/``down_attainment``: window SLO-attainment
    thresholds — attainment below ``up_attainment`` scales up even at
    modest queues (latency is the SLO, not depth), and a scale-down
    additionally requires attainment at/above ``down_attainment``
    (capacity is only returned once the SLO has recovered past where
    the scale-up bar sits). ``cooldown_rounds``: rounds after any
    action during which only the min-clamp may act (a death must be
    replaceable immediately; ordinary scaling waits out its own
    transient). ``window``: rounds of signal smoothing."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_queue: float = 3.0
    down_queue: float = 0.5
    up_attainment: float = 0.9
    down_attainment: float = 0.98
    cooldown_rounds: int = 4
    window: int = 8

    @classmethod
    def from_fitted(cls, fitted, **overrides) -> "AutoscalerPolicy":
        """A policy from an autofit ``FittedConfig``: the fitted
        ``autoscaler`` section's hysteresis bands (picked by replaying
        the recorded attainment/queue trajectory through this very
        controller offline and keeping the non-flapping candidate) —
        defaults where the config has no trajectory. Keyword overrides
        win over the fit (deployment clamps like ``max_replicas``
        stay the operator's)."""
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fitted = autofitlib.validate_fitted(fitted)
        section = fitted.get("autoscaler") or {}
        kw = {f.name: section[f.name]
              for f in dataclasses_fields(cls)
              if f.name in section}
        kw.update(overrides)
        return cls(**kw)

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}")
        if not 0.0 <= self.down_queue < self.up_queue:
            raise ValueError(
                f"hysteresis needs 0 <= down_queue < up_queue, got "
                f"{self.down_queue}/{self.up_queue} — equal thresholds "
                "flap at a steady boundary load")
        if not 0.0 <= self.up_attainment <= self.down_attainment <= 1.0:
            raise ValueError(
                f"need 0 <= up_attainment <= down_attainment <= 1, got "
                f"{self.up_attainment}/{self.down_attainment}")
        if self.cooldown_rounds < 0 or self.window < 1:
            raise ValueError(
                f"cooldown_rounds >= 0 and window >= 1 required, got "
                f"{self.cooldown_rounds}/{self.window}")


@dataclass(frozen=True)
class Signals:
    """One plane round's observed state — everything the controller
    is allowed to see. ``attained``/``judged``: requests resolved
    inside the policy window and how many of them met their class SLO
    (shed counts as judged-and-missed)."""

    round: int
    replicas: int        # live, non-draining
    queued: int          # total queue depth across them
    active: int          # total active rows
    #: requests resolved THIS round (a per-round delta, like every
    #: other field): the controller's own window is the ONLY
    #: smoothing — a producer must not pre-aggregate, or each
    #: judgment counts up to window× and lags decisions ~2×window
    attained: int = 0
    judged: int = 0


@dataclass(frozen=True)
class Decision:
    """One round's verdict, with the evidence that produced it — the
    decision log is the replay/determinism handle."""

    round: int
    action: str          # "up" | "down" | "hold"
    reason: str
    replicas: int        # live count the decision saw
    pressure: float      # window-mean queued-per-replica
    attainment: float | None  # window attainment (None: nothing judged)


class Autoscaler:
    """The pure controller: ``observe(signals) -> Decision``, one call
    per plane round. Holds only the signal window, the cooldown
    counter, and the decision log — a deterministic function of the
    signal sequence (pinned by tests/test_autoscaler.py)."""

    def __init__(self, policy: AutoscalerPolicy | None = None):
        self.policy = policy or AutoscalerPolicy()
        self._window: deque = deque(maxlen=self.policy.window)
        self._cooldown = 0
        self.decisions: list[Decision] = []

    def _decide(self, sig: Signals) -> tuple[str, str]:
        p = self.policy
        pressure = self.pressure
        att = self.attainment
        # the min-clamp outranks the cooldown: a replica death below
        # the floor must be replaceable THIS round, not after waiting
        # out the transient of the very action that dropped the count
        if sig.replicas < p.min_replicas:
            return "up", (f"below min_replicas "
                          f"({sig.replicas} < {p.min_replicas})")
        if self._cooldown > 0:
            return "hold", f"cooldown ({self._cooldown} round(s) left)"
        if sig.replicas < p.max_replicas:
            if pressure > p.up_queue:
                return "up", (f"queue pressure {pressure:.2f} > "
                              f"{p.up_queue}")
            if att is not None and att < p.up_attainment:
                return "up", (f"attainment {att:.2f} < "
                              f"{p.up_attainment}")
        if sig.replicas > p.min_replicas \
                and pressure < p.down_queue and sig.queued == 0 \
                and (att is None or att >= p.down_attainment):
            return "down", (f"queue pressure {pressure:.2f} < "
                            f"{p.down_queue}, attainment recovered")
        return "hold", "inside the hysteresis band"

    @property
    def pressure(self) -> float:
        """Window-mean queued requests per live replica."""
        if not self._window:
            return 0.0
        return sum(s.queued / max(1, s.replicas)
                   for s in self._window) / len(self._window)

    @property
    def attainment(self) -> float | None:
        """Window SLO-attainment fraction; None when nothing was
        judged inside the window (no verdict = no latency evidence)."""
        judged = sum(s.judged for s in self._window)
        if not judged:
            return None
        return sum(s.attained for s in self._window) / judged

    def observe(self, sig: Signals) -> Decision:
        self._window.append(sig)
        action, reason = self._decide(sig)
        if self._cooldown > 0:
            self._cooldown -= 1
        if action != "hold":
            self._cooldown = self.policy.cooldown_rounds
        dec = Decision(round=sig.round, action=action, reason=reason,
                       replicas=sig.replicas, pressure=self.pressure,
                       attainment=self.attainment)
        self.decisions.append(dec)
        return dec


# ---------------------------------------------------------------------------
# the warm spin-up half (residency-backed parked weights)
# ---------------------------------------------------------------------------


class WarmParamPool:
    """Replica weights parked in the HOST tier, pulled per spin-up.

    The params tree is pushed ONCE through the residency manager's
    instrumented pipeline (``mem.evict`` window; pinned-host jax
    arrays where the backend has them, numpy otherwise — the same
    tier model training's opt-state streaming uses) and registered as
    a host-tier group. Each :meth:`pull` dispatches an independent
    host->HBM copy (``mem.prefetch`` window) — a READ-THROUGH of the
    parked template, which stays host-resident for the next spin-up —
    and the caller observes completion via :meth:`complete`. This is
    why elastic scale-up is warm: the bytes already exist, nothing
    re-runs ``init_params``."""

    def __init__(self, params, *, manager=None):
        import jax

        from hpc_patterns_tpu.memory import ResidencyManager

        leaves = jax.tree.leaves(params)
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in leaves)
        self.manager = manager or ResidencyManager(
            host_blocks=max(1, len(leaves)))
        self.manager.register_group(
            "warm_params", len(leaves), nbytes, tier="host")
        self.host_params = self.manager.push_payload(
            params, attrs={"what": "warm_params"})
        self.manager.drain()  # the park is complete; close its window
        self.pulls = 0

    def pull(self):
        """Dispatch one host->HBM copy of the parked weights; returns
        ``(device_params, handle)`` — dispatch-only, the engine build
        enqueues behind it."""
        payload, handle = self.manager.pull_payload(
            self.host_params, attrs={"what": "warm_params",
                                     "pull": self.pulls})
        self.pulls += 1
        return payload, handle

    def complete(self, handle) -> None:
        """Close the pull's ``mem.prefetch`` window at an observed
        completion (the caller just blocked on the new engine)."""
        self.manager.complete_pull(handle)


# ---------------------------------------------------------------------------
# the actuation half: the elastic plane
# ---------------------------------------------------------------------------


class ElasticServingPlane(ServingPlane):
    """A :class:`~hpc_patterns_tpu.serving_plane.router.ServingPlane`
    that changes shape under the controller (module docstring has the
    design). ``engine_factory(params) -> EngineCore`` builds a new
    replica's engine on warm-pulled weights — it must produce engines
    construction-compatible with the existing ones (same config,
    sampling mode, and seed; validated on every spin-up).

    Death recovery: each replica round ends with a RESUME CHECKPOINT
    (observed tokens per active row, plus the per-row sampling key
    state in sampled mode — the PR 9 remainder); an involuntary death
    re-submits each in-flight row on a survivor as an ordinary resume
    (prompt = original + observed, ``resume_prefix``, the snapshot
    key), which is the byte-exactness contract preemption already
    proved. Queued requests re-route; bundles parked toward the dead
    replica re-target. Only a request NO survivor can hold sheds."""

    def __init__(self, replicas, *, engine_factory, warm_pool,
                 autoscaler: Autoscaler | None = None,
                 new_replica_role: str = "both", **kw):
        super().__init__(replicas, **kw)
        self.engine_factory = engine_factory
        self.warm_pool = warm_pool
        self.autoscaler = autoscaler or Autoscaler()
        self.new_replica_role = new_replica_role
        self._next_replica = len(self.replicas)
        self._round_no = 0
        #: resume checkpoint: sid -> {"out": [...], "key": (2,) uint32
        #: numpy or None, "replica": name} — refreshed at every round
        #: boundary, dropped on resolution
        self._ckpt: dict[int, dict] = {}
        #: requests awaiting an SLO judgment: entered at submit (and
        #: at the unplaceable-arrival shed), removed once judged — so
        #: the per-round judge pass costs O(unresolved), not O(every
        #: request the plane ever served)
        self._unjudged: set[int] = set()
        #: attained? verdicts of requests resolved since the last
        #: signal — drained into ONE Signals delta per plane round
        self._judgments: deque = deque(maxlen=4096)
        #: death-resumes per sid: folded into the stats row's
        #: preemption count at resolution (the engine-side count
        #: _collect_finished copies in cannot know about them — the
        #: engine that held the earlier leg is dead)
        self._death_resumes: dict[int, int] = {}
        self.spinup_s: list[float] = []
        self.resumed: list[int] = []
        self.drained: list[str] = []
        self.retired: list[str] = []

    # -- signals -----------------------------------------------------------

    def _signals(self) -> Signals:
        live = [r for r in self.replicas
                if r.alive and not r.draining]
        # drain THIS round's judgments: the Signals carry per-round
        # deltas and the controller's deque is the only smoothing
        # window (pre-aggregating here would double-window attainment
        # — each judgment counted up to window× and felt ~2×window)
        attained = sum(1 for a in self._judgments if a)
        judged = len(self._judgments)
        self._judgments.clear()
        return Signals(
            round=self._round_no,
            replicas=len(live),
            queued=sum(r.engine.queue_depth for r in live),
            active=sum(r.engine.active_count for r in live),
            attained=attained,
            judged=judged,
        )

    def submit(self, prompt, max_new: int, **kw) -> int:
        rid = super().submit(prompt, max_new, **kw)
        self._unjudged.add(rid)
        return rid

    def _shed_request(self, sid: int, *, on_death: bool = False) -> None:
        # the one resolution path that can create a stats row WITHOUT
        # going through submit (the unplaceable-arrival shed in the
        # base run loop) — make sure the judge pass sees it
        if self.stats.get(sid, {}).get("outcome") is None:
            self._unjudged.add(sid)
        super()._shed_request(sid, on_death=on_death)

    def _judge_resolved(self) -> None:
        """Judge every request that resolved since the last pass into
        the controller's signal (shed = judged-and-missed; the signal
        must see degradation). Once per PLANE round, over the
        ``_unjudged`` set only — O(unresolved), not O(history)."""
        for sid in list(self._unjudged):
            ps = self.stats.get(sid)
            if ps is None or ps.get("outcome") is None:
                continue
            self._unjudged.discard(sid)
            # the serving engine's preemption count (copied in by the
            # base collect on a finish; untouched on a shed) cannot
            # include death-resumes — the engine that held the
            # earlier leg is gone — so they are folded in HERE, once,
            # at resolution (and nowhere in flight, or a
            # resumed-then-shed row would count each resume twice)
            ps["preemptions"] = (int(ps.get("preemptions") or 0)
                                 + self._death_resumes.pop(sid, 0))
            target = (self.slo or {}).get(
                ps.get("priority", 0), slolib.SLOTarget())
            self._judgments.append(slolib.attained(ps, target))
            self._ckpt.pop(sid, None)

    def _collect_finished(self, r: Replica) -> int:
        n = super()._collect_finished(r)
        self._checkpoint_replica(r)
        return n

    # -- the resume checkpoint ---------------------------------------------

    def _checkpoint_replica(self, r: Replica) -> None:
        """Refresh the resume checkpoint for one replica at its round
        boundary: the chunk is collected, so each active row's
        ``out`` and the post-chunk key state are CONSISTENT — exactly
        the (tokens, key) pair ``_preempt``'s snapshot carries, which
        is what makes a death-resume byte-exact in sampled mode."""
        import jax

        eng = r.engine
        act = [(i, s) for i, s in enumerate(eng._slots) if s.active]
        if not act:
            return
        keys = None
        if not eng.greedy:
            # jaxlint: disable=host-sync-in-dispatch — a deliberate
            # round-boundary snapshot (the chunk readback already
            # synced this round); np.array COPIES the device_get view
            # that a later donated _chunk_step would otherwise mutate
            keys = np.array(jax.device_get(eng.keys))
        for i, s in act:
            self._ckpt[s.seq_id] = {
                "out": list(s.out),
                "key": keys[i].copy() if keys is not None else None,
                "replica": r.name,
                # the engine-side first-token stamp: a death-resume
                # must keep the TTFT the user actually saw, not the
                # survivor's post-resume readback (the same invariant
                # _dispatch_migration preserves via bundle.t_first)
                "t_first": eng.stats.get(s.seq_id, {}).get("t_first"),
            }

    # -- death recovery (overrides the static shed) ------------------------

    def _recover_casualties(self, r: Replica, active_sids, queued_sids,
                            bundles) -> None:
        for sid in active_sids:
            ck = self._ckpt.get(sid)
            req = self._requests.get(sid)
            if ck is None or req is None:
                self._shed_request(sid, on_death=True)
                continue
            out = ck["out"]
            if len(out) >= req["max_new"]:
                # fully emitted, finish report lost with the replica:
                # the observed tokens ARE the output
                ps = self.stats[sid]
                ps["outcome"], ps["tokens"] = "ok", len(out)
                if ps["t_first"] is None:
                    ps["t_first"] = ck.get("t_first")
                ps["t_finish"] = time.perf_counter()
                # jaxlint: disable=host-sync-in-dispatch — host-list
                # packing of checkpoint tokens (plain Python ints the
                # collected chunks already materialized), no readback
                self.finished[sid] = np.asarray(out, np.int32)
                self._requests.pop(sid, None)
                continue
            if self._resume_request(sid, req, out, ck):
                self.resumed.append(sid)
            else:
                self._shed_request(sid, on_death=True)
        for sid in queued_sids:
            req = self._requests.get(sid)
            if req is None or not self._route_again(sid, req):
                self._shed_request(sid, on_death=True)
        for b in bundles:
            dst = self._pick_target(b.n_pages, r)
            if dst is None:
                self._shed_request(b.seq_id, on_death=True)
                continue
            self._mig_open[b.seq] = (0.0, time.perf_counter())
            dst.pending_migrations.append(migrate_pages(b, dst.device))

    def _resume_request(self, sid: int, req: dict, out, ck) -> bool:
        """Continue a dead replica's in-flight row on a survivor as an
        ordinary RESUME: prompt = original + observed tokens, the
        checkpoint key seeding the sampled stream where the dead
        engine's left off. Byte-exact by the preemption contract
        (``_admit_row`` consumes the snapshot key with the split/pick
        order ``_chunk_step`` would have)."""
        import jax.numpy as jnp

        key = ck.get("key")
        # jaxlint: disable=host-sync-in-dispatch — host-list packing
        # of checkpoint tokens, not a device readback (the _preempt
        # resume-Request contract)
        out_arr = np.asarray(out, np.int32)
        prompt = (np.concatenate([req["prompt"], out_arr])
                  if len(out_arr) else req["prompt"])
        remaining = req["max_new"] - len(out_arr)
        target = self._pick_survivor(int(prompt.size), remaining)
        if target is None:
            return False
        kw = {}
        if not target.engine.greedy and key is not None:
            # jaxlint: disable=host-sync-in-dispatch — the key is the
            # checkpoint's HOST numpy copy (snapshotted at a prior
            # round boundary); this re-wraps it for upload, no device
            # value is read
            kw["key"] = jnp.asarray(np.asarray(key, np.uint32))
        target.engine.submit(
            prompt, remaining, seq_id=sid,
            priority=req["priority"], deadline_s=req["deadline_s"],
            temperature=req["temperature"],
            resume_prefix=out_arr if len(out_arr) else None, **kw)
        self._assignment[sid] = target
        ps = self.stats[sid]
        # the row's story continues, its clocks do not restart: TTFT
        # keeps the first token the USER saw on the dead replica (the
        # checkpoint carried it — the _dispatch_migration invariant),
        # and the collect-time merge guard (`if t_first is None`)
        # then never overwrites it with the survivor's readback
        if ps["t_first"] is None:
            ps["t_first"] = ck.get("t_first")
        # counted ONLY via _death_resumes, folded in at resolution:
        # an in-flight ps increment would double-count every resume
        # of a row that later sheds (no engine finish ever overwrites
        # the in-flight value for those)
        self._death_resumes[sid] = (
            self._death_resumes.get(sid, 0) + 1)
        ps["replica"] = target.name
        self._emit(kind="plane_resume", seq_id=sid,
                   replica=target.name, tokens=len(out_arr))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.death_resumes").inc()
        return True

    def _route_again(self, sid: int, req: dict) -> bool:
        """Re-route a queued (no device state) casualty wholesale."""
        target = self._pick_survivor(int(req["prompt"].size),
                                     req["max_new"])
        if target is None:
            return False
        target.engine.submit(
            req["prompt"], req["max_new"], seq_id=sid,
            priority=req["priority"], deadline_s=req["deadline_s"],
            temperature=req["temperature"], key=req["key"])
        # the request's clocks do not restart on re-routing: the shed
        # deadline and TTFT still count from the ORIGINAL submit (the
        # same re-stamp the open-loop arrival path applies), or a
        # re-route would silently grant a fresh deadline_s window
        t0 = self.stats[sid]["t_submit"]
        target.engine._queue[-1].t_submit = t0
        target.engine.stats[sid]["t_submit"] = t0
        self._assignment[sid] = target
        self.stats[sid]["replica"] = target.name
        return True

    def _pick_survivor(self, prompt_len: int,
                       max_new: int) -> Replica | None:
        cand = [r for r in self.replicas
                if r.alive and not r.draining
                and r.engine.would_fit(prompt_len, max_new)]
        if not cand:
            return None
        return max(cand, key=lambda r: (r.engine.free_page_count,
                                        -r.engine.queue_depth,
                                        -r.index))

    # -- the control loop --------------------------------------------------

    def _autoscale_round(self) -> bool:
        self._round_no += 1
        self._judge_resolved()
        changed = self._drain_step()
        dec = self.autoscaler.observe(self._signals())
        if dec.action == "up":
            changed |= self._spin_up(reason=dec.reason)
        elif dec.action == "down":
            changed |= self._begin_drain(reason=dec.reason)
        return changed

    def _spin_up(self, *, reason: str = "") -> bool:
        """Warm scale-up: pull the parked weights from the host tier,
        build a fresh replica on them, and join the plane — the whole
        acquisition measured as ONE ``plane.spinup`` device window
        (dispatch at the pull, completion when the engine's device
        state resolves), which is the number the bench compares
        against a cold ``init_params``."""
        import jax

        name = f"r{self._next_replica}"
        rec = tracelib.active()
        t0 = time.perf_counter()
        t_disp = (rec.mark_dispatch(
            "plane.spinup", {"replica": name, "reason": reason},
            track=spinup_track(self._next_replica))
            if rec is not None else 0.0)
        params, handle = self.warm_pool.pull()
        engine = self.engine_factory(params)
        rep = Replica(engine, name=name, role=self.new_replica_role)
        # jaxlint: disable=host-sync-in-dispatch — completion
        # measurement: the spin-up window must not close before the
        # pulled params and the engine's fresh device state resolved
        jax.block_until_ready((params, engine.temps))
        self.warm_pool.complete(handle)
        dt = time.perf_counter() - t0
        rep.index = self._next_replica
        self._next_replica += 1
        if rep.can_decode:
            engine.track_chunk_windows = True
        self.replicas.append(rep)
        try:
            self._validate_engines()
        except ValueError:
            self.replicas.pop()
            raise
        self.spinup_s.append(dt)
        if rec is not None and t_disp:
            rec.mark_complete(
                "plane.spinup", t_disp,
                {"replica": name, "spinup_s": round(dt, 6)},
                track=spinup_track(rep.index))
        self._emit(kind="plane_spinup", replica=name,
                   spinup_s=dt, reason=reason)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.spinups").inc()
            m.gauge("plane.replicas").set(
                sum(1 for x in self.replicas
                    if x.alive and not x.draining))
        return True

    def _begin_drain(self, *, reason: str = "") -> bool:
        """Voluntary scale-down: pick the emptiest live replica and
        put it in DRAIN — no new routing, no inbound migrations; its
        work leaves through :meth:`_drain_step`. Refuses a victim
        whose loss would strand a role (the last prefill- or
        decode-capable replica stays)."""
        live = [r for r in self.replicas
                if r.alive and not r.draining]
        if len(live) <= self.autoscaler.policy.min_replicas:
            return False
        cand = []
        for r in live:
            rest = [x for x in live if x is not r]
            if not any(x.can_prefill for x in rest) \
                    or not any(x.can_decode for x in rest):
                continue
            cand.append(r)
        if not cand:
            return False
        victim = min(cand, key=lambda r: (
            r.engine.active_count + r.engine.queue_depth
            + len(r.pending_migrations),
            -r.index))
        victim.draining = True
        self.drained.append(victim.name)
        self._emit(kind="plane_drain", replica=victim.name,
                   reason=reason)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("plane.drains").inc()
        return True

    def _drain_step(self) -> bool:
        """Advance every draining replica one step: re-route its
        queued requests, EXPORT its active rows to survivors through
        the PR 9 migration path (in-flight work migrates byte-exact —
        nothing sheds on a voluntary drain; a row with no destination
        this round just waits), and retire the replica once empty."""
        changed = False
        for r in self.replicas:
            if not (r.alive and r.draining):
                continue
            for req in list(r.engine._queue):
                target = self._pick_survivor(int(req.prompt.size),
                                             req.max_new)
                if target is None:
                    continue  # stays queued; retried next round
                r.engine._queue = [q for q in r.engine._queue
                                   if q is not req]
                r.engine.stats.pop(req.seq_id, None)
                target.engine.submit(
                    req.prompt, req.max_new, seq_id=req.seq_id,
                    priority=req.priority, deadline_s=req.deadline_s,
                    temperature=req.temperature, key=req.key,
                    resume_prefix=req.resume_prefix)
                # clocks do not restart on a drain re-route (the
                # _route_again rule): the shed deadline still counts
                # from the request's ORIGINAL submit instant
                target.engine._queue[-1].t_submit = req.t_submit
                target.engine.stats[req.seq_id]["t_submit"] = \
                    req.t_submit
                self._assignment[req.seq_id] = target
                self.stats[req.seq_id]["replica"] = target.name
                changed = True
            with r.device_ctx():
                for slot in r.engine.exportable_slots():
                    need = len(r.engine._slots[slot].pages)
                    dst = self._pick_target(need, r)
                    if dst is None:
                        continue  # parked on the donor; next round
                    self._dispatch_migration(r, slot, dst)
                    changed = True
            if not r.engine.has_work() and not r.pending_migrations:
                r.alive = False
                r.draining = False
                self.retired.append(r.name)
                self._emit(kind="plane_retire", replica=r.name)
                m = metricslib.get_metrics()
                if m.enabled:
                    m.gauge("plane.replicas").set(
                        sum(1 for x in self.replicas
                            if x.alive and not x.draining))
                changed = True
        return changed
