"""Known-clean: the serving-plane handoff discipline.

Every rank (router and replicas alike) issues the same migration
sequence — placement is DATA the router computes, never a branch on
the executing rank — and the migration dispatch path stays
dispatch-only: the gather, the cross-device copy, and the install all
enqueue behind the in-flight decode chunk; the one deliberate
readback (the donor's cursor snapshot) lives inside
``export_migration`` with its justified suppression, not here.
"""

from hpc_patterns_tpu.serving_plane.migration import migrate_pages


def uniform_handoff(bundle, device):
    # every rank migrates; the destination is data, not rank identity
    return migrate_pages(bundle, device)


def _dispatch_migration(engine, slot, device):
    # dispatch-only: export gathers on device, the copy enqueues async
    bundle = engine.export_migration(slot)
    return migrate_pages(bundle, device)
