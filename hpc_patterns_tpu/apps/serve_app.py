"""Serve app: continuous batching over the paged KV cache, validated.

Completes the lifecycle triad's serving leg as a CLI: a stream of
requests with varied prompt lengths (``--prompt-mix``) and budgets
served through models/serving.ContinuousBatcher (page free-list,
bucketed admission, overlapped prefill, per-row sampling), then EVERY
sequence validated token-exact against its standalone
``paged_generate`` — greedy AND sampled (per-request key streams keep
sampled serving standalone-exact); draft-assisted sampling is the one
law-only combination (its distribution oracle lives in
tests/test_serving.py). The reference's benchmark-IS-the-test
discipline (SURVEY.md §4: the binary measures its own claim and exits
SUCCESS/FAILURE). Reports tokens/s, the admission-bubble fraction,
and the prefill compile count (bounded by the bucket ladder); with
``--static-compare``, the static-batching baseline wall clock.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import (
    add_autofit_arg,
    add_explain_args,
    add_kv_dtype_arg,
    add_serving_args,
    base_parser,
    explain_enabled,
    load_autofit,
    parse_buckets,
    resolve_kv_cache_dtype,
)
from hpc_patterns_tpu.harness import explain as explainlib
from hpc_patterns_tpu.harness import reqtrace as reqtracelib
from hpc_patterns_tpu.models import TransformerConfig, init_params


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    add_serving_args(p)
    add_autofit_arg(p)
    add_explain_args(p)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--slots", type=int, default=2,
                   help="concurrent rows in the pool")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--chunk", type=int, default=4,
                   help="decode steps per jitted dispatch (admission "
                        "granularity)")
    p.add_argument("--prompt-len", type=int, default=12)
    p.add_argument("--prompt-mix", action="store_true",
                   help="vary prompt lengths 1/2..1x of --prompt-len "
                        "(the mixed-length stream the bucket ladder "
                        "exists for)")
    p.add_argument("--budget", type=int, default=12,
                   help="max new tokens per request (actual budgets "
                        "vary 1/4..1x)")
    p.add_argument("--pool-pages", type=int, default=0,
                   help="shared arena size (0 = slots * pages needed "
                        "for prompt+budget)")
    p.add_argument("--eos-id", type=int, default=-1,
                   help=">= 0: end rows early at this token")
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--pos-embed", default="learned",
                   choices=["learned", "rope"])
    # the shared serving-precision knob; bf16 = the config's default
    # compute dtype with a scale-free cache (the pre-knob behavior)
    add_kv_dtype_arg(p, default="bf16")
    p.add_argument("--checkpoint-dir", default=None,
                   help="serve a trained checkpoint (train_app "
                        "--checkpoint-dir); default: fresh init")
    p.add_argument("--draft-pair", default=None, metavar="DIR",
                   help="serve an aligned draft/target pair "
                        "(benchmarks/make_draft_pair.py): speculative "
                        "rounds inside the engine — rows advance "
                        "1..gamma+1 tokens per dispatch (overrides the "
                        "model-dim flags with the pair's configs)")
    p.add_argument("--gamma", type=int, default=4,
                   help="draft proposals per round with --draft-pair")
    p.add_argument("--static-compare", action="store_true",
                   help="also time static batching (batches of "
                        "--slots padded to the batch max budget)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="enable the flight recorder (implies --trace) "
                        "and write the Chrome-trace JSON timeline here "
                        "at exit — one flag from serving run to "
                        "Perfetto-loadable timeline")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    topology.init_distributed_from_env()
    from hpc_patterns_tpu.models.decode import paged_generate
    from hpc_patterns_tpu.models.serving import ContinuousBatcher

    need = args.prompt_len + args.budget
    try:
        buckets = parse_buckets(args.prompt_buckets, args.prompt_len)
        if args.autofit is not None:
            # the fitted ladder replaces the default 'auto' ladder;
            # an explicit --prompt-buckets value still wins
            from hpc_patterns_tpu.harness import autofit as autofitlib

            fitted = load_autofit(args.autofit)
            fitted_buckets = autofitlib.ladder_from(
                fitted, max_seq=args.prompt_len)
            if (args.prompt_buckets.strip().lower() == "auto"
                    and fitted_buckets is not None):
                buckets = fitted_buckets
                log.print(f"autofit ladder from {args.autofit}: "
                          f"{list(buckets)}")
    except (OSError, ValueError, argparse.ArgumentTypeError) as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    draft_params = draft_cfg = None
    if args.draft_pair and args.checkpoint_dir:
        log.print("ERROR: --draft-pair serves the pair's own target "
                  "checkpoint; --checkpoint-dir would be silently "
                  "ignored — pass one or the other")
        log.print("FAILURE")
        return 1
    if args.draft_pair and args.kv_dtype != "bf16":
        log.print("ERROR: --draft-pair serves from the pair's own "
                  "compute-dtype caches (META.json configs); "
                  f"--kv-dtype {args.kv_dtype} would be silently "
                  "ignored — drop it or serve without the pair")
        log.print("FAILURE")
        return 1
    # off-TPU serving takes the pure-XLA gather route on BOTH branches
    # (the pallas kernels interpret per grid point there)
    attn = "flash" if jax.default_backend() == "tpu" else "gather"
    try:
        if args.draft_pair:
            import json
            import os

            from hpc_patterns_tpu.utils.checkpoint import restore_params

            with open(os.path.join(args.draft_pair, "META.json")) as f:
                meta = json.load(f)
            cfg = TransformerConfig(**{**meta["target_cfg"],
                                       "max_seq": need,
                                       "decode_attn": attn})
            draft_cfg = TransformerConfig(**{**meta["draft_cfg"],
                                             "max_seq": need,
                                             "decode_attn": attn})
            params, _ = restore_params(
                os.path.join(args.draft_pair, "target"))
            draft_params, _ = restore_params(
                os.path.join(args.draft_pair, "draft"))
            log.print(f"aligned pair from {args.draft_pair} "
                      f"(gamma={args.gamma})")
        else:
            compute_dt, kv_dt = resolve_kv_cache_dtype(
                args.kv_dtype, note=log.print)
            cfg = TransformerConfig(
                vocab=args.vocab, d_model=args.d_model,
                n_heads=args.n_heads, n_layers=args.n_layers,
                d_ff=4 * args.d_model, max_seq=need,
                n_kv_heads=args.n_kv_heads, pos_embed=args.pos_embed,
                kv_cache_dtype=kv_dt,
                **({"dtype": compute_dt} if compute_dt else {}),
                decode_attn=attn,
            )
    except (ValueError, FileNotFoundError, KeyError) as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    if args.requests < 1 or args.slots < 1 or args.budget < 1:
        log.print("ERROR: --requests/--slots/--budget must be >= 1")
        log.print("FAILURE")
        return 1
    if not args.draft_pair:
        params = init_params(jax.random.PRNGKey(0), cfg)
        if args.checkpoint_dir:
            from hpc_patterns_tpu.utils.checkpoint import restore_params

            try:
                params, step = restore_params(args.checkpoint_dir)
                log.print(
                    f"restored step {step} from {args.checkpoint_dir}")
            except (FileNotFoundError, ValueError, KeyError) as e:
                log.print(f"ERROR: cannot restore "
                          f"{args.checkpoint_dir}: {e}")
                log.print("FAILURE")
                return 1

    # the engine owns the sizing rule (incl. speculative slack and the
    # bucket-padded prefill length — the pool must hold the padded
    # prompt even when the budget alone would need fewer pages)
    from hpc_patterns_tpu.models.serving import pad_to_bucket

    try:
        padded_max = pad_to_bucket(buckets, args.prompt_len)
    except ValueError as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    pages_per_seq = ContinuousBatcher.pages_needed(
        args.prompt_len, args.budget, args.page_size,
        gamma=args.gamma if draft_params is not None else None,
        padded_len=padded_max)
    pool_pages = args.pool_pages or args.slots * pages_per_seq
    rng = np.random.RandomState(7)
    reqs = []
    for _ in range(args.requests):
        plen = (int(rng.randint(max(1, args.prompt_len // 2),
                                args.prompt_len + 1))
                if args.prompt_mix else args.prompt_len)
        prompt = rng.randint(0, cfg.vocab, size=plen).astype(np.int32)
        budget = int(rng.choice([max(1, args.budget // 4),
                                 max(1, args.budget // 2), args.budget]))
        reqs.append((prompt, budget))
    total_budget = sum(b for _, b in reqs)
    sampled = args.temperature > 0.0
    spec = draft_params is not None

    def serve():
        # constructor/submit ValueErrors (bad gamma, vocab mismatch,
        # oversize request) keep the clean ERROR/FAILURE contract too,
        # not just run()'s RuntimeError
        try:
            eng = ContinuousBatcher(
                params, cfg, slots=args.slots, pool_pages=pool_pages,
                pages_per_seq=pages_per_seq, page_size=args.page_size,
                chunk=args.chunk,
                eos_id=args.eos_id if args.eos_id >= 0 else None,
                draft_params=draft_params, draft_cfg=draft_cfg,
                gamma=args.gamma, emit=log.emit,
                prompt_buckets=buckets, overlap=not args.no_overlap,
                temperature=args.temperature, top_k=args.top_k,
                seed=args.seed,
            )
            ids = [eng.submit(p, b) for p, b in reqs]
            got = eng.run()
        except (ValueError, RuntimeError) as e:
            return None, None, str(e)
        return {i: got[sid] for i, sid in enumerate(ids)}, eng, None

    # warmup (compiles) — keep its records out of the registry: its
    # TTFT would be compile-dominated and its counters would double
    # every request (the warmup-vs-timed discipline of harness.timing)
    from hpc_patterns_tpu.models.serving import prefill_cache_size

    m = metricslib.get_metrics()
    prev_enabled = m.enabled
    m.enabled = False
    compiles0 = prefill_cache_size()  # other engines, this process
    try:
        out, _, err = serve()
    finally:
        m.enabled = prev_enabled
    if err is not None:
        log.print(f"ERROR: {err}")
        log.print("FAILURE")
        return 1
    # THIS engine's admission-prefill compiles (cold); the measured
    # run below must add none (warm)
    compiles_cold = prefill_cache_size() - compiles0
    compiles_before = prefill_cache_size()
    if explain_enabled(args):
        # fresh recorder for the MEASURED run only: the warm-up run
        # above reused the same seq ids, and one recorder is one id
        # space (the bench-leg reconfigure discipline)
        reqtracelib.configure(enabled=True)
    t0 = time.perf_counter()
    with metricslib.span("serve.measure"):
        out, eng, _ = serve()
    dt = time.perf_counter() - t0
    served = sum(len(v) for v in out.values())
    bubble = eng.last_bubble_frac
    compiles_warm = prefill_cache_size() - compiles_before
    metricslib.get_metrics().gauge("serve.tokens_per_s").set(served / dt)

    # the oracle: every sequence token-exact vs standalone paged decode
    # with the SAME per-request key/temperature (truncated at eos when
    # enabled — same rule the engine applies). Draft-assisted sampling
    # is the one law-only combination (the rejection-sampling rounds
    # preserve the emitted law, not the draws — its distribution
    # oracle lives in tests/test_serving.py); it gets a bounds check.
    exact = True
    for i, (prompt, budget) in enumerate(reqs):
        if sampled and spec:
            ok_i = (1 <= len(out[i]) <= budget
                    and np.all(out[i] >= 0)
                    and np.all(out[i] < cfg.vocab))
            if not ok_i:
                exact = False
                log.print(f"OUT-OF-BOUNDS seq {i}: {out[i][:8]}...")
            continue
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None, :], cfg, budget,
            page_size=args.page_size,
            key=eng.request_key(i) if sampled else None,
            temperature=args.temperature, top_k=args.top_k))[0]
        if args.eos_id >= 0 and np.any(want == args.eos_id):
            want = want[:int(np.argmax(want == args.eos_id)) + 1]
        if not np.array_equal(out[i], want):
            exact = False
            log.print(f"MISMATCH seq {i}: engine {out[i][:8]}... vs "
                      f"standalone {want[:8]}...")
    # bound: cold compiles ≤ ladder rungs (x2 with a draft pair — the
    # draft prefill compiles per rung under its own config), and the
    # warm measured run adds none
    max_compiles = (len(buckets) * (2 if spec else 1)
                    if buckets is not None else None)
    bounded = (compiles_warm == 0 and
               (max_compiles is None or compiles_cold <= max_compiles))
    if not bounded:
        log.print(f"COMPILE-BOUND VIOLATION: {compiles_cold} cold + "
                  f"{compiles_warm} warm prefill compiles vs ladder "
                  f"bound {max_compiles} (warm must add none)")
    ok = exact and bounded and served > 0
    log.emit(kind="result", name="serve", success=ok,
             requests=args.requests, slots=args.slots,
             pool_pages=pool_pages, page_size=args.page_size,
             chunk=args.chunk, served_tokens=served,
             tokens_per_s=served / dt, oracle_exact=exact,
             bubble_frac=bubble, prefill_compiles=compiles_cold,
             prefill_compiles_warm=compiles_warm,
             prompt_buckets=list(buckets) if buckets else None,
             temperature=args.temperature, top_k=args.top_k,
             overlap=not args.no_overlap)
    mode = ("draft+sampled law" if sampled and spec
            else "sampled exact" if sampled else "exact")
    log.print(f"serve[{args.slots} slots, pool {pool_pages}p x "
              f"{args.page_size}] {args.requests} reqs, {served} tokens "
              f"(budget {total_budget}): {dt:.3f}s, "
              f"{served / dt:,.1f} tok/s, bubble {bubble:.1%}, "
              f"{compiles_cold} prefill compiles"
              f"{f' (ladder {len(buckets)})' if buckets else ''}"
              f"{f' +{compiles_warm} warm' if compiles_warm else ''}, "
              f"oracle[{mode}] {'ok' if exact else 'MISMATCH'}")

    rtr = reqtracelib.active()
    if rtr is not None:
        snap = rtr.snapshot(eng.stats)
        log.emit(kind="reqtrace", **snap)
        dig = explainlib.digest([snap])
        log.print(explainlib.format_explain(dig))
        if args.explain_out:
            import json as _json
            from pathlib import Path as _Path

            _Path(args.explain_out).write_text(
                _json.dumps(dig) + "\n")
            log.print(f"explain digest -> {args.explain_out}")

    if args.static_compare:
        def run_static():
            # static batching of a mixed-length stream: batches of
            # `slots` in arrival order; rows inside a batch group by
            # prompt length (rectangular batches only) and every row
            # pays the batch's LONGEST budget — the fragmentation +
            # padding waste the engine exists to remove
            o = {}
            skey = jax.random.PRNGKey(args.seed)
            for i in range(0, args.requests, args.slots):
                batch = reqs[i:i + args.slots]
                run_len = max(b for _, b in batch)
                bylen: dict[int, list] = {}
                for j, (p, b) in enumerate(batch):
                    bylen.setdefault(len(p), []).append((i + j, p, b))
                for group in bylen.values():
                    prompts = jnp.asarray(
                        np.stack([p for _, p, _ in group]))
                    toks = np.asarray(paged_generate(
                        params, prompts, cfg, run_len,
                        page_size=args.page_size,
                        key=skey if sampled else None,
                        temperature=args.temperature,
                        top_k=args.top_k))
                    for j, (idx, _, b) in enumerate(group):
                        o[idx] = toks[j, :b]
            return o

        run_static()  # warmup
        t0 = time.perf_counter()
        run_static()
        ts = time.perf_counter() - t0
        log.print(f"static batching: {ts:.3f}s "
                  f"({served / ts:,.1f} tok/s) — engine/static "
                  f"{ts / dt:.2f}x")

    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.trace_out:
        args.trace = True
    try:
        return common.run_instrumented(run, args)
    finally:
        # ANY exit path writes the timeline (run_instrumented leaves
        # the per-run recorder installed): a crashed serving run still
        # produces a loadable artifact showing where it died
        if args.trace_out:
            from hpc_patterns_tpu.harness import trace as tracelib

            rec = tracelib.get_tracer()
            if rec is not None and rec.enabled:
                out = rec.export(args.trace_out)
                print(f"trace timeline: {out} (open in Perfetto / "
                      "chrome://tracing)")


if __name__ == "__main__":
    sys.exit(main())
