"""Continuous batching vs static batching: serving throughput.

Usage: python benchmarks/bench_serving.py [--n=N] [--slots=S] [--chunk=K]
         [--mix=0|1] [--buckets=auto|none|16,32,...] [--overlap=0|1]
         [--temp=T] [--topk=K] [--smoke] [--scenario] [--plane]
         [--migration=dma|device_put|wire]
         [--elastic] [--offload] [--shared] [--quant] [--fit]
         [--autofit=config.json] [--fit-out=PATH]
         [--kv-dtype=f32|bf16|int8|fp8] [--quant-weights]

``--fit``: the AUTOFIT row (round 16) — observability becomes
control. A prefill-heavy long-tail stream is served once by the
default-ladder engine with its ``emit`` stream recorded to a RunLog
JSONL, ``harness/autofit.py`` fits a versioned FittedConfig from that
profile (the SAME fitter the CLI ``python -m
hpc_patterns_tpu.harness.autofit`` runs), and the A/B re-serves the
stream default vs ``ContinuousBatcher.from_fitted``. The fitted
ladder's expected padding must STRICTLY beat the default's
(deterministic, before any wall clock), every sequence on both legs
is byte-exact vs standalone decode, and the headline keys
``fitted_goodput_tok_s`` / ``autofit_gain_frac`` are captured by
``bench.py`` and gated by ``harness/regress.py``
(docs/observability.md "from diagnosis to control").
``--autofit=config.json`` replays an existing FittedConfig instead of
recording (reground step 4h fits from the chip trace); on the plain
rows it applies the fitted ladder in place of the 'auto' default.

``--elastic``: the ELASTIC-PLANE row (round 14) — one diurnal
open-loop ramp under seeded replica-death chaos through a FIXED
2-replica plane (a death there ends in shedding) and the autoscaled
``serving_plane/autoscaler.ElasticServingPlane`` (SLO-feedback
scale-up on warm residency-pulled params, checkpoint resume after the
death, drain-by-migration on the way down). The autoscaled plane's
per-class SLO attainment must STRICTLY exceed the static plane's on
the same replayed schedule, every served stream is byte-exact vs
standalone decode (greedy AND sampled — the sampled leg exercises
the per-row key-state checkpoint), and warm spin-up must beat a cold
``init_params`` + engine build. Headline keys
``elastic_slo_attainment`` / ``goodput_per_replica_round`` are
captured by ``bench.py`` and gated by ``harness/regress.py``
(docs/serving_plane.md "Elastic plane").

``--quant`` / ``--kv-dtype``: the QUANTIZED-DECODE row (round 13) —
the stream served from an int8/fp8 KV pool (one-byte pages + per-row
scales, ``decode_attn="paged_flash"``-ready), optionally with int8
per-channel weights (``--quant-weights``). TWO oracles before any
number: token-identical to standalone decode WITHIN the precision,
and the teacher-forced precision law (greedy top-1 agreement +
TV-distance bounds, models/quantization.py) ACROSS precisions.
Headline keys ``quant_goodput_tok_s`` / ``kv_pool_bytes_frac`` (pool
bytes vs a bf16 pool at equal residents — int8/fp8 land ~0.53) are
captured by ``bench.py`` and gated by ``harness/regress.py``.
``--kv-dtype`` also threads through ``--offload``/``--plane`` so the
gate sees the compound win (double effective HBM, half the migration
bytes); ``--shared`` refuses quantized pools loudly (prefix sharing
needs exact KV pages — docs/quantization.md).

``--shared``: the PREFIX-SHARING row (round 12) — one shared-prefix
open-loop stream (template pool + conversation-tree turns,
``harness/loadgen.make_shared_prefix_schedule``) through a
private-pages engine and the sharing-aware arena
(``prefix_cache=True``: radix match at admission, matched pages
mapped read-only + refcounted, tail-only prefill). Token-identical
to private pages (oracle before any number), ``prefill_skip_frac``
asserted > 0.3 on the template mix, and the headline keys
``shared_goodput_tok_s`` / ``prefill_skip_frac`` are captured into
``bench.py``'s detail and gated by ``harness/regress.py``
(docs/prefix_cache.md).

``--offload``: the TIERED-MEMORY row (round 11) — the same stream
through an all-HBM engine and an engine whose HBM pool is capped well
below the working set, fronting a host-resident pool via the
residency manager (``hpc_patterns_tpu/memory/``): cold rows page out
at chunk boundaries, swapped rows prefetch back with the pull
dispatched before the decode chunk. Token-identical to the all-HBM
engine (oracle before any number), the cap must force REAL eviction,
and the headline keys ``offload_goodput_tok_s`` /
``prefetch_overlap_frac`` are captured into ``bench.py``'s detail and
gated by ``harness/regress.py`` (docs/memory.md).

``--plane``: the SERVING-PLANE row (round 10) — one open-loop stream
through a single engine, a homogeneous 2-replica router plane, and
the disaggregated 1-prefill/1-decode plane with KV-page migration
overlapped behind the decode chunk (``hpc_patterns_tpu/
serving_plane/``). The bucket ladder is FIT from the stream's
observed prompt lengths (``serving.fit_bucket_ladder``) and must beat
the default ladder's expected padding; every leg is oracle-exact
(migrated rows included) before any number prints.
``--migration dma|device_put|wire`` picks the 1p/1d leg's KV-handoff
transport (round 17): ``dma`` routes bundles over the fused paired
remote-DMA kernel (``comm/migration_dma.py``, forces per-device
replica placement), ``wire`` round-trips the socket byte codec.
Headline keys ``plane_goodput_tok_s`` / ``kv_migration_overlap_frac``
/ ``dma_migration_overlap_frac`` / ``migration_bytes_per_round`` are
captured into ``bench.py``'s detail and gated by
``harness/regress.py``.

``--scenario``: the ROBUSTNESS row (round 8) — an OPEN-loop two-class
stream (harness/loadgen.py) served under page pressure that forces
preemption-and-resume, with a seeded stalled-host chaos injection
(harness/chaos.py) perturbing the engine loop, reporting **goodput**
(SLO-attained tok/s, harness/slo.py) NEXT TO raw tok/s plus the
preemption/shed counts — and the engine must STILL beat clean static
batching. The oracle extends to the degraded path: every served
sequence (including preempted-and-resumed ones) must be token-exact vs
standalone paged_generate before any number is reported.
``--smoke --scenario`` is the CI shape (tier-1,
tests/test_bench_serving.py); the full shape runs in
benchmarks/reground_r5.sh and its ``serving_goodput_tok_s`` /
``serving_degraded_bubble_frac`` keys are gated by
``harness/regress.py`` like every other headline. The timed leg also
runs under request-scoped lifecycle tracing (harness/reqtrace.py),
enforcing the coverage invariant in-run (untracked share < 5%) and
capturing ``attribution_coverage_frac`` / ``ttft_p99_queue_share``;
``--explain=1`` (or ``--explain-out=PATH``) renders the per-class
tail-attribution table (harness/explain.py) after the goodput row.

The capacity story measured on the REALISTIC stream: N requests with
VARIED prompt lengths (``--mix``, default on) and varied generation
budgets, served (a) statically — batches of ``slots`` rows in arrival
order, rows grouped by prompt length into rectangular sub-batches
(fragmentation), every row paying the longest budget in its batch
(padding) — vs (b) the ContinuousBatcher with the production levers
on: prompt-length BUCKETING (admission prefill compiles bounded by the
ladder size, not the stream's distinct lengths) and OVERLAPPED
admission (prefills enqueue behind the in-flight decode chunk).

Reported per engine run: tokens/s, the admission-bubble fraction
(host admission time exposed with no decode in flight), and the
prefill compile count with the ladder bound it must respect.

Oracle on every run (benchmark-IS-the-test): the engine's per-sequence
tokens must equal standalone paged_generate — same per-request key in
sampled mode — before any number is reported, and the compile count
must not exceed the bucket ladder size.

``--smoke``: the CI shape (seconds on the 8-device CPU mesh) —
tests/test_bench_serving.py runs it in tier-1 and asserts the engine
beats static on the mixed workload.

On-chip protocol note: the engine's host loop pays a tunnel round trip
per chunk; ``--chunk`` amortizes it (the dispatch-amortization
discipline of benchmarks/bench_decode.py). Static batching runs each
sub-batch's whole scan in one dispatch — the comparison is honest
serving reality for both.
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.harness import budget as budgetlib
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import explain as explainlib
from hpc_patterns_tpu.harness import loadgen
from hpc_patterns_tpu.harness import reqtrace as reqtracelib
from hpc_patterns_tpu.harness import slo
from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import (
    ContinuousBatcher,
    EngineCore,
    bucket_ladder,
    expected_padding,
    fit_bucket_ladder,
    pad_to_bucket,
    prefill_cache_size,
)
from hpc_patterns_tpu.models.transformer import init_params


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            v = a.split("=", 1)[1]
            if cast is bool:  # bool("0") is True; parse it properly
                return v.lower() not in ("0", "false", "no", "")
            return cast(v)
        if a == f"--{name}":
            if cast is not bool:
                raise SystemExit(
                    f"--{name} needs =VALUE (space-separated values "
                    "are not supported by this parser)")
            return True
    return default


def run_bench(*, n, slots, chunk, page_size, prompt_len, max_budget,
              cfg, params, mix=True, buckets="auto", overlap=True,
              temperature=0.0, top_k=0, seed=0, reps=1, quiet=False):
    """One engine-vs-static comparison; returns the metrics dict.
    ``buckets``: 'auto' (ladder over prompt_len), 'none', or a tuple.
    ``reps``: timed repetitions per mode, MIN taken — the shared-host
    CI box is noisy and min-of-reps is the standard load-spike shield.
    Raises AssertionError if the oracle or the compile bound fails."""
    out = print if not quiet else (lambda *a, **k: None)
    if isinstance(buckets, str):
        # 'auto' / 'none' / '8,16,32' — the same resolver the CLI
        # serving surfaces use (harness.cli)
        from hpc_patterns_tpu.harness.cli import parse_buckets

        buckets = parse_buckets(buckets, prompt_len)
    rng = np.random.RandomState(7)
    # the production-shaped stream: prompt lengths spread 1/2..1x, and
    # LONG-TAIL budgets (most requests short, a fifth at the max) —
    # static pays fragmentation (rectangular length groups) AND padding
    # (every row pays its batch's longest budget, usually the max);
    # the engine pays each row's own length and budget
    lengths = ([prompt_len // 2, (3 * prompt_len) // 4, prompt_len]
               if mix else [prompt_len])
    reqs = []
    for _ in range(n):
        t = int(rng.choice(lengths))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        budget = int(rng.choice(
            [max(1, max_budget // 8), max(1, max_budget // 4),
             max_budget],
            p=[0.5, 0.3, 0.2]))
        reqs.append((prompt, budget))
    total_tokens = sum(b for _, b in reqs)

    pages_per_seq = max(
        ContinuousBatcher.pages_needed(len(p), b, page_size,
                                       padded_len=pad_to_bucket(
                                           buckets, len(p)))
        for p, b in reqs)

    # --- static batching: batches of `slots` in arrival order; rows
    # group by prompt length into rectangular sub-batches, every row
    # pays the batch-max budget
    def run_static():
        outs = {}
        for i in range(0, n, slots):
            batch = reqs[i:i + slots]
            run_len = max(b for _, b in batch)
            bylen = {}
            for j, (p, b) in enumerate(batch):
                bylen.setdefault(len(p), []).append((i + j, p, b))
            for group in bylen.values():
                prompts = jnp.asarray(np.stack([p for _, p, _ in group]))
                toks = np.asarray(paged_generate(
                    params, prompts, cfg, run_len, page_size=page_size))
                for j, (idx, _, b) in enumerate(group):
                    outs[idx] = toks[j, :b]
        return outs

    def make_engine():
        return ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=slots * pages_per_seq,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, overlap=overlap,
            temperature=temperature, top_k=top_k, seed=seed,
        )

    def run_engine():
        eng = make_engine()
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[sid] for i, sid in enumerate(ids)}, eng

    # warmup (compiles) then timed runs
    compiles_before = prefill_cache_size()  # other engines, this process
    run_static()
    run_engine()
    compiles_warm = prefill_cache_size()
    t_static = t_engine = float("inf")
    bubble = None
    for _ in range(reps):
        t0 = time.perf_counter()
        static_out = run_static()
        t_static = min(t_static, time.perf_counter() - t0)
        t0 = time.perf_counter()
        engine_out, eng = run_engine()
        te = time.perf_counter() - t0
        if te < t_engine:
            # keep the bubble fraction of the rep whose time is
            # reported — mixing min-time with another rep's bubble
            # would pair numbers from different runs
            t_engine, bubble = te, eng.last_bubble_frac
    compiles = prefill_cache_size()

    # oracle before any number is believed: engine rows standalone-exact
    # (same per-request key when sampling), compile count inside the
    # ladder bound, and a WARM engine added no prefill compiles at all
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size,
            key=eng.request_key(i) if temperature > 0 else None,
            temperature=temperature, top_k=top_k))[0]
        np.testing.assert_array_equal(engine_out[i], want,
                                      err_msg=f"engine seq {i}")
        if temperature <= 0:
            np.testing.assert_array_equal(
                static_out[i], want[:len(static_out[i])],
                err_msg=f"static seq {i}")
    assert compiles == compiles_warm, (
        f"warm engine recompiled prefill: {compiles_warm} -> {compiles}")
    distinct = len({len(p) for p, _ in reqs})
    compiles = compiles - compiles_before  # this bench's engine only
    if buckets is not None:
        assert compiles <= len(buckets), (
            f"{compiles} prefill compiles > ladder size {len(buckets)}")

    out(f"serving[{'mixed' if mix else 'uniform'}]: n={n} slots={slots} "
        f"chunk={chunk} prompt<={prompt_len} ({distinct} lengths) "
        f"budgets<={max_budget} tokens={total_tokens} "
        f"buckets={buckets if buckets else 'off'} "
        f"overlap={'on' if overlap else 'off'}")
    out(f"  static  : {t_static:.3f}s  "
        f"{total_tokens / t_static:,.1f} tok/s")
    out(f"  engine  : {t_engine:.3f}s  "
        f"{total_tokens / t_engine:,.1f} tok/s  "
        f"bubble {bubble:.1%}  prefill compiles {compiles}"
        f"{f' (ladder {len(buckets)})' if buckets else ''}")
    out(f"  engine/static speedup: {t_static / t_engine:.3f}x "
        "(oracle-exact)")
    return {
        "t_static": t_static, "t_engine": t_engine,
        "tokens": total_tokens,
        "tokens_per_s_static": total_tokens / t_static,
        "tokens_per_s_engine": total_tokens / t_engine,
        "speedup": t_static / t_engine,
        "bubble_frac": bubble,
        "prefill_compiles": compiles,
        "ladder": len(buckets) if buckets else None,
        "distinct_lengths": distinct,
    }


def smoke_config():
    """The CI shape: a model big enough that DEVICE work (static's
    padding + fragmentation waste vs the engine's own-budget rows)
    dominates host dispatch on the 8-device CPU mesh, with the serving
    gather route so neither side pays pallas interpret cost — ONE
    definition shared by the CLI ``--smoke`` and the tier-1 pytest
    (tests/test_bench_serving.py) so they cannot drift. Engine wins
    ~2.5x here;
    the pytest asserts > 1 with that margin as the noise shield."""
    cfg = TransformerConfig(
        vocab=256, d_model=256, n_heads=4, n_layers=2, d_ff=1024,
        max_seq=256, dtype="float32", decode_attn="gather",
    )
    return dict(n=16, slots=4, chunk=16, page_size=16, prompt_len=32,
                max_budget=192, reps=2, cfg=cfg,
                params=init_params(jax.random.PRNGKey(0), cfg))


SCENARIO_CLASSES = (
    # interactive: the SLO-bearing class — tight-ish first-token and
    # per-token targets, sheds if it queues absurdly long
    loadgen.PriorityClass("interactive", 0, weight=0.4,
                          ttft_slo_s=3.0, tpot_slo_s=1.0,
                          deadline_s=30.0),
    # batch: throughput filler — no latency target, preemptible
    loadgen.PriorityClass("batch", 1, weight=0.6),
)


def scenario_smoke_config():
    """The CI chaos scenario (tier-1 via tests/test_bench_serving.py):
    a DETERMINISTIC staged schedule — two long batch requests take the
    pool at t=0, two interactive requests arrive mid-run and cannot
    get pages without EVICTING a batch row — plus two seeded
    engine-stall injections. Staged (not sampled) so the preemption
    trigger is structural, not a lucky draw; the seeded-random shapes
    are the full scenario's job (scenario_full_config)."""
    base = smoke_config()
    inter, batch = SCENARIO_CLASSES
    # two long batch rows take the pool at t=0 (free pages drop below
    # an interactive's need BY CONSTRUCTION, so the first interactive
    # arrival must preempt); a third batch row and the interactive
    # wave interleave in ARRIVAL order so that in static batching both
    # of the first two batches mix a 160-budget row with short rows —
    # every short row in them pays the 160-step run_len (padding) and
    # the length split doubles the scans (fragmentation). The engine
    # preempts one batch row, serves the wave at its own budgets, and
    # resumes the victim
    schedule = loadgen.staged_schedule([
        (0.00, batch, 32, 160),
        (0.00, batch, 32, 160),
        (0.05, inter, 16, 16),
        (0.10, batch, 32, 160),
        (0.15, inter, 16, 24),
        (0.20, inter, 16, 16),
        (0.25, inter, 16, 24),
        (0.30, inter, 16, 16),
    ], spec={"name": "smoke-chaos"})
    return dict(
        cfg=base["cfg"], params=base["params"], page_size=16,
        slots=3, chunk=8, schedule=schedule,
        classes=SCENARIO_CLASSES,
        # pool: room for the two batch rows (12 pages each) plus ONE
        # spare page — an arriving interactive row (2 pages) is starved
        # by construction and must preempt
        pool_pages=25, pages_per_seq=12,
        buckets=bucket_ladder(192),
        chaos_spec="stall:at=3,delay_ms=50;stall:at=9,delay_ms=50",
        # the high-water backoff stays off in the smoke: its pool is
        # sized to the page for the preemption trigger, and a reserve
        # would re-order the staged admissions (the full config runs
        # with the reserve on)
        admit_highwater=1.0,
    )


def scenario_full_config(on_tpu: bool):
    """The re-grounding shape: a seeded BURSTY open-loop stream (the
    admission-control stressor) over the same two classes, sized so
    bursts oversubscribe the pool and preemption/backoff do real work."""
    cfg = TransformerConfig(
        vocab=32768 if on_tpu else 256,
        d_model=1024 if on_tpu else 256,
        n_heads=8 if on_tpu else 4,
        n_layers=8 if on_tpu else 2,
        d_ff=4096 if on_tpu else 1024,
        max_seq=1024 if on_tpu else 256,
        dtype="bfloat16" if on_tpu else "float32",
        decode_attn="flash" if on_tpu else "gather",
    )
    prompt_top = 128 if on_tpu else 32
    budget_top = 256 if on_tpu else 128
    schedule = loadgen.make_schedule(
        32, rate_rps=16.0, classes=SCENARIO_CLASSES,
        prompt_lens=(prompt_top // 2, prompt_top),
        budgets=(budget_top // 8, budget_top // 2, budget_top),
        budget_probs=(0.5, 0.3, 0.2),
        process="bursty", seed=7, burst_factor=8.0,
        mean_quiet_s=0.5, mean_burst_s=0.2)
    page = 256 if on_tpu else 16
    pps = ContinuousBatcher.pages_needed(
        prompt_top, budget_top, page, padded_len=prompt_top)
    return dict(
        cfg=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        page_size=page, slots=8 if on_tpu else 4, chunk=16,
        schedule=schedule, classes=SCENARIO_CLASSES,
        # ~2.5 concurrent max-size rows' worth of pages for 4-8 slots:
        # bursts starve the arena and exercise eviction + backoff
        pool_pages=int(2.5 * pps), pages_per_seq=pps,
        buckets=bucket_ladder(prompt_top + budget_top),
        chaos_spec="stall:at=5,delay_ms=80,every=12",
        admit_highwater=0.95,
    )


def run_scenario(*, cfg, params, schedule, classes, page_size, slots,
                 chunk, pool_pages, pages_per_seq, buckets,
                 chaos_spec=None, admit_highwater=0.95, quiet=False,
                 explain=False, explain_out=None):
    """One robustness row: the open-loop schedule through (a) clean
    static batching (closed-loop, arrival order — the baseline that
    ignores arrival gaps, generous to static) and (b) the engine with
    priority admission, preemption-and-resume, SLO accounting, and the
    seeded chaos faults ACTIVE. The engine must beat static anyway,
    and every served sequence — preempted-and-resumed included — must
    be token-exact vs standalone paged_generate before any number is
    believed. Returns the metrics dict (goodput next to tok/s)."""
    out = print if not quiet else (lambda *a, **k: None)
    rng = np.random.RandomState(13)
    prompts = {r.index: rng.randint(0, cfg.vocab, size=r.prompt_len)
               .astype(np.int32) for r in schedule.requests}
    total_tokens = sum(r.max_new for r in schedule.requests)
    targets = slo.targets_from_classes(classes)

    def run_static():
        outs = {}
        reqs = [(prompts[r.index], r.max_new) for r in schedule.requests]
        for i in range(0, len(reqs), slots):
            batch = reqs[i:i + slots]
            run_len = max(b for _, b in batch)
            bylen = {}
            for j, (p, b) in enumerate(batch):
                bylen.setdefault(len(p), []).append((i + j, p, b))
            for group in bylen.values():
                arr = jnp.asarray(np.stack([p for _, p, _ in group]))
                toks = np.asarray(paged_generate(
                    params, arr, cfg, run_len, page_size=page_size))
                for j, (idx, _, b) in enumerate(group):
                    outs[idx] = toks[j, :b]
        return outs

    def run_engine():
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=pool_pages,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, preempt=True,
            admit_highwater=admit_highwater, slo=targets,
        )
        arrivals = [
            (r.t_arrival_s, dict(prompt=prompts[r.index],
                                 max_new=r.max_new, seq_id=r.index,
                                 priority=r.priority,
                                 deadline_s=r.deadline_s))
            for r in schedule.requests
        ]
        got = eng.run(arrivals=arrivals)
        return got, eng

    def prewarm_rungs():
        # resumed prompts land on ladder rungs the ORIGINAL stream
        # never visits (prompt + generated-so-far pads upward), and
        # WHICH rung depends on when the preemption fired — so the
        # warmup run cannot be trusted to have compiled them. Prefill
        # every rung once (budget-1 rows through a 1-slot engine
        # sharing this config's _prefill_one cache) so the timed leg
        # measures scheduling, not a mid-run XLA compile.
        # the SAME pool geometry as the scenario engine: _prefill_one
        # compiles key on the cache shapes too, so a differently-sized
        # pool would warm a parallel cache line and change nothing
        eng = ContinuousBatcher(
            params, cfg, slots=1, pool_pages=pool_pages,
            pages_per_seq=pages_per_seq, page_size=page_size, chunk=1,
            prompt_buckets=buckets)
        for rung in buckets:
            for plen in (rung, rung - 1):
                if plen < 1 or pad_to_bucket(buckets, plen) != rung:
                    continue
                if ContinuousBatcher.pages_needed(
                        plen, 1, page_size,
                        padded_len=rung) <= pages_per_seq:
                    eng.submit(np.zeros(plen, np.int32), 1)
                    eng.run()
                    break

    compiles_before = prefill_cache_size()
    # warmup (compiles; the chaos faults stay off so the warm cache is
    # the same one a clean run builds), then the timed legs — the
    # engine leg runs UNDER the seeded faults, static runs clean
    run_static()
    prewarm_rungs()
    run_engine()
    t0 = time.perf_counter()
    static_out = run_static()
    t_static = time.perf_counter() - t0
    chaoslib.configure(chaos_spec)  # also clears the injection log
    # request-scoped lifecycle tracing (harness/reqtrace.py) is ALWAYS
    # on for the timed leg: the attribution keys are gated per round,
    # so coverage regressions surface even without --explain. Fresh
    # recorder — the warmup leg reused the same seq_ids.
    reqtracelib.configure(enabled=True)
    try:
        t0 = time.perf_counter()
        engine_out, eng = run_engine()
        t_engine = time.perf_counter() - t0
        stalls = [e for e in chaoslib.injections()
                  if e["site"] == "engine_round"]
        req_snap = reqtracelib.active().snapshot(eng.stats)
    finally:
        chaoslib.reset()
        reqtracelib.reset()
    compiles = prefill_cache_size() - compiles_before

    # oracle before any number is believed — the DEGRADED path included:
    # a preempted-and-resumed row must be byte-identical to standalone
    rep = eng.last_slo
    for r in schedule.requests:
        if eng.stats[r.index]["outcome"] != "ok":
            continue  # shed: empty output by contract
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompts[r.index])[None], cfg, r.max_new,
            page_size=page_size))[0]
        np.testing.assert_array_equal(
            engine_out[r.index], want, err_msg=f"engine seq {r.index}")
        np.testing.assert_array_equal(
            static_out[r.index], want[:len(static_out[r.index])],
            err_msg=f"static seq {r.index}")
    assert compiles <= len(buckets), (
        f"{compiles} prefill compiles > ladder {len(buckets)} — "
        "resumed prompts left the bucket ladder")

    # tail attribution over the timed leg: the coverage invariant is
    # ENFORCED in-run — finished requests whose segment tilings leave
    # more than 5% of wall time untracked mean a stamp site went
    # missing, and the table below could no longer be believed
    dig = explainlib.digest([req_snap])
    assert dig["coverage_frac"] >= 0.95, (
        f"request-trace coverage {dig['coverage_frac']:.3f} < 0.95 — "
        "segment tilings leak untracked time (harness/reqtrace.py "
        "stamp site missing?)")
    # segment SLO budgets (harness/budget.py): did any ONE lifecycle
    # segment alone blow a class's target? The loud section rides
    # --explain; the count rides the result row either way
    breaches = budgetlib.evaluate(req_snap, targets)

    tot = rep["total"]
    served_tokens = tot["tokens"]
    result = {
        "t_static": t_static, "t_engine": t_engine,
        "tokens": total_tokens, "served_tokens": served_tokens,
        "tokens_per_s_static": total_tokens / t_static,
        "tokens_per_s_engine": served_tokens / t_engine,
        "speedup": (served_tokens / t_engine) / (total_tokens / t_static),
        "goodput_tok_s": tot["goodput_tok_s"] * eng._serve_s / t_engine
        if eng._serve_s else 0.0,
        "attained_frac": tot["attained_frac"],
        "preemptions": tot["preemptions"], "shed": tot["shed"],
        "bubble_frac": eng.last_bubble_frac,
        "stall_injections": len(stalls),
        "stall_injected_s": sum(e["delay_s"] for e in stalls),
        "prefill_compiles": compiles, "ladder": len(buckets),
        "attribution_coverage_frac": dig["coverage_frac"],
        "ttft_p99_queue_share": dig["ttft_p99_queue_share"],
        "tpot_p99_stall_share": dig["tpot_p99_stall_share"],
        "budget_breaches": len(breaches),
        "schedule": schedule.spec,
    }
    out(f"scenario[{schedule.spec.get('process', '?')}]: "
        f"n={schedule.n} slots={slots} chunk={chunk} "
        f"pool={pool_pages}p tokens={total_tokens} "
        f"chaos={chaos_spec or 'off'}")
    out(f"  static  : {t_static:.3f}s  "
        f"{result['tokens_per_s_static']:,.1f} tok/s (clean)")
    out(f"  engine  : {t_engine:.3f}s  "
        f"{result['tokens_per_s_engine']:,.1f} tok/s  "
        f"goodput {result['goodput_tok_s']:,.1f} tok/s  "
        f"bubble {result['bubble_frac']:.1%}  "
        f"preempted {result['preemptions']}  shed {result['shed']}  "
        f"stalls {result['stall_injections']} "
        f"(+{result['stall_injected_s'] * 1e3:.0f}ms)")
    out(f"  engine/static speedup under chaos: "
        f"{result['speedup']:.3f}x (oracle-exact incl. resumed rows)")
    out("  " + slo.format_slo(rep).replace("\n", "\n  "))
    if explain:
        out("  " + explainlib.format_explain(dig).replace("\n", "\n  "))
        out("  " + budgetlib.format_budget(breaches)
            .replace("\n", "\n  "))
    if explain_out:
        import json
        from pathlib import Path

        Path(explain_out).write_text(json.dumps(dig) + "\n")
        out(f"  explain digest -> {explain_out}")
    return result


def offload_smoke_config():
    """The CI tiered-memory shape (tier-1 via
    tests/test_bench_serving.py): the smoke model, an HBM pool capped
    well below the stream's working set (REAL eviction by
    construction, asserted), a deterministic cold-after-N rotation
    policy, and a host pool big enough for everything paged out."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=8, slots=4,
                chunk=16, page_size=16, prompt_len=32, max_budget=96,
                hbm_frac=0.5, cold_n=2)


def offload_full_config(on_tpu: bool):
    """The re-grounding shape: the scenario model with a long-context
    stream whose KV exceeds the HBM cap ~2.5x — the 131k-offload-row
    regime generalized from a one-shot training trick to a serving
    policy knob."""
    base = scenario_full_config(on_tpu)
    prompt_top = 256 if on_tpu else 32
    budget_top = 512 if on_tpu else 128
    return dict(cfg=base["cfg"], params=base["params"],
                n=24 if on_tpu else 8, slots=8 if on_tpu else 4,
                chunk=16, page_size=256 if on_tpu else 16,
                prompt_len=prompt_top, max_budget=budget_top,
                hbm_frac=0.4, cold_n=3)


def run_offload(*, cfg, params, n, slots, chunk, page_size, prompt_len,
                max_budget, hbm_frac, cold_n, quiet=False):
    """The tiered-memory row: the same stream through (a) an all-HBM
    engine (pool sized to the whole working set — the baseline and
    the ORACLE) and (b) a constrained engine whose HBM pool is capped
    at ``hbm_frac`` of that, fronting a host-resident pool through
    the residency manager (``hpc_patterns_tpu/memory/``) — cold rows
    page out at chunk boundaries, swapped rows prefetch back with the
    pull dispatched before the decode chunk. The constrained engine's
    outputs must be TOKEN-IDENTICAL to the all-HBM engine's (and to
    standalone ``paged_generate``) before any number is believed, and
    the cap must have forced real evictions. Reports
    ``offload_goodput_tok_s`` (SLO-attained tok/s of the constrained
    engine) and ``prefetch_overlap_frac`` (measured fraction of
    prefetch-window time hidden under the decode chunk), the two keys
    ``bench.py`` captures and ``harness/regress.py`` gates."""
    from hpc_patterns_tpu.memory import ColdAfterNPolicy, ResidencyManager

    out = print if not quiet else (lambda *a, **k: None)
    rng = np.random.RandomState(7)
    lengths = [prompt_len // 2, (3 * prompt_len) // 4, prompt_len]
    reqs = []
    for _ in range(n):
        t = int(rng.choice(lengths))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        budget = int(rng.choice(
            [max(1, max_budget // 2), max_budget], p=[0.4, 0.6]))
        reqs.append((prompt, budget))
    total_tokens = sum(b for _, b in reqs)
    buckets = bucket_ladder(prompt_len)
    targets = slo.targets_from_classes(SCENARIO_CLASSES)

    pages_per_seq = max(
        ContinuousBatcher.pages_needed(len(p), b, page_size,
                                       padded_len=pad_to_bucket(
                                           buckets, len(p)))
        for p, b in reqs)
    full_pool = slots * pages_per_seq
    hbm_pool = max(pages_per_seq, int(full_pool * hbm_frac))
    assert hbm_pool < full_pool, (
        f"hbm_frac {hbm_frac} does not constrain the pool "
        f"({hbm_pool} vs {full_pool}) — nothing would evict")

    def run_full():
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=full_pool,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, slo=targets)
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[s] for i, s in enumerate(ids)}, eng

    def run_tiered():
        mgr = ResidencyManager(host_blocks=2 * full_pool,
                               policy=ColdAfterNPolicy(cold_n))
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=hbm_pool,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, slo=targets,
            residency=mgr)
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[s] for i, s in enumerate(ids)}, eng, mgr

    # warmup (compiles), then the timed legs
    run_full()
    run_tiered()
    t0 = time.perf_counter()
    full_out, full_eng = run_full()
    t_full = time.perf_counter() - t0
    t0 = time.perf_counter()
    tier_out, tier_eng, mgr = run_tiered()
    t_tier = time.perf_counter() - t0

    # oracle before any number is believed: the constrained-HBM engine
    # is token-identical to the all-HBM one AND to standalone decode,
    # and the cap really forced the tier to do work
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size))[0]
        np.testing.assert_array_equal(full_out[i], want,
                                      err_msg=f"all-HBM seq {i}")
        np.testing.assert_array_equal(tier_out[i], want,
                                      err_msg=f"tiered seq {i}")
    assert mgr.swap_outs > 0 and mgr.swap_ins > 0, (
        f"HBM cap {hbm_pool}/{full_pool} pages forced no paging — "
        "the row measured nothing")

    tot_full = full_eng.last_slo["total"]
    tot_tier = tier_eng.last_slo["total"]
    overlap = mgr.prefetch_overlap_frac or 0.0
    result = {
        "t_full": t_full, "t_tiered": t_tier, "tokens": total_tokens,
        "tokens_per_s_full": total_tokens / t_full,
        "tokens_per_s_tiered": total_tokens / t_tier,
        "full_goodput_tok_s": tot_full["goodput_tok_s"]
        * full_eng._serve_s / t_full if t_full > 0 else 0.0,
        "offload_goodput_tok_s": tot_tier["goodput_tok_s"]
        * tier_eng._serve_s / t_tier if t_tier > 0 else 0.0,
        "prefetch_overlap_frac": overlap,
        "swap_outs": mgr.swap_outs, "swap_ins": mgr.swap_ins,
        "prefetch_bytes": mgr.prefetch_bytes,
        "hbm_pool": hbm_pool, "full_pool": full_pool,
        "bubble_frac": tier_eng.last_bubble_frac,
    }
    out(f"offload: n={n} slots={slots} chunk={chunk} "
        f"hbm={hbm_pool}p of {full_pool}p working set "
        f"(host pool {2 * full_pool}p) tokens={total_tokens}")
    out(f"  all-HBM : {t_full:.3f}s  "
        f"{result['tokens_per_s_full']:,.1f} tok/s  "
        f"goodput {result['full_goodput_tok_s']:,.1f}")
    out(f"  tiered  : {t_tier:.3f}s  "
        f"{result['tokens_per_s_tiered']:,.1f} tok/s  "
        f"goodput {result['offload_goodput_tok_s']:,.1f}  "
        f"swaps {mgr.swap_outs}/{mgr.swap_ins}  "
        f"prefetch overlap {overlap:.1%}")
    out(f"  capacity {t_full / t_tier:.3f}x wall cost for "
        f"{full_pool / hbm_pool:.1f}x pool oversubscription "
        "(token-identical, oracle-exact)")
    return result


def slo_budget_smoke_config():
    """The CI segment-budget shape (tier-1 via
    tests/test_bench_serving.py): a deliberately TINY model (the
    tests/test_reqtrace.py attribution geometry, seconds on CPU) on a
    5-request stream through a 2-resident tiered pool with a seeded
    ``slow_host_transfer`` — every pull eats a known synthetic delay,
    so the decode-phase stall is injected into ONE mechanism and the
    budget evaluator must blame exactly that mechanism. The knobs are
    sized so the ``prefetch_wait`` allowance sits well under one
    injected delay while every other segment's allowance sits well
    over anything the stream can spend."""
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq=128,
                            dtype="float32", decode_attn="gather")
    params = init_params(jax.random.PRNGKey(5), cfg)
    return dict(cfg=cfg, params=params, n=5, prompt_len=8,
                max_budget=24, page_size=8, chunk=4, slots=5,
                hbm_seqs=2, cold_n=2, delay_ms=60,
                ttft_slo_s=5.0, tpot_slo_s=0.08)


#: the seeded-stall budget: prefetch_wait may eat 2% of the decode
#: allowance (0.02 * 0.08s * 23 tokens ≈ 37ms — LESS than one 60ms
#: injected transfer delay), everything else is allowed most of the
#: target — so the injected chaos breaches its own bucket and no other
SLO_BUDGET_SEEDED = budgetlib.SLOBudget(
    ttft_shares={"queued": 0.9, "admit_wait": 0.9, "untracked": 0.5},
    tpot_shares={"prefetch_wait": 0.02, "swapped_out": 0.9,
                 "preempted": 0.9, "migrating": 0.9, "untracked": 0.5},
)


def _tiered_stall_leg(*, cfg, params, n, prompt_len, max_budget,
                      page_size, chunk, slots, hbm_seqs, cold_n,
                      delay_ms, prefetch_depth=None,
                      min_resident_rounds=1, emit=None):
    """One tiered stream under a seeded ``slow_host_transfer`` with
    request tracing on: an HBM pool sized for ``hbm_seqs`` of the
    ``n``-row working set forces the cold-after-N rotation to page,
    and every host->HBM pull eats the injected delay. Returns
    ``(outs, eng, mgr, snapshot, fired)`` — the shared chassis of the
    ``--slo-budget`` row and the ``--fit`` blame A/B."""
    from hpc_patterns_tpu.memory import ColdAfterNPolicy, ResidencyManager

    pps = ContinuousBatcher.pages_needed(prompt_len, max_budget,
                                         page_size)
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab, size=prompt_len)
               .astype(np.int32) for _ in range(n)]
    if delay_ms > 0:
        chaoslib.configure(f"slow_host_transfer:delay_ms={delay_ms}")
    reqtracelib.configure(enabled=True)
    try:
        mgr = ResidencyManager(host_blocks=n * pps,
                               policy=ColdAfterNPolicy(cold_n),
                               min_resident_rounds=min_resident_rounds,
                               prefetch_depth=prefetch_depth)
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=hbm_seqs * pps,
            pages_per_seq=pps, page_size=page_size, chunk=chunk,
            residency=mgr, emit=emit)
        ids = [eng.submit(p, max_budget) for p in prompts]
        got = eng.run()
        fired = [e for e in chaoslib.injections()
                 if e["site"] == "host_transfer"]
        snap = reqtracelib.active().snapshot(eng.stats)
    finally:
        chaoslib.reset()
        reqtracelib.reset()
    return {i: got[s] for i, s in enumerate(ids)}, eng, mgr, snap, fired


def run_slo_budget(*, cfg, params, n, prompt_len, max_budget,
                   page_size, chunk, slots, hbm_seqs, cold_n,
                   delay_ms, ttft_slo_s, tpot_slo_s, quiet=False,
                   explain=False):
    """The segment-budget row: seeded chaos must land in the budget
    bucket it was injected into. A ``slow_host_transfer`` run is
    evaluated against :data:`SLO_BUDGET_SEEDED` and the row ASSERTS
    the breach set is exactly ``{"prefetch_wait"}`` — the injected
    mechanism blamed, nothing else smeared — and that the inter-token
    digest attributes a nonzero stall share to the same decode phase.
    Outputs stay oracle-exact vs standalone decode (paging + chaos
    change WHEN tokens arrive, never WHICH). Reports
    ``tpot_p99_stall_share`` and ``budget_breach_segments``, the two
    keys ``bench.py`` captures and ``harness/regress.py`` gates."""
    out = print if not quiet else (lambda *a, **k: None)
    leg = dict(cfg=cfg, params=params, n=n, prompt_len=prompt_len,
               max_budget=max_budget, page_size=page_size, chunk=chunk,
               slots=slots, hbm_seqs=hbm_seqs, cold_n=cold_n)
    # warmup (compiles) with the delay off; the judged leg runs seeded
    _tiered_stall_leg(**leg, delay_ms=0)
    t0 = time.perf_counter()
    outs, eng, mgr, snap, fired = _tiered_stall_leg(
        **leg, delay_ms=delay_ms)
    wall = time.perf_counter() - t0

    # oracle before any number is believed
    rng = np.random.RandomState(11)
    for i in range(n):
        prompt = rng.randint(0, cfg.vocab, size=prompt_len) \
            .astype(np.int32)
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, max_budget,
            page_size=page_size))[0]
        np.testing.assert_array_equal(outs[i], want,
                                      err_msg=f"budget seq {i}")
    assert mgr.swap_outs > 0 and fired, (
        f"seeded stall row paged nothing (swap_outs={mgr.swap_outs}, "
        f"injections={len(fired)}) — the row measured nothing")

    targets = {0: slo.SLOTarget(ttft_s=ttft_slo_s, tpot_s=tpot_slo_s)}
    breaches = budgetlib.evaluate(snap, targets, SLO_BUDGET_SEEDED)
    segs = budgetlib.breached_segments(breaches)
    assert segs == {"prefetch_wait"}, (
        f"seeded slow_host_transfer breached {sorted(segs)} — chaos "
        "must land in the budget bucket it was injected into")
    dig = explainlib.digest([snap])
    assert dig["tpot_p99_stall_share"] > 0.0, (
        "inter-token digest attributes no stall time to a run whose "
        "every pull was seeded slow")

    result = {
        "wall_s": wall, "n": n,
        "tokens": n * max_budget,
        "swap_outs": mgr.swap_outs,
        "stall_injections": len(fired),
        "stall_injected_s": sum(e["delay_s"] for e in fired),
        "attribution_coverage_frac": dig["coverage_frac"],
        "tpot_p99_stall_share": dig["tpot_p99_stall_share"],
        "budget_breach_segments": sorted(segs),
        "budget_breaches": len(breaches),
    }
    out(f"slo-budget: n={n} hbm={hbm_seqs}/{n} seqs resident "
        f"chaos=slow_host_transfer:{delay_ms}ms "
        f"targets ttft={ttft_slo_s}s tpot={tpot_slo_s}s")
    out(f"  stream  : {wall:.3f}s  swaps {mgr.swap_outs}  "
        f"injected {result['stall_injected_s'] * 1e3:.0f}ms over "
        f"{len(fired)} pull(s) (oracle-exact)")
    out(f"  tpot p99-gap stall share "
        f"{dig['tpot_p99_stall_share']:.0%}  coverage "
        f"{dig['coverage_frac']:.1%}")
    out("  " + budgetlib.format_budget(breaches).replace("\n", "\n  "))
    if explain:
        out("  " + explainlib.format_explain(dig)
            .replace("\n", "\n  "))
    return result


def shared_smoke_config():
    """The CI prefix-sharing shape (tier-1 via
    tests/test_bench_serving.py): the smoke model on a template-pool +
    conversation-tree stream (2 templates × per-request tails, a
    quarter of arrivals extending an earlier prompt), small enough for
    seconds on the CPU mesh, shared enough that the matched span is
    well past the 0.3 skip-fraction floor the row asserts."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=16, slots=4,
                chunk=8, page_size=16, n_templates=2, template_len=32,
                tail_lens=(4, 8, 12), budgets=(16, 32),
                tree_frac=0.25, rate_rps=200.0, seed=12)


def shared_full_config(on_tpu: bool):
    """The re-grounding shape (reground_r5.sh step 4e): the scenario
    model on a heavier template mix — on chip the first real-HBM
    number for the dedup'd arena. The decode route is pinned to
    "gather": prefix sharing mirrors the einsum prefill path, and the
    engine refuses flash configs whose page-multiple rungs would send
    monolithic prefills through the Pallas kernel instead (the
    constructor guard) — decode_attn is a dispatch knob, so the
    scenario params are reused as-is."""
    base = scenario_full_config(on_tpu)
    cfg = dataclasses.replace(base["cfg"], decode_attn="gather")
    return dict(cfg=cfg, params=base["params"],
                n=48 if on_tpu else 24, slots=8 if on_tpu else 4,
                chunk=16, page_size=256 if on_tpu else 16,
                n_templates=3, template_len=512 if on_tpu else 32,
                tail_lens=(16, 32, 64) if on_tpu else (4, 8, 12),
                budgets=(64, 128) if on_tpu else (16, 32),
                tree_frac=0.25, rate_rps=64.0, seed=12)


def run_shared(*, cfg, params, n, slots, chunk, page_size, n_templates,
               template_len, tail_lens, budgets, tree_frac, rate_rps,
               seed=12, quiet=False):
    """The prefix-sharing row (round 12): ONE shared-prefix open-loop
    stream (harness/loadgen.make_shared_prefix_schedule — template
    pool + conversation-tree turns) served by (a) a PRIVATE-pages
    engine (every request prefills its full prompt) and (b) the
    SHARING-AWARE arena (``prefix_cache=True``: radix match at
    admission, matched pages mapped read-only, tail-only prefill).
    The ORACLE runs before any number: both engines token-identical
    to standalone ``paged_generate`` per request — sharing must be
    invisible in the tokens. Reports ``shared_goodput_tok_s``
    (SLO-attained tok/s of the sharing engine) and
    ``prefill_skip_frac`` (fraction of submitted prompt tokens whose
    prefill the radix match skipped — asserted > 0.3 on the template
    mix), the two keys ``bench.py`` captures and ``harness/regress.py``
    gates (docs/prefix_cache.md)."""
    schedule = loadgen.make_shared_prefix_schedule(
        n, rate_rps=rate_rps, classes=SCENARIO_CLASSES,
        n_templates=n_templates, template_len=template_len,
        tail_lens=tail_lens, budgets=budgets, tree_frac=tree_frac,
        seed=seed)
    out = print if not quiet else (lambda *a, **k: None)
    prompts = {r.index: loadgen.materialize_prompt(schedule, r.index,
                                                   cfg.vocab)
               for r in schedule.requests}
    targets = slo.targets_from_classes(SCENARIO_CLASSES)
    # an ALIGNED ladder (multiples of the page size, which the sharing
    # engine requires aligned to decode.PREFIX_ALIGN) fit to the
    # stream: sharing is rung-keyed, so rungs double as sharing scopes
    lengths = [p.size for p in prompts.values()]
    buckets = tuple(sorted({-(-int(L) // page_size) * page_size
                            for L in lengths}))
    pages_per_seq = max(
        ContinuousBatcher.pages_needed(
            len(prompts[r.index]), r.max_new, page_size,
            padded_len=pad_to_bucket(buckets, len(prompts[r.index])))
        for r in schedule.requests)
    pool_pages = slots * pages_per_seq
    total_tokens = sum(r.max_new for r in schedule.requests)
    arrivals = [
        (r.t_arrival_s, dict(prompt=prompts[r.index],
                             max_new=r.max_new, seq_id=r.index,
                             priority=r.priority,
                             deadline_s=r.deadline_s))
        for r in schedule.requests
    ]

    def run_one(share: bool):
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=pool_pages,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, slo=targets,
            prefix_cache=share)
        got = eng.run(arrivals=list(arrivals))
        return got, eng

    # warmup + best-of-reps: open-loop pacing means admission grouping
    # (and with it the (matched, rung) tail-prefill jit variants) can
    # differ run to run, so one warmup cannot guarantee the timed leg
    # compiles nothing — min-of-reps (the harness timing discipline)
    # keeps a stray in-leg XLA compile out of the GATED goodput number;
    # the (t, outputs, engine) triple stays from the same rep so the
    # SLO math is consistent with the wall time it divides by
    def best_of(share: bool, reps: int = 2):
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            got, eng = run_one(share)
            dt = time.perf_counter() - t0
            if best is None or dt < best[0]:
                best = (dt, got, eng)
        return best

    run_one(False)
    run_one(True)
    t_priv, priv_out, priv_eng = best_of(False)
    t_shr, shr_out, shr_eng = best_of(True)

    # oracle before any number is believed: sharing is invisible in
    # the tokens — both engines equal standalone paged decode
    for r in schedule.requests:
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompts[r.index])[None], cfg,
            r.max_new, page_size=page_size))[0]
        np.testing.assert_array_equal(priv_out[r.index], want,
                                      err_msg=f"private seq {r.index}")
        np.testing.assert_array_equal(shr_out[r.index], want,
                                      err_msg=f"shared seq {r.index}")
    skip = shr_eng.prefill_skip_frac
    assert skip > 0.3, (
        f"prefill_skip_frac {skip:.3f} <= 0.3 on the template mix — "
        "the radix match is not finding the shared prefixes")
    assert shr_eng._prefix.hits > 0, "no prefix-cache hit fired"

    tot_priv = priv_eng.last_slo["total"]
    tot_shr = shr_eng.last_slo["total"]
    result = {
        "t_private": t_priv, "t_shared": t_shr, "tokens": total_tokens,
        "tokens_per_s_private": total_tokens / t_priv,
        "tokens_per_s_shared": total_tokens / t_shr,
        "private_goodput_tok_s": tot_priv["goodput_tok_s"]
        * priv_eng._serve_s / t_priv if t_priv > 0 else 0.0,
        "shared_goodput_tok_s": tot_shr["goodput_tok_s"]
        * shr_eng._serve_s / t_shr if t_shr > 0 else 0.0,
        "prefill_skip_frac": skip,
        "prefix_hits": shr_eng._prefix.hits,
        "prefix_misses": shr_eng._prefix.misses,
        "ladder": buckets, "pool_pages": pool_pages,
        "bubble_frac": shr_eng.last_bubble_frac,
        "schedule": schedule.spec,
    }
    out(f"shared-prefix: n={n} slots={slots} chunk={chunk} "
        f"templates={n_templates}x{template_len} tree={tree_frac:.0%} "
        f"pool={pool_pages}p tokens={total_tokens}")
    out(f"  private : {t_priv:.3f}s  "
        f"{result['tokens_per_s_private']:,.1f} tok/s  "
        f"goodput {result['private_goodput_tok_s']:,.1f}")
    out(f"  shared  : {t_shr:.3f}s  "
        f"{result['tokens_per_s_shared']:,.1f} tok/s  "
        f"goodput {result['shared_goodput_tok_s']:,.1f}  "
        f"skip {skip:.1%}  hits {shr_eng._prefix.hits}/"
        f"{shr_eng._prefix.hits + shr_eng._prefix.misses}")
    out(f"  prefill skipped {skip:.1%} of prompt tokens "
        "(token-identical to private pages, oracle-exact)")
    return result


def quantized_smoke_config():
    """The CI quantized-decode shape (tier-1 via
    tests/test_bench_serving.py): the smoke model served with a
    quantized KV pool — small enough for seconds on the CPU mesh, big
    enough that the pool-bytes fraction is geometry-dominated (the
    scale pools' overhead shows honestly)."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=8, slots=4,
                chunk=16, page_size=16, prompt_len=32, max_budget=64,
                kv_dtype="int8")


def quantized_full_config(on_tpu: bool):
    """The re-grounding shape (reground_r5.sh step 4f): the scenario
    model with the attention-route RACE on — the quantized stream runs
    once on the gather route and once on ``paged_flash``
    (ops/paged_attention.py) at real VMEM limits. The interpret-mode
    ~10x penalty that forced serving onto the gather route off-TPU is
    exactly the number the chip race replaces."""
    base = scenario_full_config(on_tpu)
    prompt_top = 128 if on_tpu else 32
    budget_top = 256 if on_tpu else 96
    return dict(cfg=base["cfg"], params=base["params"],
                n=24 if on_tpu else 12, slots=8 if on_tpu else 4,
                chunk=16, page_size=256 if on_tpu else 16,
                prompt_len=prompt_top, max_budget=budget_top,
                kv_dtype="int8", race_attn=on_tpu)


def run_quantized(*, cfg, params, n, slots, chunk, page_size,
                  prompt_len, max_budget, kv_dtype="int8",
                  quant_weights=False, race_attn=False, quiet=False):
    """The quantized-decode row (round 13): one mixed stream served by
    (a) the compute-dtype baseline engine and (b) an engine whose KV
    pool stores ``kv_dtype`` (int8/fp8 one-byte pages + per-row f32
    scales; ``quant_weights`` additionally runs the int8
    per-output-channel weight path through every decode matmul,
    models/quantization.py).

    TWO oracles before any number is believed:

    - **exact within the precision**: the quantized engine's tokens
      equal standalone ``paged_generate`` under the SAME quantized
      config — quantization changes the math, never the scheduling;
    - **the precision law across precisions**
      (:func:`hpc_patterns_tpu.models.quantization.precision_law`):
      teacher-forced greedy top-1 agreement and TV-distance bounds vs
      the baseline precision — token identity cannot hold across
      precisions, so the law is the contract (docs/quantization.md).

    Reports ``quant_goodput_tok_s`` (SLO-attained tok/s of the
    quantized engine) and ``kv_pool_bytes_frac`` (quantized pool bytes
    / a bf16 pool at EQUAL geometry — the capacity headline; int8 and
    fp8 land ~0.53, i.e. the residency manager's host tier, the
    migration wire, and the prefix arena's resident count all roughly
    double), the two keys ``bench.py`` captures and
    ``harness/regress.py`` gates. ``race_attn``: also time the
    quantized stream on ``decode_attn="paged_flash"`` vs the gather
    route (the chip leg; pointless under interpret mode)."""
    from hpc_patterns_tpu.harness.cli import resolve_kv_cache_dtype
    from hpc_patterns_tpu.models.quantization import (
        precision_law,
        quantize_weights_int8,
    )

    out = print if not quiet else (lambda *a, **k: None)
    compute_dt, kv = resolve_kv_cache_dtype(kv_dtype, note=out)
    if kv == "compute":
        raise SystemExit(
            f"--quant needs a quantized --kv-dtype (int8/fp8), got "
            f"{kv_dtype!r} — the compute-dtype rows are the ordinary "
            "serving benches")
    over = {"kv_cache_dtype": kv}
    if compute_dt:
        over["dtype"] = compute_dt
    cfg_q = dataclasses.replace(cfg, **over)
    params_q = quantize_weights_int8(params) if quant_weights else params

    rng = np.random.RandomState(7)
    lengths = [prompt_len // 2, (3 * prompt_len) // 4, prompt_len]
    reqs = []
    for _ in range(n):
        t = int(rng.choice(lengths))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        budget = int(rng.choice(
            [max(1, max_budget // 2), max_budget], p=[0.4, 0.6]))
        reqs.append((prompt, budget))
    total_tokens = sum(b for _, b in reqs)
    buckets = bucket_ladder(prompt_len)
    targets = slo.targets_from_classes(SCENARIO_CLASSES)
    pages_per_seq = max(
        ContinuousBatcher.pages_needed(len(p), b, page_size,
                                       padded_len=pad_to_bucket(
                                           buckets, len(p)))
        for p, b in reqs)
    pool = slots * pages_per_seq

    # the precision LAW gate first — broken dequant must fail before
    # any throughput number exists (TV toward 1, agreement toward 1/V)
    law_prompts = np.stack([
        rng.randint(0, cfg.vocab, size=prompt_len).astype(np.int32)
        for _ in range(4)])
    law = precision_law(params, cfg, params_q, cfg_q, law_prompts,
                        steps=8)
    law.check()

    def run_one(c, p):
        eng = ContinuousBatcher(
            p, c, slots=slots, pool_pages=pool,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, slo=targets)
        ids = [eng.submit(pr, b) for pr, b in reqs]
        got = eng.run()
        return {i: got[s] for i, s in enumerate(ids)}, eng

    def timed(c, p):
        run_one(c, p)  # warmup (compiles)
        t0 = time.perf_counter()
        got, eng = run_one(c, p)
        return time.perf_counter() - t0, got, eng

    t_base, base_out, base_eng = timed(cfg, params)
    t_q, q_out, q_eng = timed(cfg_q, params_q)

    # exact oracle WITHIN the precision: the quantized engine must be
    # token-identical to standalone quantized decode — and the
    # baseline to baseline decode — before any number is believed
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size))[0]
        np.testing.assert_array_equal(base_out[i], want,
                                      err_msg=f"baseline seq {i}")
        want_q = np.asarray(paged_generate(
            params_q, jnp.asarray(prompt)[None], cfg_q, b,
            page_size=page_size))[0]
        np.testing.assert_array_equal(q_out[i], want_q,
                                      err_msg=f"quantized seq {i}")

    # pool bytes at EQUAL geometry: the quantized pool vs a bf16 pool
    # (the capacity headline — measured from real allocations, scale
    # pools included, table excluded on both sides)
    from hpc_patterns_tpu.models.decode import init_paged_cache

    def pool_bytes(c):
        cache = init_paged_cache(c, slots, pages_per_seq, page_size,
                                 pool_pages=pool + 1)
        return sum(int(arr.nbytes) for name, pools in cache.items()
                   if name != "table" for arr in pools)

    bf16_cfg = dataclasses.replace(cfg, dtype="bfloat16",
                                   kv_cache_dtype="compute")
    q_bytes = pool_bytes(cfg_q)
    bf16_bytes = pool_bytes(bf16_cfg)
    bytes_frac = q_bytes / bf16_bytes

    tot_base = base_eng.last_slo["total"]
    tot_q = q_eng.last_slo["total"]
    result = {
        "t_baseline": t_base, "t_quant": t_q, "tokens": total_tokens,
        "tokens_per_s_baseline": total_tokens / t_base,
        "tokens_per_s_quant": total_tokens / t_q,
        "baseline_goodput_tok_s": tot_base["goodput_tok_s"]
        * base_eng._serve_s / t_base if t_base > 0 else 0.0,
        "quant_goodput_tok_s": tot_q["goodput_tok_s"]
        * q_eng._serve_s / t_q if t_q > 0 else 0.0,
        "kv_pool_bytes_frac": bytes_frac,
        "kv_pool_bytes": q_bytes, "bf16_pool_bytes": bf16_bytes,
        "kv_dtype": kv, "quant_weights": bool(quant_weights),
        "greedy_agreement": law.greedy_agreement,
        "tv_mean": law.tv_mean, "tv_max": law.tv_max,
        "baseline_bubble_frac": base_eng.last_bubble_frac,
        "quant_bubble_frac": q_eng.last_bubble_frac,
    }
    out(f"quantized[{kv}{'+w8' if quant_weights else ''}]: n={n} "
        f"slots={slots} chunk={chunk} pool={pool}p "
        f"tokens={total_tokens}")
    out(f"  baseline : {t_base:.3f}s  "
        f"{result['tokens_per_s_baseline']:,.1f} tok/s  goodput "
        f"{result['baseline_goodput_tok_s']:,.1f}  bubble "
        f"{result['baseline_bubble_frac']:.1%}")
    out(f"  {kv:<9}: {t_q:.3f}s  "
        f"{result['tokens_per_s_quant']:,.1f} tok/s  goodput "
        f"{result['quant_goodput_tok_s']:,.1f}  bubble "
        f"{result['quant_bubble_frac']:.1%}")
    out(f"  kv pool bytes {q_bytes:,} = {bytes_frac:.3f}x the bf16 "
        f"pool ({bf16_bytes:,}) at equal residents")
    out(f"  precision law: greedy agreement "
        f"{law.greedy_agreement:.3f}, TV mean {law.tv_mean:.4f} / "
        f"max {law.tv_max:.4f} over {law.steps} teacher-forced steps "
        "(oracle-exact within the precision)")

    if race_attn:
        # the kernel race per precision: the SAME quantized stream on
        # the gather route vs the exact-softmax paged kernel — the
        # number reground step 4f exists for (interpret mode would
        # measure the ~10x per-grid-point host cost, not the kernel)
        cfg_pf = dataclasses.replace(cfg_q, decode_attn="paged_flash")
        cfg_ga = dataclasses.replace(cfg_q, decode_attn="gather")
        t_ga, ga_out, _ = timed(cfg_ga, params_q)
        t_pf, pf_out, _ = timed(cfg_pf, params_q)
        # the route-parity claim ON THIS BACKEND: the exact-softmax
        # kernel mirrors the gather math. Interpret mode holds that
        # BITWISE even for quantized pools (test-pinned), so any token
        # flip fails loudly; on chip a quantized pool's dequant
        # multiply order may legally differ by a ULP
        # (ops/paged_attention.py), so the tolerance tier allows
        # near-tie argmax flips but pins agreement — a broken kernel
        # sends agreement toward vocab-random, not 0.999
        n_tok = n_flip = 0
        for i in sorted(pf_out):
            a, b = np.asarray(pf_out[i]), np.asarray(ga_out[i])
            n_tok += a.size
            n_flip += int(np.sum(a != b))
        if jax.default_backend() == "tpu":
            agreement = 1.0 - n_flip / max(n_tok, 1)
            assert agreement >= 0.999, (
                f"route race token agreement {agreement:.4f} < 0.999 "
                f"({n_flip}/{n_tok} flips) — beyond ULP near-tie "
                "divergence, the paged_flash kernel is broken here")
        else:
            assert n_flip == 0, (
                f"paged_flash vs gather: {n_flip}/{n_tok} token "
                "mismatches in interpret mode (the bitwise contract)")
        result["tokens_per_s_gather"] = total_tokens / t_ga
        result["tokens_per_s_paged_flash"] = total_tokens / t_pf
        out(f"  route race [{kv}]: gather "
            f"{result['tokens_per_s_gather']:,.1f} tok/s vs "
            f"paged_flash {result['tokens_per_s_paged_flash']:,.1f} "
            f"tok/s ({t_ga / t_pf:.2f}x)")
    return result


ELASTIC_CLASSES = (
    # generous latency targets: attainment in the CI shape is decided
    # by SERVING (a shed never attains), not by wall-clock jitter —
    # the deterministic margin the elastic-vs-static comparison gates
    loadgen.PriorityClass("interactive", 0, weight=0.5,
                          ttft_slo_s=30.0, tpot_slo_s=5.0),
    loadgen.PriorityClass("batch", 1, weight=0.5),
)


def elastic_smoke_config():
    """The CI elastic shape (tier-1 via tests/test_bench_serving.py):
    the smoke model on a diurnal open-loop ramp whose front-loaded
    peak oversubscribes a 2-replica plane, with a seeded
    ``die:replica=1`` chaos fault killing one replica while it
    provably holds in-flight rows. DELIBERATELY on the chaos
    scenario's engine geometry (slots/pool/ladder/chunk of
    ``scenario_smoke_config`` — same pool shapes, same rungs, same
    prompt/budget points): the suite runs the scenario row first, so
    every greedy jit variant the elastic legs touch is already warm
    and the tier-1 cost is serving, not compiling. The sampled leg
    is smaller still (its sampling variants are the one fresh
    compile family)."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=10, slots=3,
                chunk=8, page_size=16, prompt_len=32, max_budget=24,
                rate_rps=200.0, period_s=0.4, depth=0.8, seed=17,
                die_replica=1, die_at=2, sampled_n=4,
                # the scenario geometry (see docstring): ladder top
                # 192, 12-page table, 25-page arena per replica
                ladder_top=192, pages_per_seq=12, pool_pages=25,
                budgets=(16, 24))


def elastic_full_config(on_tpu: bool):
    """The re-grounding shape (reground_r5.sh step 4g): the scenario
    model on a longer diurnal ramp — on chip the first real number
    for warm spin-up (host->HBM param paging at real DMA rates vs a
    real on-device init) and for the elastic plane's goodput-per-
    replica-round at chip throughput."""
    base = scenario_full_config(on_tpu)
    prompt_top = 128 if on_tpu else 32
    budget_top = 256 if on_tpu else 64
    return dict(cfg=base["cfg"], params=base["params"],
                n=32 if on_tpu else 20, slots=4 if on_tpu else 2,
                chunk=16, page_size=256 if on_tpu else 16,
                prompt_len=prompt_top, max_budget=budget_top,
                rate_rps=24.0, period_s=1.0, depth=0.8, seed=17,
                die_replica=1, die_at=3, sampled_n=6)


def run_elastic(*, cfg, params, n, slots, chunk, page_size, prompt_len,
                max_budget, rate_rps, period_s, depth, seed=17,
                die_replica=1, die_at=2, sampled_n=5,
                ladder_top=None, pages_per_seq=None, pool_pages=None,
                budgets=None, quiet=False):
    """The ELASTIC-PLANE row (round 14): one diurnal open-loop ramp
    under replica-death chaos, served by (a) a FIXED 2-replica plane
    (a death there ends in shedding — the ROADMAP's nobody-closes-
    the-loop baseline) and (b) the autoscaled
    :class:`~hpc_patterns_tpu.serving_plane.autoscaler.
    ElasticServingPlane` — SLO-feedback scale-up on warm
    residency-pulled params, checkpoint resume after the death, drain
    via migration on the way down.

    The robustness verdict, asserted before any number is believed:

    - the seeded ``die`` fault FIRED on both legs and the static
      plane's victim held in-flight rows (the fault did real damage);
    - the static plane demonstrably SHEDS (``shed_on_death >= 1``)
      while the elastic plane serves everything (nothing shed);
    - the elastic plane's per-class SLO attainment STRICTLY exceeds
      the static plane's on the same replayed schedule;
    - every served stream — death-resumed and drain-migrated rows
      included — is byte-exact vs standalone ``paged_generate``,
      GREEDY and (on the sampled leg, via the checkpointed key state)
      SAMPLED;
    - warm spin-up (the ``plane.spinup`` window's host span) is
      measurably faster than a cold ``init_params`` + engine build.

    Reports ``elastic_slo_attainment`` and
    ``goodput_per_replica_round`` (SLO-attained tokens per live
    replica-round — efficiency, not just peak), the two keys
    ``bench.py`` captures and ``harness/regress.py`` gates."""
    from hpc_patterns_tpu.serving_plane.autoscaler import (
        Autoscaler,
        AutoscalerPolicy,
        ElasticServingPlane,
        WarmParamPool,
    )
    from hpc_patterns_tpu.serving_plane.router import (
        Replica,
        ServingPlane,
    )

    out = print if not quiet else (lambda *a, **k: None)
    schedule = loadgen.make_schedule(
        n, rate_rps=rate_rps, classes=ELASTIC_CLASSES,
        prompt_lens=(prompt_len // 2, prompt_len),
        budgets=budgets or (max(1, max_budget // 2), max_budget),
        process="diurnal", seed=seed, period_s=period_s, depth=depth)
    rng = np.random.RandomState(seed + 1)
    prompts = {r.index: rng.randint(0, cfg.vocab, size=r.prompt_len)
               .astype(np.int32) for r in schedule.requests}
    targets = slo.targets_from_classes(ELASTIC_CLASSES)
    # the ladder covers prompt + budget: a death-resume's prompt is
    # the original plus everything already emitted, and a resume that
    # left the ladder could not re-admit anywhere (the run_scenario
    # sizing rule). ``ladder_top``/``pages_per_seq``/``pool_pages``
    # override to share another row's engine geometry (the smoke
    # rides the scenario row's warm jit caches)
    buckets = bucket_ladder(ladder_top or (prompt_len + max_budget))
    if pages_per_seq is None:
        pages_per_seq = max(
            EngineCore.pages_needed(r.prompt_len, r.max_new, page_size,
                                    padded_len=pad_to_bucket(
                                        buckets, r.prompt_len))
            for r in schedule.requests)
    pool = pool_pages or slots * pages_per_seq
    chaos_spec = (f"die:replica={die_replica},at={die_at},"
                  "site=replica_round")
    policy = AutoscalerPolicy(min_replicas=2, max_replicas=4,
                              up_queue=1.5, down_queue=0.25,
                              cooldown_rounds=3, window=4)

    def mk_engine(p, **skw):
        return EngineCore(
            p, cfg, slots=slots, pool_pages=pool,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, **skw)

    def arrivals(sched):
        return [(r.t_arrival_s,
                 dict(prompt=prompts[r.index], max_new=r.max_new,
                      priority=r.priority, deadline_s=r.deadline_s))
                for r in sched.requests]

    def run_static():
        plane = ServingPlane(
            [Replica(mk_engine(params), name=f"r{i}")
             for i in range(2)], slo=targets)
        chaoslib.configure(chaos_spec)
        try:
            got = plane.run(arrivals=arrivals(schedule))
            died = [e for e in chaoslib.injections()
                    if e["kind"] == "die"]
        finally:
            chaoslib.reset()
        assert died, "the seeded replica-death fault never fired"
        return got, plane

    def run_autoscaled(**skw):
        pool_w = WarmParamPool(params)
        plane = ElasticServingPlane(
            [Replica(mk_engine(params, **skw), name=f"r{i}")
             for i in range(2)],
            engine_factory=lambda p: mk_engine(p, **skw),
            warm_pool=pool_w,
            autoscaler=Autoscaler(policy), slo=targets)
        chaoslib.configure(chaos_spec)
        try:
            got = plane.run(arrivals=arrivals(schedule))
            died = [e for e in chaoslib.injections()
                    if e["kind"] == "die"]
        finally:
            chaoslib.reset()
        assert died, "the seeded replica-death fault never fired"
        return got, plane

    # no dedicated warmup leg: every GATED number here is wall-clock
    # free (attainment fractions; goodput per replica-ROUND — the
    # wall cancels out of attained_tokens / replica_rounds), so an
    # in-leg compile cannot move the gate. The tier-1 smoke
    # additionally rides the scenario row's warm caches by sharing
    # its engine geometry (elastic_smoke_config), and the one timed
    # claim (warm spin-up < cold init) compiles nothing on either
    # side (device_put vs eager init_params; pool allocation common).
    static_out, static = run_static()
    elastic_out, elastic = run_autoscaled()

    # the fault did real damage: the static victim held in-flight
    # rows, so the fixed plane SHEDS — the degraded mode this row
    # exists to beat — while the elastic plane serves everything
    assert static.deaths and elastic.deaths, "no replica died"
    assert static.shed_on_death >= 1, (
        "the static plane's dead replica held nothing — the death "
        "perturbed neither leg, the comparison measured nothing")
    assert elastic.shed_on_death == 0, (
        f"elastic plane shed {elastic.shed_on_death} on the death it "
        "exists to absorb")
    assert len(elastic.spinup_s) >= 1, "the autoscaler never scaled up"

    # oracle before any number is believed — death-resumed rows
    # included: every served stream byte-exact vs standalone (GREEDY;
    # the sampled leg below covers the key-checkpoint path)
    oracle: dict = {}

    def check(outs, plane):
        for r in schedule.requests:
            ps = plane.stats.get(r.index)
            if ps is None or ps.get("outcome") != "ok":
                continue
            want = oracle.get(r.index)
            if want is None:
                want = oracle[r.index] = np.asarray(paged_generate(
                    params, jnp.asarray(prompts[r.index])[None], cfg,
                    r.max_new, page_size=page_size))[0]
            np.testing.assert_array_equal(
                outs[r.index], want, err_msg=f"seq {r.index}")

    check(static_out, static)
    check(elastic_out, elastic)
    for r in schedule.requests:
        assert elastic.stats[r.index]["outcome"] == "ok", (
            f"elastic plane failed to serve seq {r.index}: "
            f"{elastic.stats[r.index]}")

    att_static = static.last_slo["total"]["attained_frac"]
    att_elastic = elastic.last_slo["total"]["attained_frac"]
    assert att_elastic > att_static, (
        f"autoscaled attainment {att_elastic:.3f} does not exceed "
        f"static {att_static:.3f} — the loop closed nothing")

    # warm spin-up vs cold init: the plane.spinup span (pull parked
    # host params + build the engine on them) against a cold
    # init_params + engine build — min-of-2 each side, the standard
    # load-spike shield
    def cold_once():
        t0 = time.perf_counter()
        p = init_params(jax.random.PRNGKey(0), cfg)
        eng = mk_engine(p)
        jax.block_until_ready((p, eng.temps))
        return time.perf_counter() - t0

    cold_init_s = min(cold_once() for _ in range(2))
    warm_spinup_s = min(elastic.spinup_s)
    assert warm_spinup_s < cold_init_s, (
        f"warm spin-up {warm_spinup_s * 1e3:.1f}ms not faster than "
        f"cold init {cold_init_s * 1e3:.1f}ms — the residency-backed "
        "pool bought nothing")

    # the SAMPLED leg: a smaller stream through sampled engines — the
    # death-resume must continue each stream from the CHECKPOINTED
    # key state, byte-exact vs standalone with the same request key.
    # Submitted UP FRONT (not open-loop): the leg exists to pin the
    # key checkpoint under death, so the victim must STRUCTURALLY
    # hold in-flight rows when the fault fires — the greedy legs own
    # the open-loop ramp realism
    sprompts = [rng.randint(0, cfg.vocab,
                            size=int(rng.choice([prompt_len // 2,
                                                 prompt_len])))
                .astype(np.int32) for _ in range(sampled_n)]
    sbudget = max(2 * chunk, max_budget // 4)
    skw = dict(temperature=0.7, top_k=8, seed=0)
    pool_s = WarmParamPool(params)
    es = ElasticServingPlane(
        [Replica(mk_engine(params, **skw), name=f"s{i}")
         for i in range(2)],
        engine_factory=lambda p: mk_engine(p, **skw),
        warm_pool=pool_s,
        autoscaler=Autoscaler(policy), slo=targets)
    chaoslib.configure("die:replica=0,at=1,site=replica_round")
    try:
        sids = [es.submit(p, sbudget) for p in sprompts]
        got_s = es.run()
    finally:
        chaoslib.reset()
    assert es.deaths, "sampled-leg death never fired"
    assert es.resumed, (
        "the sampled-leg victim held no in-flight rows — the key-"
        "checkpoint path went unexercised")
    key_src = es.replicas[1].engine
    for sid, p in zip(sids, sprompts):
        assert es.stats[sid]["outcome"] == "ok", (
            f"sampled seq {sid}: {es.stats[sid]}")
        want = np.asarray(paged_generate(
            params, jnp.asarray(p)[None], cfg, sbudget,
            page_size=page_size, key=key_src.request_key(sid),
            temperature=0.7, top_k=8))[0]
        np.testing.assert_array_equal(
            got_s[sid], want, err_msg=f"sampled seq {sid}")

    gppr = elastic.goodput_per_replica_round or 0.0
    per_class = {
        prio: {"static": static.last_slo["classes"]
               .get(prio, {}).get("attained_frac"),
               "elastic": elastic.last_slo["classes"]
               .get(prio, {}).get("attained_frac")}
        for prio in sorted({c.priority for c in ELASTIC_CLASSES})
    }
    result = {
        "elastic_slo_attainment": att_elastic,
        "static_slo_attainment": att_static,
        "per_class_attainment": per_class,
        "goodput_per_replica_round": gppr,
        "static_goodput_per_replica_round":
            static.goodput_per_replica_round or 0.0,
        "static_shed_on_death": static.shed_on_death,
        "elastic_shed_on_death": elastic.shed_on_death,
        "spinups": len(elastic.spinup_s),
        "warm_spinup_s": warm_spinup_s,
        "cold_init_s": cold_init_s,
        "resumed": sorted(elastic.resumed),
        "drained": list(elastic.drained),
        "replica_rounds": elastic.replica_rounds,
        "static_replica_rounds": static.replica_rounds,
        "sampled_resumed": sorted(es.resumed),
        "schedule": schedule.spec,
    }
    out(f"elastic: n={n} slots={slots} chunk={chunk} pool={pool}p "
        f"diurnal(period={period_s}s depth={depth}) chaos="
        f"{chaos_spec}")
    out(f"  static  : attained {att_static:.1%}  shed-on-death "
        f"{static.shed_on_death}  replica-rounds "
        f"{static.replica_rounds}")
    out(f"  elastic : attained {att_elastic:.1%}  shed-on-death 0  "
        f"spinups {len(elastic.spinup_s)}  resumed "
        f"{sorted(elastic.resumed)}  replica-rounds "
        f"{elastic.replica_rounds}")
    out(f"  warm spin-up {warm_spinup_s * 1e3:.1f}ms vs cold init "
        f"{cold_init_s * 1e3:.1f}ms "
        f"({cold_init_s / warm_spinup_s:.1f}x)")
    out(f"  goodput/replica-round {gppr:,.2f} tok (static "
        f"{result['static_goodput_per_replica_round']:,.2f})")
    out("  oracle-exact on every served stream, greedy AND sampled "
        "(death-resumed rows included)")
    return result


def plane_smoke_config():
    """The CI plane shape (tier-1 via tests/test_bench_serving.py): a
    seeded open-loop two-class stream through (a) one engine, (b) a
    2-replica homogeneous plane, (c) the disaggregated 1-prefill/
    1-decode plane — small enough for seconds on the CPU mesh, long
    enough that most migrations land behind an in-flight decode chunk
    (the overlap floor the tier-1 test pins)."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=12,
                slots=3, chunk=8, page_size=16, prompt_len=32,
                max_budget=64, rate_rps=200.0, seed=11)


def plane_full_config(on_tpu: bool):
    """The re-grounding shape: the scenario model at a heavier stream."""
    base = scenario_full_config(on_tpu)
    prompt_top = 128 if on_tpu else 32
    budget_top = 256 if on_tpu else 128
    return dict(cfg=base["cfg"], params=base["params"], n=32,
                slots=8 if on_tpu else 4, chunk=16,
                page_size=256 if on_tpu else 16,
                prompt_len=prompt_top, max_budget=budget_top,
                rate_rps=32.0, seed=11,
                # per-chip replica placement (own weight copy, real
                # cross-device KV migration) is a chip-leg claim; the
                # CPU's virtual devices share one host
                place_on_devices=on_tpu)


def devices_share_host(devs) -> bool:
    """True when the 'distinct' devices replicas were placed on are
    virtual shards of ONE host (the CPU mesh under
    ``--xla_force_host_platform_device_count``): placement still pins
    arrays and exercises the real transfer paths, but every copy
    crosses the same memory — so cross-device timings on such a mesh
    are mechanism proofs, not speed claims. The plane row prints this
    loudly instead of letting the CPU numbers impersonate a chip
    result (tests/test_bench_serving.py pins the detection)."""
    if len(devs) < 2:
        return False
    return (all(d.platform == "cpu" for d in devs)
            or len({d.process_index for d in devs}) == 1
            and all(d.platform == "cpu" for d in devs))


def run_plane(*, cfg, params, n, slots, chunk, page_size, prompt_len,
              max_budget, rate_rps, seed=11, place_on_devices=False,
              migration="device_put", quiet=False):
    """The serving-plane row: one open-loop stream through three legs
    — single engine (the baseline), a homogeneous 2-replica plane
    (router + least-loaded placement), and the disaggregated
    1-prefill/1-decode plane (KV migration overlapped behind the
    decode chunk). Every leg's served sequences are token-exact vs
    standalone ``paged_generate`` before any number is believed; the
    ladder is FIT from the stream's observed prompt lengths
    (serving.fit_bucket_ladder — the round-6 autotuning item) and must
    beat the default ladder's expected padding.

    ``migration`` selects the 1p/1d leg's KV-handoff transport
    (``--migration dma|device_put|wire``, router.MIGRATION_TRANSPORTS);
    ``dma`` forces per-device placement (the paired remote-DMA kernel
    needs distinct chips) even when ``place_on_devices`` is off.
    Reports ``plane_goodput_tok_s`` (2-replica leg),
    ``kv_migration_overlap_frac``, ``dma_migration_overlap_frac`` and
    ``migration_bytes_per_round`` (1p/1d leg) — the keys ``bench.py``
    captures and ``harness/regress.py`` gates."""
    from hpc_patterns_tpu.serving_plane.router import (
        MIGRATION_TRANSPORTS,
        Replica,
        ServingPlane,
    )

    if migration not in MIGRATION_TRANSPORTS:
        raise SystemExit(
            f"--migration {migration!r} not in "
            f"{'/'.join(MIGRATION_TRANSPORTS)}")

    out = print if not quiet else (lambda *a, **k: None)
    rng = np.random.RandomState(13)
    schedule = loadgen.make_schedule(
        n, rate_rps=rate_rps, classes=SCENARIO_CLASSES,
        prompt_lens=(prompt_len // 4, prompt_len // 2, prompt_len),
        budgets=(max(1, max_budget // 8), max(1, max_budget // 2),
                 max_budget),
        budget_probs=(0.5, 0.3, 0.2), process="poisson", seed=seed)
    prompts = {r.index: rng.randint(0, cfg.vocab, size=r.prompt_len)
               .astype(np.int32) for r in schedule.requests}
    targets = slo.targets_from_classes(SCENARIO_CLASSES)

    # bucket-ladder autotuning from the OBSERVED prompt-length
    # distribution (open since round 6): the fit ladder must beat the
    # shape-blind default on expected padding, and both router and
    # engines run it
    lengths = [r.prompt_len for r in schedule.requests]
    default_ladder = bucket_ladder(prompt_len)
    buckets = fit_bucket_ladder(lengths, len(default_ladder),
                                max_len=prompt_len)
    pad_fit = expected_padding(buckets, lengths)
    pad_default = expected_padding(default_ladder, lengths)
    assert pad_fit <= pad_default, (
        f"fit ladder {buckets} pads worse than default "
        f"{default_ladder}: {pad_fit:.2f} vs {pad_default:.2f}")

    pages_per_seq = max(
        EngineCore.pages_needed(r.prompt_len, r.max_new, page_size,
                                padded_len=pad_to_bucket(
                                    buckets, r.prompt_len))
        for r in schedule.requests)
    pool = slots * pages_per_seq

    def mk_engine(device=None):
        # with a device, the replica gets its OWN weight copy there
        # (the multi-chip serving shape: one replica per chip, KV
        # migration a real cross-device copy). Off by default on the
        # CPU smoke: the virtual devices share one host, so placement
        # only adds copies — the chip leg is where it means something.
        import contextlib

        p = (jax.device_put(params, device) if device is not None
             else params)
        ctx = (jax.default_device(device) if device is not None
               else contextlib.nullcontext())
        with ctx:
            return EngineCore(
                p, cfg, slots=slots, pool_pages=pool,
                pages_per_seq=pages_per_seq, page_size=page_size,
                chunk=chunk, prompt_buckets=buckets)

    def arrivals():
        return [(r.t_arrival_s,
                 dict(prompt=prompts[r.index], max_new=r.max_new,
                      priority=r.priority, deadline_s=r.deadline_s))
                for r in schedule.requests]

    def run_single():
        eng = ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=pool,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, slo=targets)
        got = eng.run(arrivals=arrivals())
        return got, eng

    # the DMA tier needs replicas on distinct devices — force
    # placement for it even on the CPU mesh (mechanism proof there;
    # devices_share_host() below keeps the wording honest)
    placed = place_on_devices or migration == "dma"

    def run_plane_leg(roles):
        devs = jax.devices() if placed else []
        replicas = []
        for i, role in enumerate(roles):
            dev = devs[i % len(devs)] if len(devs) > 1 else None
            replicas.append(Replica(mk_engine(dev), name=f"r{i}",
                                    role=role, device=dev))
        plane = ServingPlane(replicas, slo=targets,
                             migration=migration)
        got = plane.run(arrivals=arrivals())
        return got, plane

    oracle_cache: dict = {}

    def check(outs):
        # the standalone oracle depends only on (prompt, budget) —
        # identical across the three legs, so compute each once
        for r in schedule.requests:
            if len(outs.get(r.index, ())) == 0:
                continue  # shed: empty by contract
            want = oracle_cache.get(r.index)
            if want is None:
                want = oracle_cache[r.index] = np.asarray(
                    paged_generate(
                        params, jnp.asarray(prompts[r.index])[None],
                        cfg, r.max_new, page_size=page_size))[0]
            np.testing.assert_array_equal(
                outs[r.index], want, err_msg=f"plane seq {r.index}")

    # warmup (compiles shared across engines — one jit cache per
    # static config), then the timed legs
    run_single()
    t0 = time.perf_counter()
    single_out, single = run_single()
    t_single = time.perf_counter() - t0
    t0 = time.perf_counter()
    plane_out, plane2 = run_plane_leg(["both", "both"])
    t_plane = time.perf_counter() - t0
    t0 = time.perf_counter()
    disagg_out, disagg = run_plane_leg(["prefill", "decode"])
    t_disagg = time.perf_counter() - t0
    check(single_out)
    check(plane_out)
    check(disagg_out)
    assert disagg.migrations > 0, "disaggregated leg migrated nothing"

    tot1 = single.last_slo["total"]
    tot2 = plane2.last_slo["total"]
    totd = disagg.last_slo["total"]
    overlap = disagg.last_kv_migration_overlap_frac or 0.0
    dma_overlap = disagg.last_dma_migration_overlap_frac
    shared_host = placed and devices_share_host(jax.devices())
    result = {
        "t_single": t_single, "t_plane": t_plane, "t_disagg": t_disagg,
        "single_goodput_tok_s": tot1["goodput_tok_s"]
        * single._serve_s / t_single if t_single > 0 else 0.0,
        "plane_goodput_tok_s": tot2["goodput_tok_s"]
        * plane2._serve_s / t_plane if t_plane > 0 else 0.0,
        "disagg_goodput_tok_s": totd["goodput_tok_s"]
        * disagg._serve_s / t_disagg if t_disagg > 0 else 0.0,
        "kv_migration_overlap_frac": overlap,
        # DMA-tier-only overlap: None unless bundles actually rode the
        # paired kernel — a fallback cannot impersonate the DMA tier
        "dma_migration_overlap_frac": dma_overlap,
        "migration_bytes_per_round": disagg.migration_bytes_per_round,
        "migration_transport": migration,
        "migration_transports": dict(disagg.migration_transports),
        "placement_shares_host": shared_host,
        "migrations": disagg.migrations,
        "shed": tot2["shed"] + totd["shed"],
        "ladder_fit": list(buckets),
        "ladder_default": list(default_ladder),
        "expected_padding_fit": pad_fit,
        "expected_padding_default": pad_default,
        "schedule": schedule.spec,
    }
    out(f"plane: n={n} slots={slots}x chunk={chunk} "
        f"pool={pool}p ladder fit {buckets} "
        f"(E[pad] {pad_fit:.1f} vs default {pad_default:.1f})")
    out(f"  single    : {t_single:.3f}s  "
        f"{result['single_goodput_tok_s']:,.1f} goodput tok/s")
    out(f"  2-replica : {t_plane:.3f}s  "
        f"{result['plane_goodput_tok_s']:,.1f} goodput tok/s  "
        f"(routed {tot2['n']} reqs, shed {tot2['shed']})")
    out(f"  1p/1d     : {t_disagg:.3f}s  "
        f"{result['disagg_goodput_tok_s']:,.1f} goodput tok/s  "
        f"migrations {disagg.migrations}  "
        f"kv overlap {overlap:.1%}  transport {migration} "
        f"{dict(disagg.migration_transports)}  "
        + (f"dma overlap {dma_overlap:.1%}  "
           if dma_overlap is not None else "")
        + f"{result['migration_bytes_per_round']:,.0f} B/round")
    if shared_host:
        out("  NOTE: replicas placed on VIRTUAL devices sharing one "
            "host — cross-device copies are mechanism proofs, not "
            "bandwidth numbers (run the chip leg for those)")
    out("  oracle-exact on all three legs (migrated rows included)")
    return result


def fit_smoke_config():
    """The CI autofit shape (tier-1 via tests/test_bench_serving.py):
    the smoke model on a prefill-heavy long-tail stream whose bulk
    (60%) sits at a prompt length the default power-of-two ladder pads
    badly (40 -> 64, +60% prefill work on those rows) — the regime the
    fitted ladder exists for. Small decode budgets keep the row
    prefill-dominated so the padding win is visible in wall clock, and
    the shared smoke cfg/params ride the suite's warm decode caches."""
    base = smoke_config()
    return dict(cfg=base["cfg"], params=base["params"], n=16, slots=4,
                chunk=16, page_size=16, max_budget=32, reps=2,
                lengths=(16, 40, 64), length_probs=(0.2, 0.6, 0.2))


def fit_full_config(on_tpu: bool):
    """The re-grounding shape (reground_r5.sh step 4h): the scenario
    model on the same long-tail length mix scaled to chip prompts —
    fit once from the recorded stream, then the fitted ladder must
    beat the default on real HBM prefills."""
    base = scenario_full_config(on_tpu)
    top = 512 if on_tpu else 64
    return dict(cfg=base["cfg"], params=base["params"],
                n=32 if on_tpu else 16, slots=8 if on_tpu else 4,
                chunk=16, page_size=256 if on_tpu else 16,
                max_budget=256 if on_tpu else 32, reps=2,
                lengths=(top // 4, (5 * top) // 8, top),
                length_probs=(0.2, 0.6, 0.2))


def run_fitted(*, cfg, params, n, slots, chunk, page_size, max_budget,
               lengths, length_probs, reps=2, autofit_path=None,
               fit_out=None, quiet=False):
    """The AUTOFIT row (round 16): observability becomes control. One
    long-tail stream served three times:

    1. the RECORDING leg — the default-ladder engine, untimed, with
       its ``emit`` stream captured to a RunLog JSONL (the profile
       artifact a production run would already have);
    2. ``harness.autofit`` fits a FittedConfig from that JSONL through
       the REAL ingestion path (``fit_paths`` -> ``dumps_config`` ->
       ``load_fitted`` round trip, exactly what the CLI does);
    3. the A/B — the default-ladder engine vs
       ``ContinuousBatcher.from_fitted`` on the SAME stream and pool
       geometry, warmed then timed min-of-reps;
    4. the BLAME A/B — a seeded decode-stall stream (the
       ``--slo-budget`` chassis) recorded, blame-fitted, and
       re-served under the fitted residency; asserts the fitter
       blames the injected ``prefetch_wait`` mechanism and that the
       blamed segment's pooled p99-gap-band share STRICTLY shrinks
       under the fitted config (attribution closed into control).

    Deterministic win first: the fitted ladder's expected padding must
    be STRICTLY below the default's on the observed lengths (the DP
    fitter's contract — no wall clock involved). Oracle before any
    number: every sequence on BOTH legs byte-exact vs standalone
    ``paged_generate``. Reports ``fitted_goodput_tok_s`` and
    ``autofit_gain_frac`` (fitted/default - 1), the two keys
    ``bench.py`` captures and ``harness/regress.py`` gates.

    ``autofit_path``: skip the recording leg and apply an existing
    FittedConfig (reground step 4h fits from the chip trace);
    ``fit_out``: also copy the fitted config JSON here."""
    import tempfile

    from hpc_patterns_tpu.harness import autofit as autofitlib
    from hpc_patterns_tpu.harness.runlog import RunLog

    out = print if not quiet else (lambda *a, **k: None)
    rng = np.random.RandomState(7)
    reqs = []
    for _ in range(n):
        t = int(rng.choice(lengths, p=length_probs))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        budget = int(rng.choice(
            [max(1, max_budget // 8), max(1, max_budget // 4),
             max_budget],
            p=[0.5, 0.3, 0.2]))
        reqs.append((prompt, budget))
    total_tokens = sum(b for _, b in reqs)
    obs_lengths = [len(p) for p, _ in reqs]
    default_ladder = bucket_ladder(max(obs_lengths))

    def mk_engine(buckets, pages, *, emit=None):
        return ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=slots * pages,
            pages_per_seq=pages, page_size=page_size, chunk=chunk,
            prompt_buckets=buckets, emit=emit)

    def serve(eng):
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[s] for i, s in enumerate(ids)}

    with tempfile.TemporaryDirectory() as tmp:
        cfg_path = os.path.join(tmp, "fitted.json")
        if autofit_path is None:
            # recording leg: the profile run the fitter consumes —
            # default config, untimed, emit -> RunLog JSONL
            log_path = os.path.join(tmp, "profile.jsonl")
            pages_rec = max(
                ContinuousBatcher.pages_needed(
                    len(p), b, page_size,
                    padded_len=pad_to_bucket(default_ladder, len(p)))
                for p, b in reqs)
            serve(mk_engine(default_ladder, pages_rec,
                            emit=RunLog(log_path).emit))
            fitted = autofitlib.fit_paths([log_path])
            with open(cfg_path, "w") as f:
                f.write(autofitlib.dumps_config(fitted))
        else:
            cfg_path = autofit_path
        # the round trip every consumer uses (CLI parity)
        fitted = autofitlib.load_fitted(cfg_path)
        if fit_out:
            with open(fit_out, "w") as f:
                f.write(autofitlib.dumps_config(fitted))
        fitted_ladder = autofitlib.ladder_from(fitted,
                                               max_seq=cfg.max_seq)
    assert fitted_ladder is not None, (
        "the fitted config carries no ladder — the recording leg "
        "emitted no serve_admit records")

    # the deterministic win BEFORE any wall clock: the DP fit must
    # strictly beat the shape-blind default on the observed lengths
    pad_fit = expected_padding(fitted_ladder, obs_lengths)
    pad_default = expected_padding(default_ladder, obs_lengths)
    assert pad_fit < pad_default, (
        f"fitted ladder {fitted_ladder} does not beat default "
        f"{default_ladder}: E[pad] {pad_fit:.2f} vs {pad_default:.2f}")

    # the A/B shares ONE pool geometry (sized for whichever ladder
    # pads a length worse) so the comparison is ladder-only
    pages_per_seq = max(
        ContinuousBatcher.pages_needed(
            len(p), b, page_size,
            padded_len=max(pad_to_bucket(default_ladder, len(p)),
                           pad_to_bucket(fitted_ladder, len(p))))
        for p, b in reqs)

    def mk_fitted():
        eng = ContinuousBatcher.from_fitted(
            params, cfg, fitted, slots=slots,
            pool_pages=slots * pages_per_seq,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk)
        assert eng.prompt_buckets == fitted_ladder, (
            "from_fitted did not apply the fitted ladder")
        return eng

    # warmup (compiles), then min-of-reps timed legs; the timed runs
    # must add no prefill compiles
    serve(mk_engine(default_ladder, pages_per_seq))
    serve(mk_fitted())
    compiles_warm = prefill_cache_size()
    t_default = t_fitted = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        default_out = serve(mk_engine(default_ladder, pages_per_seq))
        t_default = min(t_default, time.perf_counter() - t0)
        t0 = time.perf_counter()
        fitted_out = serve(mk_fitted())
        t_fitted = min(t_fitted, time.perf_counter() - t0)
    assert prefill_cache_size() == compiles_warm, (
        "a timed leg recompiled prefill — the warmup missed a rung")

    # oracle before any number is believed: both legs byte-exact vs
    # standalone decode (the fitted ladder changes padding, never
    # tokens)
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size))[0]
        np.testing.assert_array_equal(default_out[i], want,
                                      err_msg=f"default seq {i}")
        np.testing.assert_array_equal(fitted_out[i], want,
                                      err_msg=f"fitted seq {i}")

    # the BLAME A/B (attribution becomes control): a decode-stall
    # stream — the --slo-budget chassis, seeded slow_host_transfer
    # under a thrashing 2-resident tier — is RECORDED (emit stream +
    # its reqtrace snapshot, the same two inputs a production RunLog
    # carries), fitted, and re-served under the blame-fitted
    # residency. Two asserts close the loop: the fitter must blame
    # the injected mechanism (tpot/prefetch_wait, not the queued-
    # dominated TTFT shape every saturated stream shows), and the
    # blamed segment's pooled p99-gap-band share must STRICTLY
    # shrink under the fitted config.
    bcfg = slo_budget_smoke_config()
    bleg = dict(cfg=bcfg["cfg"], params=bcfg["params"], n=bcfg["n"],
                prompt_len=bcfg["prompt_len"],
                max_budget=bcfg["max_budget"],
                page_size=bcfg["page_size"], chunk=bcfg["chunk"],
                slots=bcfg["slots"], hbm_seqs=bcfg["hbm_seqs"],
                cold_n=bcfg["cold_n"])
    _tiered_stall_leg(**bleg, delay_ms=0)  # warmup (compiles)
    blame_records: list = []
    outs_rec, _be, _bm, snap_rec, _bf = _tiered_stall_leg(
        **bleg, delay_ms=bcfg["delay_ms"],
        emit=lambda **kw: blame_records.append(kw))
    blame_records.append(dict(snap_rec, kind="reqtrace"))
    bfit = autofitlib.fit(blame_records)
    blame = bfit.get("blame")
    assert blame and blame["axis"] == "tpot" \
        and blame["dominant"] == "prefetch_wait", (
        f"blame fitter read the seeded decode stall as {blame} — the "
        "injected mechanism must be the one blamed")
    bres = bfit.get("residency") or {}
    outs_bfit, _be2, _bm2, snap_fit, _bf2 = _tiered_stall_leg(
        **bleg, delay_ms=bcfg["delay_ms"],
        prefetch_depth=bres.get("prefetch_depth"),
        min_resident_rounds=int(bres.get("min_resident_rounds") or 1))
    for i in sorted(outs_rec):
        np.testing.assert_array_equal(
            outs_bfit[i], outs_rec[i],
            err_msg=f"blame-fitted leg diverged on seq {i}")
    blame_share_default = float(blame["share"])
    blame_share_fitted = float(
        (explainlib.digest([snap_fit])["tpot_p99_band_shares"] or {})
        .get("prefetch_wait", 0.0))
    assert blame_share_fitted < blame_share_default, (
        f"blame-fitted config did not shrink the blamed segment: "
        f"prefetch_wait p99-band share {blame_share_default:.3f} -> "
        f"{blame_share_fitted:.3f}")

    gain = t_default / t_fitted - 1.0
    result = {
        "t_default": t_default, "t_fitted": t_fitted,
        "tokens": total_tokens,
        "default_goodput_tok_s": total_tokens / t_default,
        "fitted_goodput_tok_s": total_tokens / t_fitted,
        "autofit_gain_frac": gain,
        "ladder_default": list(default_ladder),
        "ladder_fitted": list(fitted_ladder),
        "expected_padding_default": pad_default,
        "expected_padding_fitted": pad_fit,
        "blame_segment": blame["dominant"],
        "blame_share_default": blame_share_default,
        "blame_share_fitted": blame_share_fitted,
        "config_sections": sorted(
            k for k in ("ladder", "residency", "placement",
                        "autoscaler", "blame") if fitted.get(k)),
    }
    out(f"autofit: n={n} slots={slots} chunk={chunk} "
        f"lengths={sorted(set(obs_lengths))} tokens={total_tokens} "
        f"({'replayed ' + autofit_path if autofit_path else 'fitted from recording leg'})")
    out(f"  default : {t_default:.3f}s  "
        f"{result['default_goodput_tok_s']:,.1f} tok/s  ladder "
        f"{list(default_ladder)}  E[pad] {pad_default:.1f}")
    out(f"  fitted  : {t_fitted:.3f}s  "
        f"{result['fitted_goodput_tok_s']:,.1f} tok/s  ladder "
        f"{list(fitted_ladder)}  E[pad] {pad_fit:.1f}")
    out(f"  autofit gain {gain:+.1%} wall clock, E[pad] "
        f"{pad_default:.1f} -> {pad_fit:.1f} tokens/req "
        "(oracle-exact, strict padding win asserted)")
    out(f"  blame   : {blame['axis']}.{blame['dominant']} "
        f"p99-band share {blame_share_default:.0%} -> "
        f"{blame_share_fitted:.0%} under "
        f"{blame['actions']} (strict shrink asserted)")
    return result


def _apply_kv_dtype(conf, kv_dtype):
    """Thread a ``--kv-dtype`` value into a serving-bench config dict
    (the compound rows: --offload/--plane run their whole scenario on
    the quantized pool, so the gate sees quantization MULTIPLY the
    other levers — double the effective HBM under residency, half the
    migration bytes on the plane). Resolution (fp8 degrade included)
    goes through the ONE shared resolver (harness.cli)."""
    if not kv_dtype:
        return conf
    from hpc_patterns_tpu.harness.cli import resolve_kv_cache_dtype

    compute_dt, kv = resolve_kv_cache_dtype(kv_dtype)
    over = {"kv_cache_dtype": kv}
    if compute_dt:
        over["dtype"] = compute_dt
    conf = dict(conf)
    conf["cfg"] = dataclasses.replace(conf["cfg"], **over)
    return conf


def main():
    kv_dtype = arg("kv-dtype", None, str)
    if kv_dtype:
        from hpc_patterns_tpu.harness.cli import KV_DTYPE_CHOICES

        kv_dtype = kv_dtype.strip().lower()
        if kv_dtype not in KV_DTYPE_CHOICES:
            # validate BEFORE any mode branches: --shared's quantized
            # refusal and --quant's resolver must only ever see legal
            # values, so a typo reads as a typo, not as a precision
            # policy message or a resolver traceback
            raise SystemExit(
                f"--kv-dtype must be one of {KV_DTYPE_CHOICES}, got "
                f"{kv_dtype!r}")
    if arg("quant", False, bool):
        if arg("smoke", False, bool):
            conf = quantized_smoke_config()
        else:
            conf = quantized_full_config(jax.default_backend() == "tpu")
        if kv_dtype:
            conf["kv_dtype"] = kv_dtype
        conf["quant_weights"] = arg("quant-weights", False, bool)
        run_quantized(**conf)
        return
    if arg("shared", False, bool):
        if kv_dtype and kv_dtype not in ("f32", "bf16"):
            # the documented refusal, surfaced HERE instead of deep in
            # the engine constructor: prefix sharing needs exact KV
            # pages (the monolithic prefill attends to unquantized
            # K/V, so shared dequantized pages break bitwise parity —
            # models/serving.py, docs/quantization.md)
            raise SystemExit(
                f"--shared refuses --kv-dtype {kv_dtype}: prefix "
                "sharing needs exact KV pages — the monolithic "
                "prefill attends to unquantized K/V and quantizes "
                "only for storage, so a tail computed from "
                "dequantized shared pages could not be bit-identical "
                "(docs/quantization.md); run --quant for the "
                "quantized row or --shared at f32/bf16")
        if arg("smoke", False, bool):
            run_shared(**_apply_kv_dtype(shared_smoke_config(),
                                         kv_dtype))
        else:
            run_shared(**_apply_kv_dtype(shared_full_config(
                jax.default_backend() == "tpu"), kv_dtype))
        return
    if arg("offload", False, bool):
        if arg("smoke", False, bool):
            run_offload(**_apply_kv_dtype(offload_smoke_config(),
                                          kv_dtype))
        else:
            run_offload(**_apply_kv_dtype(offload_full_config(
                jax.default_backend() == "tpu"), kv_dtype))
        return
    if arg("elastic", False, bool):
        if arg("smoke", False, bool):
            run_elastic(**elastic_smoke_config())
        else:
            run_elastic(**elastic_full_config(
                jax.default_backend() == "tpu"))
        return
    if arg("slo-budget", False, bool):
        # one shape on every backend: the row's value is the seeded
        # attribution assert (chaos lands in its own budget bucket),
        # not throughput — the injected delay dwarfs the model math
        # either way. NOT --budget: that flag is the plain row's
        # token budget.
        run_slo_budget(**slo_budget_smoke_config(),
                       explain=arg("explain", False, bool))
        return
    if arg("fit", False, bool):
        if arg("smoke", False, bool):
            conf = fit_smoke_config()
        else:
            conf = fit_full_config(jax.default_backend() == "tpu")
        run_fitted(**conf, autofit_path=arg("autofit", None, str),
                   fit_out=arg("fit-out", None, str))
        return
    if arg("plane", False, bool):
        mig = arg("migration", "device_put", str)
        if arg("smoke", False, bool):
            conf = _apply_kv_dtype(plane_smoke_config(), kv_dtype)
        else:
            conf = _apply_kv_dtype(plane_full_config(
                jax.default_backend() == "tpu"), kv_dtype)
        # --trace/--log ride the apps' shared instrumentation session
        # (reground step 7e: the DMA-migration row traced, so the
        # plane.kv_migration windows + algorithm="dma" fingerprints
        # land in a flight-recorder snapshot like the launched tier's)
        from types import SimpleNamespace

        from hpc_patterns_tpu.apps import common

        ns = SimpleNamespace(trace=arg("trace", False, bool),
                             metrics=False,
                             log=arg("log", None, str),
                             trace_capacity=None)
        common.run_instrumented(
            lambda _a: (run_plane(**conf, migration=mig), 0)[1], ns)
        return
    if arg("scenario", False, bool):
        # --explain/--explain-out mirror the shared CLI pair
        # (harness/cli.py add_explain_args) through this parser, the
        # same way --autofit and --kv-dtype are mirrored
        exp = dict(explain=(arg("explain", False, bool)
                            or bool(arg("explain-out", None, str))),
                   explain_out=arg("explain-out", None, str))
        if arg("smoke", False, bool):
            run_scenario(**scenario_smoke_config(), **exp)
        else:
            run_scenario(**scenario_full_config(
                jax.default_backend() == "tpu"), **exp)
        return
    def resolve_autofit_buckets(buckets, max_seq):
        # --autofit on the plain rows: the fitted ladder replaces the
        # default 'auto' ladder (an explicit --buckets value wins) —
        # the SAME precedence the CLI serving surfaces apply
        path = arg("autofit", None, str)
        if not path or buckets != "auto":
            return buckets
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fb = autofitlib.ladder_from(autofitlib.load_fitted(path),
                                    max_seq=max_seq)
        return fb if fb is not None else buckets

    if arg("smoke", False, bool):
        conf = smoke_config()
        run_bench(**conf,
                  overlap=bool(arg("overlap", 1)),
                  buckets=resolve_autofit_buckets(
                      arg("buckets", "auto", str),
                      conf["cfg"].max_seq))
        return
    on_tpu = jax.default_backend() == "tpu"
    n = arg("n", 32 if on_tpu else 16)
    slots = arg("slots", 8 if on_tpu else 4)
    chunk = arg("chunk", 16)
    page_size = arg("page", 256 if on_tpu else 16)
    prompt_len = arg("prompt", 512 if on_tpu else 32)
    max_budget = arg("budget", 512 if on_tpu else 192)
    cfg = TransformerConfig(
        vocab=arg("vocab", 32768 if on_tpu else 256),
        d_model=arg("d", 1024 if on_tpu else 256),
        n_heads=arg("heads", 8 if on_tpu else 4),
        n_layers=arg("layers", 8 if on_tpu else 2),
        d_ff=arg("ff", 4096 if on_tpu else 1024),
        max_seq=prompt_len + max_budget,
        dtype="bfloat16" if on_tpu else "float32",
        kv_cache_dtype=arg("cache", "compute", str),
        # off-TPU the serving surfaces take the pure-XLA gather route:
        # a pallas_call runs in interpret mode there, paying per-grid
        # host cost that swamps both sides of the comparison
        decode_attn="flash" if on_tpu else arg("attn", "gather", str),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    run_bench(n=n, slots=slots, chunk=chunk, page_size=page_size,
              prompt_len=prompt_len, max_budget=max_budget,
              cfg=cfg, params=params,
              mix=bool(arg("mix", 1)),
              buckets=resolve_autofit_buckets(
                  arg("buckets", "auto", str), cfg.max_seq),
              overlap=bool(arg("overlap", 1)),
              temperature=arg("temp", 0.0, float),
              top_k=arg("topk", 0))


if __name__ == "__main__":
    main()
