"""Prefetch loader tests + pipeline-parallel TRAINING (gradient) test."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu import parallel
from hpc_patterns_tpu.utils.data import PrefetchLoader, synthetic_tokens


class TestPrefetchLoader:
    def test_yields_all_batches_in_order(self):
        batches = [np.full((4,), i, np.float32) for i in range(10)]
        out = list(PrefetchLoader(batches, depth=3))
        assert len(out) == 10
        for i, b in enumerate(out):
            assert float(b[0]) == i
            assert isinstance(b, jax.Array)

    def test_worker_error_propagates(self):
        def bad():
            yield np.zeros(2)
            raise RuntimeError("corrupt shard")

        with pytest.raises(RuntimeError, match="corrupt shard"):
            list(PrefetchLoader(bad()))

    def test_custom_placer(self):
        dev = jax.devices()[0]
        loader = PrefetchLoader(
            [np.zeros((2,), np.float32)], place=lambda b: jax.device_put(b, dev)
        )
        (out,) = list(loader)
        assert out.devices() == {dev}

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            PrefetchLoader([], depth=0)

    def test_synthetic_tokens_shapes(self):
        batches = list(synthetic_tokens(
            jax.random.PRNGKey(0), batch=2, seq=8, vocab=100, steps=3
        ))
        assert len(batches) == 3
        assert all(b.shape == (2, 8) for b in batches)
        assert all(0 <= b.min() and b.max() < 100 for b in batches)


class TestPipelineTraining:
    def test_pipeline_gradients_match_sequential(self, mesh8):
        """PP must work for training, not just inference: gradients
        through the ring handoffs equal the sequential model's."""
        M, B, F = 4, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (M, B, F))
        ws = jax.random.normal(jax.random.PRNGKey(1), (8, F, F)) / 4

        def stage(w, h):
            return jnp.tanh(jnp.dot(h, w))

        def seq_loss(ws):
            h = x
            for r in range(8):
                h = stage(ws[r], h)
            return jnp.mean(jnp.square(h))

        def pp_loss(ws):
            def local(x_all, w):
                outs = parallel.pipeline_forward(stage, w[0], x_all, "x")
                me = jax.lax.axis_index("x")
                # loss lives on the last stage; psum broadcasts it
                mine = jnp.where(me == 7, jnp.mean(jnp.square(outs)), 0.0)
                return jax.lax.psum(mine, "x")[None]

            per_rank = jax.shard_map(
                local, mesh=mesh8,
                in_specs=(P(), P("x", None, None)),
                out_specs=P("x"),
            )(x, ws)
            return per_rank[0]

        want = jax.grad(seq_loss)(ws)
        got = jax.jit(jax.grad(pp_loss))(ws)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)
        # losses agree too
        assert float(pp_loss(ws)) == pytest.approx(float(seq_loss(ws)), rel=1e-5)
