"""Device discovery and topology — TPU-native analog of the reference's
``aurora.mpich.miniapps/src/include/devices.hpp`` (C8 in SURVEY.md).

The reference provides (devices.hpp:6-59):
- platform-prefix device lookup (``get_devices(target)``, devices.hpp:6-13)
- "device fission": partition each GPU into NUMA tiles via
  ``create_sub_devices<partition_by_affinity_domain>`` with whole-GPU
  fallback (devices.hpp:28-38)
- rank->device mapping: modulo round-robin when ranks > devices
  (devices.hpp:47), contiguous block split when devices >= ranks
  (devices.hpp:49-53)

TPU-native equivalents here:
- device lookup over ``jax.devices()`` filtered by platform
- "fission" = the chip -> core topology JAX already exposes (each TPU core
  is a device), plus grouping helpers by host/slice so meshes can be laid
  out so collectives ride ICI, not DCN
- the same two rank->device policies, reused for mesh construction
- :func:`make_mesh` — the central entry point: build a
  ``jax.sharding.Mesh`` with named axes (dp/sp/tp/...) over the devices,
  the TPU analog of MPI communicators.
"""

from __future__ import annotations

import dataclasses
import math
import os
from collections import defaultdict
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: bool | None = None):
    """``jax.shard_map`` across jax versions — ONE call shape for every
    site in the tree. Promoted ``jax.shard_map`` (and its ``check_vma``
    flag) when the build has it; the pre-promotion
    ``jax.experimental.shard_map`` location otherwise, where the flag
    was named ``check_rep`` (same meaning: replication/varying-axes
    checking, which a ``pallas_call`` body cannot declare)."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    # pre-promotion check_rep has NO pallas_call replication rule (the
    # promoted checker reads the kernels' declared vma instead), so the
    # old route runs unchecked unless a caller asks explicitly
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      check_rep=bool(check_vma) if check_vma else False)


class TopologyError(RuntimeError):
    """Raised when no usable device topology exists.

    Analog of the reference's fail-fast no-device error
    (allreduce-mpi-sycl.cpp:137-141).
    """


def get_devices(platform: str | None = None) -> list[jax.Device]:
    """All addressable devices, optionally filtered by platform prefix.

    Analog of ``get_devices(target)`` (devices.hpp:6-13), which filters
    SYCL platforms by name prefix; here the "platform" is the JAX backend
    name ("tpu", "cpu", "gpu").
    """
    devices = list(jax.devices())
    if platform is not None:
        devices = [d for d in devices if d.platform.startswith(platform)]
    if not devices:
        raise TopologyError(
            f"no devices for platform prefix {platform!r}; "
            f"available: {sorted({d.platform for d in jax.devices()})}"
        )
    return devices


@dataclasses.dataclass(frozen=True)
class CoreInfo:
    """Chip/core facts for one device — the TPU analog of the
    reference's sub-device (NUMA-tile) introspection (devices.hpp:29-38).

    ``num_cores`` > 1 with one device = a megacore chip (v4/v5p: two
    cores fused behind one device — XLA schedules across them; no
    finer software partition exists). Multiple devices sharing
    ``coords`` = per-core devices of one chip (v2/v3)."""

    device: jax.Device
    kind: str
    coords: tuple | None  # chip position in the slice, if exposed
    core_on_chip: int | None
    num_cores: int  # cores fused behind this device (1 = plain core)

    @property
    def megacore(self) -> bool:
        return self.num_cores > 1

    @classmethod
    def of(cls, d: jax.Device) -> "CoreInfo":
        coords = getattr(d, "coords", None)
        return cls(
            device=d,
            kind=getattr(d, "device_kind", d.platform),
            coords=tuple(coords) if coords is not None else None,
            core_on_chip=getattr(d, "core_on_chip", None),
            num_cores=int(getattr(d, "num_cores", 1) or 1),
        )


def core_topology(
    devices: Sequence[jax.Device] | None = None,
) -> list[CoreInfo]:
    """Per-device chip/core introspection (see :class:`CoreInfo`)."""
    if devices is None:
        devices = get_devices()
    return [CoreInfo.of(d) for d in devices]


def group_by_chip(
    devices: Sequence[jax.Device] | None = None,
) -> dict[tuple, list[jax.Device]]:
    """Group devices by physical chip: devices sharing (process, coords)
    are cores of one chip (v2/v3 style); one device per key means the
    chip IS the finest unit (v5e) or a fused megacore (v4/v5p)."""
    if devices is None:
        devices = get_devices()
    groups: dict[tuple, list[jax.Device]] = defaultdict(list)
    for d in devices:
        coords = getattr(d, "coords", None)
        key = (
            (d.process_index, tuple(coords))
            if coords is not None
            else (d.process_index, ("dev", d.id))
        )
        groups[key].append(d)
    return dict(groups)


def fission(devices: Sequence[jax.Device] | None = None) -> list[jax.Device]:
    """Expose the finest-grained compute units as devices.

    The reference's fission splits each GPU into NUMA tiles, falling back
    to whole GPUs when sub-devices are unsupported (devices.hpp:28-38).
    On TPU, JAX already enumerates the finest software-visible unit
    (v2/v3: one device per core, grouped by chip via
    :func:`group_by_chip`; v5e: one core per chip; v4/v5p: a megacore
    chip is ONE device — XLA schedules across the fused cores and no
    finer partition exists, which :func:`core_topology` reports as
    ``megacore=True``/``num_cores=2``). So this returns the devices
    as-is — the reference's whole-GPU fallback semantics — with the
    sub-device structure available from the introspection helpers.
    It never fails.
    """
    if devices is None:
        devices = get_devices()
    return list(devices)


def assign_device(rank: int, size: int, devices: Sequence[jax.Device]) -> jax.Device:
    """Map an SPMD rank to a device with the reference's two policies.

    - ranks > devices: modulo round-robin — ``rank % n`` (devices.hpp:47)
    - devices >= ranks: contiguous block split, rank r owns block
      ``[r * n//size, (r+1) * n//size)`` and uses its first device
      (devices.hpp:49-53)
    """
    if size <= 0 or rank < 0 or rank >= size:
        raise ValueError(f"bad rank/size: {rank}/{size}")
    n = len(devices)
    if n == 0:
        raise TopologyError("no devices to assign")
    if size > n:
        return devices[rank % n]
    block = n // size
    return devices[rank * block]


def devices_for_rank(rank: int, size: int, devices: Sequence[jax.Device]) -> list[jax.Device]:
    """The full device block owned by ``rank`` under the block policy."""
    if size <= 0 or rank < 0 or rank >= size:
        raise ValueError(f"bad rank/size: {rank}/{size}")
    n = len(devices)
    if size > n:
        return [devices[rank % n]]
    block = n // size
    return list(devices[rank * block : (rank + 1) * block])


def group_by_host(devices: Sequence[jax.Device] | None = None) -> dict[int, list[jax.Device]]:
    """Group devices by owning process/host (ICI domain approximation).

    Within one host/slice, collectives ride ICI; across hosts they may
    cross DCN. Mesh layouts should put fast axes (tp/sp) inside a group.
    """
    if devices is None:
        devices = get_devices()
    groups: dict[int, list[jax.Device]] = defaultdict(list)
    for d in devices:
        groups[d.process_index].append(d)
    return dict(groups)


# Slice-topology override for environments without real multi-slice
# hardware (HPCPAT_SLICE_GROUPING): "process" treats each OS process as
# one slice (apps/launch.py sets it so a -np N launch IS an N-slice
# system and the DCN-axis collectives cross real process boundaries);
# "process:a,b,..." maps process id -> slice id (several processes per
# slice); "devices:K" groups by device id in runs of K (single-process
# synthetic slices for tests). Every process computes the same grouping
# from the same env value — the SPMD invariant group_by_slice must keep.
ENV_SLICE_GROUPING = "HPCPAT_SLICE_GROUPING"


def _slice_id_fn():
    spec = os.environ.get(ENV_SLICE_GROUPING)
    if not spec:
        return lambda d: getattr(d, "slice_index", 0)
    kind, _, arg = spec.partition(":")
    if kind == "process":
        if not arg:
            return lambda d: d.process_index
        try:
            mapping = [int(s) for s in arg.split(",")]
        except ValueError as e:
            raise TopologyError(
                f"{ENV_SLICE_GROUPING}={spec!r}: 'process:map' wants "
                "comma-separated integers"
            ) from e

        def by_process(d):
            if d.process_index >= len(mapping):
                raise TopologyError(
                    f"{ENV_SLICE_GROUPING}={spec!r} maps "
                    f"{len(mapping)} processes; device {d} is from "
                    f"process {d.process_index}"
                )
            return mapping[d.process_index]

        return by_process
    if kind == "devices":
        try:
            k = int(arg)
        except ValueError:
            k = 0
        if k < 1:
            raise TopologyError(
                f"{ENV_SLICE_GROUPING}={spec!r}: 'devices:K' needs a "
                "positive integer K"
            )
        return lambda d: d.id // k
    raise TopologyError(
        f"{ENV_SLICE_GROUPING}={spec!r}: want 'process[:map]' or "
        "'devices:K'"
    )


def group_by_slice(devices: Sequence[jax.Device] | None = None) -> dict[int, list[jax.Device]]:
    """Group devices by TPU slice (multi-slice = DCN between groups).
    ``HPCPAT_SLICE_GROUPING`` overrides the hardware ``slice_index`` —
    see :data:`ENV_SLICE_GROUPING`."""
    if devices is None:
        devices = get_devices()
    slice_id = _slice_id_fn()
    groups: dict[int, list[jax.Device]] = defaultdict(list)
    for d in devices:
        groups[slice_id(d)].append(d)
    return dict(groups)


def is_multihost() -> bool:
    return jax.process_count() > 1


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Multi-host runtime init — the ``MPI_Init`` analog (SURVEY.md §2.3:
    ``jax.distributed.initialize`` replaces MPI_Init, mesh axes replace
    communicators).

    On TPU pods the arguments come from the environment automatically;
    explicit args cover CPU/GPU clusters (coordinator address ≙ the
    mpirun rendezvous). Idempotent: returns False when already
    initialized or single-process (the reference's guard style,
    allreduce-mpi-sycl.cpp:91-97), True when initialization happened.
    """
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except (RuntimeError, ValueError):
        if explicit:
            # the operator asked for a specific rendezvous: a failure is
            # a real failure (N silent single-host copies otherwise)
            raise
        # nothing discoverable from the environment — the common
        # dev-box case; callers proceed single-host
        return False


def cpu_worker_env(base: Mapping[str, str], n_devices: int) -> dict:
    """Environment for a child process that must run as a CPU SPMD worker
    with ``n_devices`` virtual devices instead of attaching real TPU
    hardware. The single source of truth for the CPU-forcing recipe,
    shared by apps/launch.py (the mpirun -np analog) and the
    self-bootstrapping multi-chip dry run (__graft_entry__).
    """
    env = dict(base)
    # drop the TPU-plugin trigger so the child cannot grab the real chip
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # cross-process CPU computations need a collectives backend: jaxlib
    # builds default to none and then reject multi-process executables
    # outright ("Multiprocess computations aren't implemented on the
    # CPU backend"), so a worker that exists to be one rank of many
    # must ask for gloo. setdefault: an operator's explicit choice
    # (e.g. "mpi") wins.
    env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    # override (not inherit) any existing device-count flag — e.g. the
    # test conftest's 8 — so n_devices is what it says
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def pump_lines(prefix: str, stream, sink) -> None:
    """Echo ``stream`` to ``sink`` line by line (with ``prefix``) until
    EOF, flushing each line — the output pump for child SPMD workers,
    shared by apps/launch.py and the self-bootstrapping dry run so
    progress is visible while a child compiles."""
    for line in iter(stream.readline, ""):
        sink.write(f"{prefix}{line}")
        sink.flush()
    stream.close()


# env rendezvous protocol set by apps/launch.py (the local mpirun -np
# analog); one process per "host", CPU devices standing in for chips
ENV_COORDINATOR = "HPCPAT_COORDINATOR"
ENV_NUM_PROCESSES = "HPCPAT_NUM_PROCESSES"
ENV_PROCESS_ID = "HPCPAT_PROCESS_ID"
# per-rank flight-recorder handoff: when the launcher sets this to a
# directory, every traced child (--trace) writes its closing recorder
# snapshot there as rank<id>.trace.json for the launcher to collect and
# merge (harness/collect.py) — the distributed-trace file protocol
ENV_TRACE_DIR = "HPCPAT_TRACE_DIR"


def process_env_info(environ=None) -> tuple[int, int, int]:
    """``(process_id, num_processes, slice_id)`` for THIS process, from
    the launcher env protocol when present (the same variables
    :func:`init_distributed_from_env` consumes, so the answer is right
    even before jax.distributed is initialized), falling back to the
    live jax runtime, then to the single-process identity. ``slice_id``
    applies a process-keyed :data:`ENV_SLICE_GROUPING` override to the
    process id (device-keyed specs don't determine a per-process slice).

    This is what stamps flight-recorder snapshots with their rank
    (harness/trace.py), so cross-rank merges know whose timeline each
    ring is without trusting file names.
    """
    env = os.environ if environ is None else environ
    pid_s = env.get(ENV_PROCESS_ID)
    if pid_s is not None:
        pid = int(pid_s)
        nprocs = int(env.get(ENV_NUM_PROCESSES, 1))
    else:
        try:
            pid, nprocs = jax.process_index(), jax.process_count()
        except Exception:  # noqa: BLE001 — backends may not be up yet
            pid, nprocs = 0, 1
    slice_id = 0
    spec = env.get(ENV_SLICE_GROUPING)
    if spec:
        kind, _, arg = spec.partition(":")
        if kind == "process":
            if not arg:
                slice_id = pid
            else:
                try:
                    mapping = [int(s) for s in arg.split(",")]
                    if pid < len(mapping):
                        slice_id = mapping[pid]
                except ValueError:
                    pass  # malformed spec: group_by_slice raises; a
                    # telemetry stamp just falls back to slice 0
    return pid, nprocs, slice_id


def init_distributed_from_env(environ=None) -> bool:
    """Join the rendezvous described by ``HPCPAT_COORDINATOR`` /
    ``HPCPAT_NUM_PROCESSES`` / ``HPCPAT_PROCESS_ID`` (exported by
    ``apps/launch.py``, the ``mpirun -np`` analog — the reference's apps
    learn their rank the same way, from the launcher via MPI_Init).

    No-op (False) when the variables are absent or the runtime is
    already initialized; True when this call joined the rendezvous.
    Called by app scaffolding (apps/common.py) so every miniapp is
    launchable both standalone and under the launcher, like the
    reference's binaries under ctest/mpirun.
    """
    import os

    env = os.environ if environ is None else environ
    coord = env.get(ENV_COORDINATOR)
    if not coord:
        return False
    # the launcher recipe (cpu_worker_env) requests a CPU collectives
    # backend via env, but jax flags don't read env vars — apply it
    # here, before the first device touch creates the CPU client (a
    # client built with collectives=none rejects every multi-process
    # computation outright)
    impl = env.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")
    if impl:
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except Exception:  # noqa: BLE001 — flag renamed/removed: let
            pass           # the runtime surface its own error later
    try:
        return init_distributed(
            coord,
            int(env[ENV_NUM_PROCESSES]),
            int(env[ENV_PROCESS_ID]),
        )
    except RuntimeError as e:
        # second app in one process: jax raises "distributed.initialize
        # should only be called once." (wording varies across versions)
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            return False
        raise


@dataclasses.dataclass(frozen=True)
class TopologyInfo:
    """A summary of the visible device topology (for logs and verdicts)."""

    platform: str
    n_devices: int
    n_hosts: int
    n_slices: int
    coords: tuple | None  # chip coords of device 0, if exposed

    @classmethod
    def detect(cls) -> "TopologyInfo":
        ds = get_devices()
        d0 = ds[0]
        return cls(
            platform=d0.platform,
            n_devices=len(ds),
            n_hosts=jax.process_count(),
            n_slices=len(group_by_slice(ds)),
            coords=getattr(d0, "coords", None),
        )


def _factor_axes(n_devices: int, axes: Mapping[str, int]) -> dict[str, int]:
    """Resolve -1 ("auto", the reference's CLI sentinel, sycl_con.cpp:179-232)
    axis sizes so the product equals ``n_devices``."""
    sizes = dict(axes)
    for k, v in sizes.items():
        if v != -1 and v < 1:
            raise TopologyError(f"axis {k!r} has invalid size {v} (use -1 for auto)")
    auto = [k for k, v in sizes.items() if v == -1]
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if n_devices % fixed != 0:
        raise TopologyError(
            f"mesh axes {dict(axes)} do not divide {n_devices} devices"
        )
    rest = n_devices // fixed
    if not auto:
        if fixed != n_devices:
            raise TopologyError(
                f"mesh axes {dict(axes)} use {fixed} devices but {n_devices} exist"
            )
        return sizes
    # Give the remainder to the first auto axis, 1 to the others.
    for k in auto[1:]:
        sizes[k] = 1
    sizes[auto[0]] = rest
    return sizes


def make_mesh(
    axes: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named-axis device mesh — the TPU analog of the reference's
    MPI communicator + rank->device map (devices.hpp:22-59).

    ``axes`` maps axis name -> size; a size of -1 means "auto" (fill with
    the remaining devices), mirroring the reference CLI's -1 sentinels.
    Axis order matters: later axes vary fastest over the device list, so
    put the most communication-heavy axes (tp, then sp) last to keep their
    collectives on adjacent devices (ICI, not DCN).
    """
    if devices is None:
        devices = get_devices()
    sizes = _factor_axes(len(devices), axes)
    names = tuple(sizes.keys())
    shape = tuple(sizes[k] for k in names)
    kw = _auto_axis_types(len(names))
    try:
        # Let JAX pick an ICI-friendly physical layout when it can.
        return jax.make_mesh(shape, names, devices=tuple(devices), **kw)
    except (ValueError, TypeError):
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, names, **kw)


def _auto_axis_types(n: int) -> dict:
    """Auto axis types for mesh construction: the framework uses
    with_sharding_constraint / shard_map-style GSPMD, not the Explicit
    sharding-in-types mode. On jax builds predating sharding-in-types
    (no ``jax.sharding.AxisType`` — e.g. 0.4.x) GSPMD-auto is the ONLY
    mode and the kwarg doesn't exist; omit it rather than fail every
    mesh build."""
    if hasattr(jax.sharding, "AxisType"):
        return {"axis_types": (jax.sharding.AxisType.Auto,) * n}
    return {}


def single_device_mesh(axes: Sequence[str] = ("dp",)) -> Mesh:
    """A trivial 1-device mesh so every code path also runs on one chip."""
    d = get_devices()[0]
    shape = (1,) * len(axes)
    return Mesh(np.asarray([d]).reshape(shape), tuple(axes))


def hybrid_device_layout(
    dcn_axes: Mapping[str, int],
    ici_axes: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> tuple[np.ndarray, tuple[str, ...]]:
    """Order devices for a multi-slice mesh: DCN axes vary across
    slices, ICI axes within one. Returns ``(device_array, axis_names)``
    with DCN axes leading (slowest-varying), so any sharding over an
    ICI axis touches devices of a single slice and its collectives
    ride ICI, while DCN axes (typically ``dp``) pay the slow link only
    for their own collectives — the SURVEY §2.3 ICI/DCN mapping.

    ``-1`` sizes auto-fill as in :func:`make_mesh`; the DCN product
    must equal the slice count, the ICI product the per-slice device
    count (slices must be equal-sized).
    """
    if devices is None:
        devices = get_devices()
    groups = group_by_slice(devices)
    slice_ids = sorted(groups)
    per_slice = {s: len(groups[s]) for s in slice_ids}
    if len(set(per_slice.values())) != 1:
        raise TopologyError(
            f"slices are unequal ({per_slice}); a hybrid mesh needs "
            "equal-sized slices"
        )
    n_slices = len(slice_ids)
    n_per = per_slice[slice_ids[0]]
    dcn_sizes = _factor_axes(n_slices, dcn_axes)
    ici_sizes = _factor_axes(n_per, ici_axes)
    overlap = set(dcn_sizes) & set(ici_sizes)
    if overlap:
        raise TopologyError(f"axes {sorted(overlap)} appear in both "
                            "dcn_axes and ici_axes")
    # slice-major order: row s = slice s's devices (each row is one ICI
    # domain), then fold rows into the DCN shape and columns into ICI
    arr = np.array(
        [groups[s] for s in slice_ids], dtype=object
    ).reshape(*dcn_sizes.values(), *ici_sizes.values())
    return arr, (*dcn_sizes.keys(), *ici_sizes.keys())


def make_hybrid_mesh(
    dcn_axes: Mapping[str, int],
    ici_axes: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-slice :class:`Mesh`: DCN axes across slices, ICI axes
    within (see :func:`hybrid_device_layout`). On a single slice this
    degenerates to ``make_mesh`` with the DCN axes sized 1."""
    arr, names = hybrid_device_layout(dcn_axes, ici_axes, devices)
    return Mesh(arr, names, **_auto_axis_types(len(names)))
