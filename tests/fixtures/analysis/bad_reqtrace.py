"""Known-bad: the round-18 request-trace bug shapes, minimized. A
lifecycle stamp (harness/reqtrace.py) is a ``perf_counter`` read plus
host list work by contract — it fires inside engine transitions the
batcher already owns (admission, preemption, migration export) with
decode chunks in flight. These variants smuggle a device readback into
the stamp to "enrich" the segment metadata, turning the observability
layer itself into the host stall it exists to attribute."""

import time

import numpy as np

import jax


def stamp_transition(histories, engine, seq_id, kind):
    """The enriched stamp: reading the engine's device-resident decode
    cursor back to annotate the segment synchronizes the queue on
    EVERY transition — queued/prefill/decode boundaries become the
    bubble the table then blames on the scheduler."""
    now = time.perf_counter()
    pos_now = int(np.asarray(engine.pos)[seq_id])  # EXPECT: host-sync-in-dispatch
    segs = histories.setdefault(seq_id, [])
    if segs and segs[-1][2] is None:
        segs[-1][2] = now
    segs.append([kind, now, None, {"pos": pos_now}])
    return segs


def export_history(histories, engine, seq_id):
    """Export with a 'consistency check': block_until_ready on the KV
    slab before handing the segment tuple to the bundle serializes the
    donor's in-flight chunk behind the migration bookkeeping."""
    jax.block_until_ready(engine.kv_pages)  # EXPECT: host-sync-in-dispatch
    segs = histories.get(seq_id) or []
    if segs and segs[-1][2] is None:
        segs[-1][2] = time.perf_counter()
    return tuple(tuple(s) for s in segs)


def finish_request(histories, engine, seq_id, t):
    """Finish stamp that materializes the generated-token count from
    a device counter: float()-of-a-call reads the value back on the
    one boundary every finished request crosses."""
    tokens = float(jax.device_get(engine.generated)[seq_id])  # EXPECT: host-sync-in-dispatch
    segs = histories.get(seq_id) or []
    if segs and segs[-1][2] is None:
        segs[-1][2] = t
    return tokens, segs
