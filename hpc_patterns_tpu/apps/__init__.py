"""Benchmark applications — the TPU rebuilds of the reference's CLI
binaries (SURVEY.md §2.1, layer L5).

Each app is a self-validating benchmark in the reference's sense (§4):
it measures its own claim, validates against an analytic oracle where one
exists, prints grep-able SUCCESS/FAILURE lines, and exits 0/1.

| reference binary / config             | app module               |
|---------------------------------------|--------------------------|
| allreduce-mpi-sycl / -omp-offload     | ``allreduce_app``        |
| (BASELINE.json pt2pt ping-pong)       | ``pingpong_app``         |
| sycl_con / omp_con / omp_con_meta     | ``concurrency_app``      |
| concurency/run.sh                     | ``sweep``                |
| interop_omp_ze_sycl                   | ``interop_app``          |
| (BASELINE.json halo-exchange stencil) | ``stencil_app``          |
| (flagship model, beyond parity)       | ``train_app``            |

Run any app as ``python -m hpc_patterns_tpu.apps.<name> --help``.
"""
