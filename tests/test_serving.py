"""Continuous batching (models/serving.py): every sequence admitted
through the shared-pool engine must emit exactly the tokens its
standalone paged_generate emits — regardless of what was scheduled
around it, what chunk size amortized the dispatch, how often its pages
were recycled, what bucket rung padded its prompt, or (in sampled
mode) what its neighbors drew from their own key streams. Draft-
assisted SAMPLING is the one law-only surface: the rejection-sampling
rounds preserve the emitted distribution, not the draws — its oracle
is distributional."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import (
    ContinuousBatcher,
    bucket_ladder,
    prefill_cache_size,
)

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")


def _setup(**over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _standalone(params, cfg, prompt, max_new, **kw):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8, **kw))[0]


def _requests(cfg, n, seed=1):
    """n requests with varied prompt lengths and budgets."""
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        t = int(rng.choice([5, 8, 11]))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        reqs.append((prompt, int(rng.choice([3, 6, 9]))))
    return reqs


class TestContinuousBatching:
    @pytest.mark.parametrize("chunk", [1, 4])
    def test_every_sequence_matches_standalone(self, chunk):
        # 6 requests through 2 slots and a pool with room for ~2 rows:
        # admission waits on freed pages, rows complete at their own
        # budgets, and each output must equal standalone paged decode
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8,
                                chunk=chunk)
        reqs = _requests(cfg, 6)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        assert sorted(got) == sorted(ids)
        for sid, (prompt, max_new) in zip(ids, reqs):
            want = _standalone(params, cfg, prompt, max_new)
            np.testing.assert_array_equal(got[sid], want,
                                          err_msg=f"seq {sid}")
        # the arena drained back to empty
        assert sorted(eng.free_pages) == list(range(6))

    def test_single_slot_serializes_exactly(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8, chunk=2)
        reqs = _requests(cfg, 4, seed=3)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_int8_pages_compose(self):
        cfg, params = _setup(kv_cache_dtype="int8")
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=4)
        reqs = _requests(cfg, 4, seed=5)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_eos_truncates_like_standalone_prefix(self):
        # pick the eos id from a standalone run's interior so it WILL
        # fire mid-generation; the engine must emit exactly the prefix
        # through that first occurrence
        cfg, params = _setup()
        prompt = np.arange(5, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 9)
        eos = int(full[3])
        first = int(np.argmax(full == eos))
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                eos_id=eos)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(got, full[:first + 1])

    def test_engine_reuse_across_runs(self):
        # a drained engine accepts a second wave: pages/slots/cursors
        # reset cleanly and the second run's outputs are exact too.
        # (True mid-run admission — new requests entering while rows
        # are generating — is covered by the 6-requests/2-slots test,
        # where 4 requests queue behind active rows.)
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=4)
        r1 = _requests(cfg, 2, seed=7)
        ids1 = [eng.submit(p, m) for p, m in r1]
        eng.run()
        r2 = _requests(cfg, 2, seed=9)
        ids2 = [eng.submit(p, m) for p, m in r2]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids1 + ids2, r1 + r2):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    @pytest.mark.parametrize("gamma", [2, 4])
    def test_draft_assisted_matches_standalone(self, gamma):
        # speculative decoding INSIDE the engine: the draft proposes,
        # the target verifies per round, rows advance 1..gamma+1 tokens
        # per dispatch at their own acceptance — and every sequence is
        # STILL token-exact vs its standalone paged decode (greedy
        # speculative == greedy target, the serving oracle)
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=gamma)
        reqs = _requests(cfg, 5, seed=11)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new),
                err_msg=f"seq {sid} gamma={gamma}")
        assert sorted(eng.free_pages) == list(range(8))

    def test_draft_assisted_self_draft_accepts_everything(self):
        # target drafting for itself: every proposal accepted, rows
        # advance gamma+1 per round, output still exact
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=params, draft_cfg=cfg,
                                gamma=3)
        prompt = np.arange(5, dtype=np.int32)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(
            got, _standalone(params, cfg, prompt, 9))

    def test_draft_assisted_eos(self):
        cfg, params = _setup()
        prompt = np.arange(5, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 9)
        eos = int(full[3])
        first = int(np.argmax(full == eos))
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                                pages_per_seq=4, page_size=8,
                                draft_params=params, draft_cfg=cfg,
                                gamma=2, eos_id=eos)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(got, full[:first + 1])

    def test_draft_assisted_int8_matches_standalone(self):
        # all three serving levers at once: draft-assisted rounds over
        # int8 page pools — still token-exact vs standalone int8 paged
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup(kv_cache_dtype="int8")
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2,
                                    "kv_cache_dtype": "int8"})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=2)
        reqs = _requests(cfg, 4, seed=13)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_draft_assisted_tp_matches_standalone(self, mesh_dp_sp_tp):
        # draft-assisted rounds under tp: the engine's pools shard on
        # kv heads, draft kernel steps shard_map, the extend rides
        # GSPMD — still token-exact vs unsharded standalone
        from hpc_patterns_tpu.models.sharding import shard_params
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup(n_heads=4)  # kv_heads 4, tp=2 divides
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        d_sh = shard_params(dparams, mesh_dp_sp_tp, dcfg)
        eng = ContinuousBatcher(p_sh, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=d_sh, draft_cfg=dcfg,
                                gamma=2, mesh=mesh_dp_sp_tp)
        reqs = _requests(cfg, 3, seed=17)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_draft_guards(self):
        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        from hpc_patterns_tpu.models.transformer import init_params as ip

        dparams = ip(jax.random.PRNGKey(42), dcfg)
        with pytest.raises(ValueError, match="draft_cfg"):
            ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                              pages_per_seq=3, page_size=8,
                              draft_params=dparams)

    def test_telemetry_events(self):
        # the observability hook records every admission and
        # completion with page accounting (the metrics/logging
        # subsystem applied to serving)
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                emit=lambda **kw: events.append(kw))
        reqs = _requests(cfg, 3, seed=21)
        ids = [eng.submit(p, m) for p, m in reqs]
        eng.run()
        admits = [e for e in events if e["kind"] == "serve_admit"]
        finishes = [e for e in events if e["kind"] == "serve_finish"]
        assert sorted(e["seq_id"] for e in admits) == sorted(ids)
        assert sorted(e["seq_id"] for e in finishes) == sorted(ids)
        for e, (prompt, max_new) in zip(sorted(admits,
                                               key=lambda e: e["seq_id"]),
                                        reqs):
            assert e["prompt_len"] == len(prompt)
            assert e["budget"] == max_new
        for e in finishes:
            assert e["tokens"] >= 1 and e["pages_freed"] >= 1

    def test_guards(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=2,
                                pages_per_seq=3, page_size=8)
        with pytest.raises(ValueError, match="pages_per_seq"):
            eng.submit(np.arange(20, dtype=np.int32), 20)
        with pytest.raises(ValueError, match="max_new"):
            eng.submit(np.arange(4, dtype=np.int32), 0)
        # needs 3 pages but the pool only has 2: deadlock, loudly
        eng.submit(np.arange(10, dtype=np.int32), 8)
        with pytest.raises(RuntimeError, match="deadlock"):
            eng.run()


class TestBucketedAdmission:
    def test_ladder(self):
        assert bucket_ladder(12, lo=4) == (4, 8, 12)
        assert bucket_ladder(100, lo=16) == (16, 32, 64, 100)
        assert bucket_ladder(8) == (8,)  # lo above max: one rung
        with pytest.raises(ValueError, match="max_len"):
            bucket_ladder(0)
        with pytest.raises(ValueError, match="growth"):
            bucket_ladder(64, growth=1.0)

    def test_compile_count_bounded_and_exact(self):
        # TEN distinct prompt lengths through a THREE-rung ladder: the
        # admission-prefill jit cache (prefill_cache_size — one entry
        # per distinct padded length x config) may grow by at most the
        # ladder size, and every bucket-padded sequence must still be
        # token-exact vs standalone (causality keeps the true prefix
        # independent of the padding; last_pos redirects the logits).
        # d_ff=68 makes the config unique in this process, so the
        # cache delta belongs to THIS engine alone.
        cfg, params = _setup(d_ff=68)
        ladder = bucket_ladder(12, lo=4)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8, chunk=4,
                                prompt_buckets=ladder)
        rng = np.random.RandomState(2)
        reqs = [(rng.randint(0, cfg.vocab, size=t).astype(np.int32), 5)
                for t in range(1, 11)]  # every length 1..10
        before = prefill_cache_size()
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        assert prefill_cache_size() - before <= len(ladder)
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new),
                err_msg=f"seq {sid} len {len(prompt)}")
        # a SECOND wave re-uses the warm rungs: zero new compiles
        before = prefill_cache_size()
        ids2 = [eng.submit(p, m, seq_id=100 + i)
                for i, (p, m) in enumerate(reqs)]
        got = eng.run()
        assert prefill_cache_size() == before
        for sid, (prompt, max_new) in zip(ids2, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_bucketed_draft_assisted_exact(self):
        # bucket padding composes with speculative rounds: the draft
        # prefill pads to the same rung, and greedy draft-assisted
        # serving stays token-exact
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=10,
                                pages_per_seq=5, page_size=8,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=2, prompt_buckets=(4, 8, 12))
        reqs = _requests(cfg, 4, seed=19)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_ladder_guards(self):
        cfg, params = _setup()
        with pytest.raises(ValueError, match="max_seq"):
            ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                              pages_per_seq=4, page_size=8,
                              prompt_buckets=(8, 100))
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                                pages_per_seq=4, page_size=8,
                                prompt_buckets=(8,))
        with pytest.raises(ValueError, match="ladder"):
            eng.submit(np.arange(9, dtype=np.int32), 4)  # above top rung

    def test_pages_cover_padded_prefill(self):
        # a 1-token prompt padded to rung 8 with budget 1 needs a page
        # for the PAD region too — pages_needed must size for the
        # padded length, or the prefill would scatter past the row's
        # pages
        assert ContinuousBatcher.pages_needed(1, 1, 8, padded_len=8) == 1
        assert ContinuousBatcher.pages_needed(1, 1, 8, padded_len=16) == 2
        assert ContinuousBatcher.pages_needed(9, 8, 8, padded_len=16) == 3


class TestSampledServing:
    def test_sampled_token_exact_vs_standalone(self):
        # sampling in the engine is NOT a weaker distributional claim:
        # each row consumes its own key stream exactly as standalone
        # paged_generate(key=request_key(sid)) does, so served tokens
        # are identical draw-for-draw — scheduling independence holds
        # for sampled serving too
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=3,
                                temperature=0.8, top_k=8, seed=3)
        reqs = _requests(cfg, 6, seed=23)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            want = _standalone(params, cfg, prompt, max_new,
                               key=eng.request_key(sid),
                               temperature=0.8, top_k=8)
            np.testing.assert_array_equal(got[sid], want,
                                          err_msg=f"seq {sid}")

    def test_sampled_with_buckets_exact(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8, chunk=4,
                                temperature=1.1, top_k=0, seed=5,
                                prompt_buckets=(4, 8, 12))
        reqs = _requests(cfg, 5, seed=29)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            want = _standalone(params, cfg, prompt, max_new,
                               key=eng.request_key(sid),
                               temperature=1.1)
            np.testing.assert_array_equal(got[sid], want)

    def test_per_request_overrides(self):
        # a per-request temperature/key overrides the engine defaults,
        # and the standalone reproduction uses exactly those
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8,
                                temperature=0.7, top_k=0, seed=9)
        prompt = np.arange(6, dtype=np.int32)
        my_key = jax.random.PRNGKey(777)
        sid_default = eng.submit(prompt, 7)
        sid_custom = eng.submit(prompt, 7, temperature=1.5, key=my_key)
        got = eng.run()
        np.testing.assert_array_equal(
            got[sid_default],
            _standalone(params, cfg, prompt, 7,
                        key=eng.request_key(sid_default),
                        temperature=0.7))
        np.testing.assert_array_equal(
            got[sid_custom],
            _standalone(params, cfg, prompt, 7, key=my_key,
                        temperature=1.5))

    def test_greedy_engine_rejects_per_request_temperature(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8)
        with pytest.raises(ValueError, match="sampling engine"):
            eng.submit(np.arange(4, dtype=np.int32), 4, temperature=0.9)
        with pytest.raises(ValueError, match="sampling engine"):
            eng.submit(np.arange(4, dtype=np.int32), 4,
                       key=jax.random.PRNGKey(1))
        with pytest.raises(ValueError, match="> 0"):
            ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                              pages_per_seq=3, page_size=8,
                              temperature=0.9).submit(
                np.arange(4, dtype=np.int32), 4, temperature=-1.0)


class TestOverlappedAdmission:
    def test_overlap_output_identical_to_serial(self):
        # overlapped admission is a SCHEDULING change only: the same
        # stream through overlap=True and overlap=False engines emits
        # identical tokens, and the exposed-admission (bubble) fraction
        # is recorded on both
        cfg, params = _setup()
        reqs = _requests(cfg, 8, seed=31)
        outs, bubbles = [], []
        for overlap in (True, False):
            eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                    pages_per_seq=3, page_size=8,
                                    chunk=2, overlap=overlap)
            ids = [eng.submit(p, m) for p, m in reqs]
            got = eng.run()
            outs.append([got[sid] for sid in ids])
            bubbles.append(eng.last_bubble_frac)
        for a, b in zip(*outs):
            np.testing.assert_array_equal(a, b)
        assert all(0.0 <= b <= 1.0 for b in bubbles)

    def test_admit_telemetry_has_overlap_fields(self):
        # first wave admits with nothing in flight (exposed — the
        # bubble); a request admitted into a freed slot while the OTHER
        # row's chunk is dispatched records overlapped=True
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8, chunk=2,
                                prompt_buckets=(8, 12),
                                emit=lambda **kw: events.append(kw))
        eng.submit(np.arange(5, dtype=np.int32), 2)   # finishes fast
        eng.submit(np.arange(5, dtype=np.int32), 8)   # keeps running
        eng.submit(np.arange(5, dtype=np.int32), 2)   # admitted mid-run
        eng.run()
        admits = [e for e in events if e["kind"] == "serve_admit"]
        assert [e["seq_id"] for e in admits] == [0, 1, 2]
        for e in admits:
            assert e["padded_len"] == 8 and e["prompt_len"] == 5
        assert admits[0]["overlapped"] is False
        assert admits[1]["overlapped"] is False
        assert admits[2]["overlapped"] is True


class TestPreemptionAndResume:
    """The preempt/resume oracle: a sequence evicted under forced page
    starvation and later resumed must emit BYTE-IDENTICAL tokens to an
    uninterrupted standalone run with the same request key — greedy
    and sampled. The starvation is structural (pool sized one page
    short of the high-priority arrival), not a timing accident."""

    def _starved(self, cfg, params, events=None, **over):
        # 4-page pool; the low-priority victim takes all 4, the
        # 8-token-prompt high-priority arrival needs 2 — page-starved
        # by construction until the victim is evicted
        return ContinuousBatcher(
            params, cfg, slots=2, pool_pages=4, pages_per_seq=4,
            page_size=8, chunk=2, preempt=True,
            prompt_buckets=(8, 16, 24, 32),
            emit=(lambda **kw: events.append(kw)) if events is not None
            else None, **over)

    def test_preempted_and_resumed_tokens_exact_greedy(self):
        cfg, params = _setup()
        events = []
        eng = self._starved(cfg, params, events)
        pA = np.arange(5, dtype=np.int32)
        pB = np.arange(8, dtype=np.int32) + 7
        a = eng.submit(pA, 20, priority=1)  # needs all 4 pages
        eng.run(max_rounds=3)               # A mid-generation
        b = eng.submit(pB, 4, priority=0)   # starved -> must evict A
        got = eng.run()
        pre = [e for e in events if e["kind"] == "serve_preempt"]
        assert [e["seq_id"] for e in pre] == [a]
        assert pre[0]["for_seq_id"] == b
        assert eng.stats[a]["preemptions"] == 1
        # the oracle: byte-identical to never having been preempted
        np.testing.assert_array_equal(got[a], _standalone(params, cfg,
                                                          pA, 20))
        np.testing.assert_array_equal(got[b], _standalone(params, cfg,
                                                          pB, 4))
        # the arena drained; the resumed admission was flagged as such
        assert sorted(eng.free_pages) == list(range(4))
        resumed = [e for e in events
                   if e["kind"] == "serve_admit" and e["resumed"]]
        assert [e["seq_id"] for e in resumed] == [a]

    def test_preempted_and_resumed_sampled_key_stream_exact(self):
        # the sharper half of the oracle: the victim's PER-ROW KEY
        # STATE snapshots at eviction and the resume consumes it with
        # the same split/pick order — so even SAMPLED draws are
        # byte-identical to the uninterrupted standalone run
        cfg, params = _setup()
        eng = self._starved(cfg, params, temperature=0.8, top_k=8,
                            seed=3)
        pA = np.arange(5, dtype=np.int32)
        pB = np.arange(8, dtype=np.int32) + 7
        a = eng.submit(pA, 20, priority=1)
        eng.run(max_rounds=3)
        b = eng.submit(pB, 4, priority=0)
        got = eng.run()
        assert eng.stats[a]["preemptions"] == 1
        np.testing.assert_array_equal(
            got[a], _standalone(params, cfg, pA, 20,
                                key=eng.request_key(a),
                                temperature=0.8, top_k=8))
        np.testing.assert_array_equal(
            got[b], _standalone(params, cfg, pB, 4,
                                key=eng.request_key(b),
                                temperature=0.8, top_k=8))

    def test_equal_priority_never_preempts(self):
        # preemption is a PRIORITY mechanism, not a fairness one: an
        # equal-priority arrival waits for pages like round 6 always did
        cfg, params = _setup()
        events = []
        eng = self._starved(cfg, params, events)
        pA = np.arange(5, dtype=np.int32)
        a = eng.submit(pA, 12, priority=1)
        eng.run(max_rounds=2)
        b = eng.submit(np.arange(8, dtype=np.int32), 4, priority=1)
        got = eng.run()
        assert not [e for e in events if e["kind"] == "serve_preempt"]
        assert eng.stats[a]["preemptions"] == 0
        np.testing.assert_array_equal(got[a], _standalone(params, cfg,
                                                          pA, 12))

    def test_priority_order_admission(self):
        # both queued up front: the high-priority request admits FIRST
        # even though the low-priority one was submitted earlier
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8, chunk=2,
                                emit=lambda **kw: events.append(kw))
        lo = eng.submit(np.arange(5, dtype=np.int32), 4, priority=2)
        hi = eng.submit(np.arange(5, dtype=np.int32), 4, priority=0)
        eng.run()
        admits = [e["seq_id"] for e in events
                  if e["kind"] == "serve_admit"]
        assert admits == [hi, lo]

    def test_shed_expired_deadline(self):
        # a queued request whose deadline lapses is SHED: empty output,
        # outcome "shed", telemetry event — not silent starvation
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8, chunk=2,
                                emit=lambda **kw: events.append(kw))
        a = eng.submit(np.arange(5, dtype=np.int32), 9)
        b = eng.submit(np.arange(5, dtype=np.int32), 4,
                       deadline_s=0.0)  # expires while a serves
        got = eng.run()
        assert eng.stats[b]["outcome"] == "shed"
        assert got[b].size == 0
        assert [e["seq_id"] for e in events
                if e["kind"] == "serve_shed"] == [b]
        np.testing.assert_array_equal(
            got[a], _standalone(params, cfg,
                                np.arange(5, dtype=np.int32), 9))

    def test_highwater_defers_fresh_admissions(self):
        # admit_highwater reserves headroom: the second fresh request
        # would push used pages past the mark, so it waits for the
        # first to finish even though pages are nominally free
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                admit_highwater=0.5,
                                emit=lambda **kw: events.append(kw))
        a = eng.submit(np.arange(5, dtype=np.int32), 9)   # 2 pages
        b = eng.submit(np.arange(5, dtype=np.int32), 9)   # would be 4>3
        got = eng.run()
        admits = [e for e in events if e["kind"] == "serve_admit"]
        # b admitted only after a freed its pages: never 2 concurrent
        assert admits[1]["free_pages"] >= 4
        for sid in (a, b):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg,
                                      np.arange(5, dtype=np.int32), 9))
        with pytest.raises(ValueError, match="admit_highwater"):
            ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                              pages_per_seq=3, page_size=8,
                              admit_highwater=0.0)

    def test_infeasible_head_never_evicts(self):
        # a fresh high-priority request whose need exceeds the
        # high-water cap can NEVER admit — preempting for it would
        # thrash lower classes through re-prefills every round and
        # still end stuck. The engine must leave the victims alone,
        # serve them to completion, and then fail LOUDLY.
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=6, page_size=8, chunk=2,
                                preempt=True, admit_highwater=0.5,
                                emit=lambda **kw: events.append(kw))
        pA = np.arange(5, dtype=np.int32)
        a = eng.submit(pA, 9, priority=1)       # 2 pages <= cap 3
        eng.run(max_rounds=2)
        eng.submit(np.arange(10, dtype=np.int32), 16,
                   priority=0)                  # 4 pages > cap 3: stuck
        with pytest.raises(RuntimeError, match="admit_highwater"):
            eng.run()
        assert not [e for e in events if e["kind"] == "serve_preempt"]
        np.testing.assert_array_equal(
            eng.finished[a], _standalone(params, cfg, pA, 9))

    def test_non_victim_pages_over_the_cap_never_evict(self):
        # the thrash shape: the head is kept over the high-water cap
        # by pages that belong to SAME-or-higher-priority rows, so
        # evicting the lower-priority victim could never admit it —
        # the victim's resume would bypass the mark, re-admit the same
        # round, and be evicted again next round, forever. The
        # feasibility check must count only victim pages as freeable.
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=3, pool_pages=8,
                                pages_per_seq=4, page_size=8, chunk=2,
                                preempt=True,
                                emit=lambda **kw: events.append(kw))
        pA = np.arange(5, dtype=np.int32)
        a = eng.submit(pA, 20, priority=0)   # 4 pages, non-victim
        b = eng.submit(pA, 9, priority=2)    # 2 pages, the only victim
        eng.run(max_rounds=2)                # both active (used 6/8)
        # the operator tightens the mark mid-run: cap drops to 4.8 —
        # a fresh p1 head (2 pages) now reads used 6 + 2 > 4.8, and
        # even with b evicted the p0 row alone keeps 4 + 2 > 4.8
        eng.admit_highwater = 0.6
        c = eng.submit(pA, 9, priority=1)
        eng.run(max_rounds=4)
        assert not [e for e in events if e["kind"] == "serve_preempt"]
        got = eng.run()  # a and b drain; c admits into the empty pool
        for sid, budget in ((a, 20), (b, 9), (c, 9)):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, pA, budget))
        assert eng.stats[b]["preemptions"] == 0

    def test_bounded_run_parks_instead_of_waiting_for_arrivals(self):
        import time as _time

        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8, chunk=2)
        t0 = _time.perf_counter()
        eng.run(arrivals=[(30.0, dict(prompt=np.arange(4, dtype=np.int32),
                                      max_new=2))],
                max_rounds=1)
        # parks immediately: must not idle-wait the 30s arrival out
        assert _time.perf_counter() - t0 < 5.0

    def test_stats_and_slo_rollup(self):
        from hpc_patterns_tpu.harness import slo as slolib

        cfg, params = _setup()
        targets = {0: slolib.SLOTarget(ttft_s=60.0, tpot_s=60.0)}
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                slo=targets)
        ids = [eng.submit(p, m) for p, m in _requests(cfg, 4, seed=41)]
        eng.run()
        assert eng.last_slo is not None
        tot = eng.last_slo["total"]
        assert tot["served"] == 4 and tot["shed"] == 0
        # absurdly loose targets: everything attains, goodput == raw
        assert tot["attained"] == 4
        assert tot["goodput_tok_s"] == pytest.approx(tot["tok_s"])
        for sid in ids:
            rec = eng.stats[sid]
            assert rec["outcome"] == "ok"
            assert rec["t_submit"] <= rec["t_first"] <= rec["t_finish"]
            assert rec["tokens"] == len(eng.finished[sid])

    def test_open_loop_arrivals_replay(self):
        # run(arrivals=...) submits on the schedule's clock; outputs
        # stay oracle-exact and stats carry every arrival
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2)
        reqs = _requests(cfg, 4, seed=43)
        arrivals = [
            (0.02 * i, dict(prompt=p, max_new=m, seq_id=100 + i))
            for i, (p, m) in enumerate(reqs)
        ]
        got = eng.run(arrivals=arrivals)
        for i, (p, m) in enumerate(reqs):
            np.testing.assert_array_equal(
                got[100 + i], _standalone(params, cfg, p, m))
        assert all(eng.stats[100 + i]["outcome"] == "ok"
                   for i in range(4))


class TestDraftSampledDistribution:
    def test_draft_assisted_sampling_preserves_law(self):
        # the distribution oracle for the one law-only serving mode:
        # draft-assisted SAMPLED serving emits tokens whose law equals
        # target-only sampling (Leviathan accept/resample), though the
        # draws differ. Protocol: N requests, same prompt, budget 2 —
        # token[0] comes from the prefill pick (per-request key: its
        # law is trivially exact), token[1] from a LIVE rejection-
        # sampling round against an INDEPENDENT draft (low acceptance,
        # so the resample branch is exercised). The empirical
        # distribution of token[1] must match the exact mixture law
        # q = mean_i p_warped(. | prompt, t0_i) computed from the
        # target's own logits. Deterministic given the seeds.
        from hpc_patterns_tpu.models import forward
        from hpc_patterns_tpu.models.decode import _topk_mask
        from hpc_patterns_tpu.models.transformer import init_params as ip

        temp, top_k, n_req = 1.0, 4, 160
        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(1234), dcfg)
        prompt = np.arange(5, dtype=np.int32)
        pps = ContinuousBatcher.pages_needed(5, 2, 8, gamma=2)
        eng = ContinuousBatcher(params, cfg, slots=4,
                                pool_pages=4 * pps, pages_per_seq=pps,
                                page_size=8, chunk=2,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=2, temperature=temp, top_k=top_k,
                                seed=11)
        ids = [eng.submit(prompt, 2) for _ in range(n_req)]
        got = eng.run()
        firsts = np.array([got[sid][0] for sid in ids])
        seconds = np.array([got[sid][1] for sid in ids])

        def warped_next(seq):
            logits = np.asarray(forward(
                params, jnp.asarray(seq, jnp.int32)[None, :], cfg))[0, -1]
            masked = np.asarray(_topk_mask(jnp.asarray(logits), top_k))
            z = (masked / temp) - masked.max()
            p = np.exp(z)
            p[~np.isfinite(p)] = 0.0
            return p / p.sum()

        law = {}
        q = np.zeros(cfg.vocab)
        for t0 in firsts:
            t0 = int(t0)
            if t0 not in law:
                law[t0] = warped_next(np.append(prompt, t0))
            q += law[t0]
        q /= n_req
        emp = np.bincount(seconds, minlength=cfg.vocab) / n_req
        tv = 0.5 * np.abs(emp - q).sum()
        assert tv < 0.2, (
            f"draft-assisted sampled law diverged: TV {tv:.3f} "
            f"(support emp {np.count_nonzero(emp)}, "
            f"law {np.count_nonzero(q > 1e-6)})")
