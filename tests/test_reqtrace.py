"""Request-scoped tracing (harness/reqtrace.py + harness/explain.py):
the coverage invariant and the attribution teeth.

THE claim of round 18: a finished request's lifecycle segments tile
``[t_submit, t_finish]`` exactly — through preemption-and-resume,
swap-out/prefetch, and cross-replica migration (greedy AND sampled) —
with every unclaimed span surfacing as an explicit ``untracked``
segment, and a seeded chaos delay landing in the bucket that names its
cause. The history rides the MigrationBundle and the wire codec as a
backward-compatible field (absent key -> one ``untracked`` segment),
so a migrated request's destination-side record never starts fresh.
Disabled, the tracer must be invisible: same tokens, no recorder."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import explain as explainlib
from hpc_patterns_tpu.harness import reqtrace
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import ContinuousBatcher, EngineCore
from hpc_patterns_tpu.serving_plane.migration import (
    bundle_from_wire,
    bundle_to_wire,
)
from hpc_patterns_tpu.serving_plane.router import Replica, ServingPlane

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")
ENG = dict(slots=2, pool_pages=8, pages_per_seq=4, page_size=8,
           chunk=2)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**BASE)
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(autouse=True)
def _clean_tracer():
    reqtrace.reset()
    yield
    reqtrace.reset()


def _standalone(params, cfg, prompt, max_new, **kw):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8, **kw))[0]


def _coverage(rtr, stats, sid):
    st = stats[sid]
    return reqtrace.coverage_frac(rtr.segments(sid) or (),
                                  st["t_submit"], st["t_finish"])


def _kinds(rtr, sid):
    return [k for k, *_ in rtr.segments(sid)]


class TestSegmentMechanics:
    def test_transitions_tile_without_gaps(self):
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(7, 1.0)
        rtr.stamp_transition(7, "admit_wait", 2.0)
        rtr.stamp_transition(7, "prefill", 2.5)
        rtr.stamp_transition(7, "decode", 3.0)
        rtr.finish_request(7, 5.0)
        tiled, untracked = reqtrace.finalize(rtr.segments(7), 1.0, 5.0)
        assert untracked == 0.0
        assert [s[0] for s in tiled] == [
            "queued", "admit_wait", "prefill", "decode"]
        # the tiling is exact: spans sum to the request's whole life
        assert sum(s[2] - s[1] for s in tiled) == pytest.approx(4.0)

    def test_gap_becomes_explicit_untracked(self):
        # a stamp site that went missing leaves a gap; finalize turns
        # it into a measured untracked segment, never silence
        segs = [["queued", 0.0, 1.0, None], ["decode", 3.0, 4.0, None]]
        tiled, untracked = reqtrace.finalize(segs, 0.0, 4.0)
        assert [s[0] for s in tiled] == ["queued", "untracked", "decode"]
        assert untracked == pytest.approx(2.0)
        assert reqtrace.coverage_frac(segs, 0.0, 4.0) == pytest.approx(
            0.5)

    def test_unresolved_ends_clamp_into_span(self):
        # open t1 resolves to t_finish; None t0 (the legacy decode)
        # resolves to the cursor; everything clamps into the life
        segs = [["untracked", None, None, None]]
        tiled, untracked = reqtrace.finalize(segs, 2.0, 6.0)
        assert tiled == [["untracked", 2.0, 6.0, None]]
        assert untracked == pytest.approx(4.0)

    def test_empty_history_is_all_untracked(self):
        tiled, untracked = reqtrace.finalize((), 0.0, 3.0)
        assert tiled == [["untracked", 0.0, 3.0, None]]
        assert untracked == pytest.approx(3.0)

    def test_shed_marker_survives_zero_length(self):
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(1, 0.0)
        rtr.finish_request(1, 2.0, final="shed")
        tiled, _ = reqtrace.finalize(rtr.segments(1), 0.0, 2.0)
        assert tiled[-1][0] == "shed"
        assert tiled[-1][1] == tiled[-1][2] == 2.0

    def test_rebegin_continues_one_life(self):
        # the plane's death-resume resubmits the SAME id: one user-
        # visible life, one tiling — a re-begin must not wipe history
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(4, 0.0)
        rtr.stamp_transition(4, "prefill", 1.0)
        rtr.begin_request(4, 2.0)
        assert [k for k, *_ in rtr.segments(4)] == [
            "queued", "prefill", "queued"]

    def test_restamp_submit_moves_start_back_only(self):
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(2, 5.0)
        rtr.restamp_submit(2, 3.0)
        assert rtr.segments(2)[0][1] == 3.0
        rtr.restamp_submit(2, 9.0)  # never forward
        assert rtr.segments(2)[0][1] == 3.0

    def test_annotate_open_tags_current_segment(self):
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(3, 0.0)
        rtr.stamp_transition(3, "migrating", 1.0)
        rtr.annotate_open(3, seq=11)
        assert rtr.segments(3)[-1][3] == {"seq": 11}

    def test_active_is_none_by_default(self):
        assert reqtrace.active() is None
        rtr = reqtrace.configure(enabled=True)
        assert reqtrace.active() is rtr
        reqtrace.configure(enabled=False)
        assert reqtrace.active() is None


class TestCoverageInvariant:
    """The tiling holds through every degraded path the engine owns."""

    def test_plain_serve_full_coverage(self, setup):
        cfg, params = setup
        reqtrace.configure(enabled=True)
        eng = ContinuousBatcher(params, cfg, **ENG)
        ids = [eng.submit(np.arange(5 + i, dtype=np.int32), 6)
               for i in range(4)]
        eng.run()
        rtr = reqtrace.active()
        for sid in ids:
            assert _coverage(rtr, eng.stats, sid) >= 0.999
            assert _kinds(rtr, sid) == [
                "queued", "admit_wait", "prefill", "decode"]

    @pytest.mark.parametrize("sampled", [False, True])
    def test_preempt_resume_tiles_exactly(self, setup, sampled):
        # the starved shape (test_serving.py): the victim's history
        # must carry preempted -> re-admission -> resumed decode with
        # zero untracked time, greedy AND sampled
        cfg, params = setup
        kw = (dict(temperature=0.8, top_k=8, seed=3) if sampled
              else {})
        reqtrace.configure(enabled=True)
        eng = ContinuousBatcher(
            params, cfg, slots=2, pool_pages=4, pages_per_seq=4,
            page_size=8, chunk=2, preempt=True,
            prompt_buckets=(8, 16, 24, 32), **kw)
        pA = np.arange(5, dtype=np.int32)
        pB = np.arange(8, dtype=np.int32) + 7
        a = eng.submit(pA, 20, priority=1)
        eng.run(max_rounds=3)
        b = eng.submit(pB, 4, priority=0)
        got = eng.run()
        assert eng.stats[a]["preemptions"] == 1
        rtr = reqtrace.active()
        assert _coverage(rtr, eng.stats, a) >= 0.999
        assert _coverage(rtr, eng.stats, b) >= 0.999
        kinds = _kinds(rtr, a)
        assert "preempted" in kinds
        # the resume re-enters through admission, not through a wipe
        assert kinds.index("preempted") < len(kinds) - 1
        assert kinds.count("prefill") == 2
        np.testing.assert_array_equal(
            got[a], _standalone(params, cfg, pA, 20, **(
                dict(key=eng.request_key(a), temperature=0.8, top_k=8)
                if sampled else {})))

    @pytest.mark.parametrize("sampled", [False, True])
    def test_plane_migration_tiles_exactly(self, setup, sampled):
        # 1 prefill + 1 decode replica: every request crosses the KV
        # handoff and its ONE history spans both engines — the
        # satellite bugfix (destination record must not start fresh)
        cfg, params = setup
        kw = (dict(temperature=0.8, top_k=8, seed=0) if sampled
              else {})
        reqtrace.configure(enabled=True)
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG, **kw), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, **ENG, **kw), name="d",
                    role="decode"),
        ])
        rng = np.random.RandomState(5)
        reqs = [(rng.randint(0, cfg.vocab, size=8).astype(np.int32), 6)
                for _ in range(3)]
        rids = [plane.submit(p, m) for p, m in reqs]
        plane.run()
        assert plane.migrations >= len(rids)
        rtr = reqtrace.active()
        for rid in rids:
            assert _coverage(rtr, plane.stats, rid) >= 0.999
            kinds = _kinds(rtr, rid)
            # donor-side life PRECEDES the handoff in the one history
            assert kinds.index("prefill") < kinds.index("migrating")
            assert kinds[-1] == "decode"
            # the router tagged the migration seq for the merge's
            # flow arrows
            mig = [s for s in rtr.segments(rid)
                   if s[0] == "migrating"]
            assert all(isinstance(s[3], dict) and "seq" in s[3]
                       for s in mig)

    def test_disabled_path_identical_tokens_no_recorder(self, setup):
        # --trace-off byte-identical: same tokens with the tracer off
        # and on, and the off path never installs a recorder
        cfg, params = setup
        rng = np.random.RandomState(2)
        reqs = [(rng.randint(0, cfg.vocab, size=8).astype(np.int32), 6)
                for _ in range(3)]

        def serve():
            eng = ContinuousBatcher(params, cfg, **ENG)
            ids = [eng.submit(p, m) for p, m in reqs]
            return {s: eng.run()[s] for s in ids}

        assert reqtrace.active() is None
        off = serve()
        reqtrace.configure(enabled=True)
        on = serve()
        for s in off:
            np.testing.assert_array_equal(off[s], on[s])
        reqtrace.reset()
        assert reqtrace.active() is None


class TestChaosAttribution:
    """The teeth: a seeded delay must land in the bucket that names
    its cause, within tolerance — not smear into a neighbor."""

    def test_stall_lands_in_queued(self, setup):
        # slots=1: seq1 waits queued while seq0 decodes; the seeded
        # engine_round stall delays seq1's admission, so the injected
        # time must show up inside seq1's queued segment
        cfg, params = setup
        delay_ms = 80
        warm = ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                                 pages_per_seq=4, page_size=8, chunk=2)
        warm.submit(np.arange(5, dtype=np.int32), 8)
        warm.run()  # absorb XLA compiles outside the timed claim
        reqtrace.configure(enabled=True)
        chaoslib.configure(f"stall:at=1,delay_ms={delay_ms}")
        try:
            eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                                    pages_per_seq=4, page_size=8,
                                    chunk=2)
            eng.submit(np.arange(5, dtype=np.int32), 8)
            s1 = eng.submit(np.arange(6, dtype=np.int32), 4)
            eng.run()
            inj = [e for e in chaoslib.injections()
                   if e["site"] == "engine_round"]
            assert inj, "seeded stall never fired"
            delay_s = sum(e["delay_s"] for e in inj)
            rtr = reqtrace.active()
            queued = sum(t1 - t0 for k, t0, t1, _ in rtr.segments(s1)
                         if k == "queued")
            assert queued >= delay_s, (
                f"stall delay {delay_s}s missing from queued "
                f"({queued}s)")
            assert _coverage(rtr, eng.stats, s1) >= 0.999
        finally:
            chaoslib.reset()

    def test_slow_host_transfer_lands_in_prefetch_wait(self, setup):
        # the tiered path: a seeded host_transfer delay must widen the
        # prefetch_wait segment it sits inside (the residency window
        # discipline of test_residency_serving, per-request form)
        from hpc_patterns_tpu.memory import (
            ColdAfterNPolicy,
            ResidencyManager,
        )

        cfg = TransformerConfig(**{**BASE, "max_seq": 128,
                                   "decode_attn": "gather",
                                   "n_heads": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        pps = ContinuousBatcher.pages_needed(8, 24, 8)
        delay_s = 0.06
        reqtrace.configure(enabled=True)
        chaoslib.configure(
            f"slow_host_transfer:delay_ms={int(delay_s * 1e3)}")
        try:
            mgr = ResidencyManager(host_blocks=5 * pps,
                                   policy=ColdAfterNPolicy(2))
            eng = ContinuousBatcher(
                params, cfg, slots=5, pool_pages=2 * pps,
                pages_per_seq=pps, page_size=8, chunk=4,
                residency=mgr)
            rng = np.random.RandomState(3)
            ids = [eng.submit(rng.randint(0, cfg.vocab, size=8)
                              .astype(np.int32), 24) for _ in range(5)]
            eng.run()
            assert mgr.swap_outs > 0
            fired = [e for e in chaoslib.injections()
                     if e["site"] == "host_transfer"]
            assert fired
            rtr = reqtrace.active()
            waits = [t1 - t0 for sid in ids
                     for k, t0, t1, _ in rtr.segments(sid)
                     if k == "prefetch_wait" and t1 is not None]
            assert waits and max(waits) >= delay_s
            swapped = [sid for sid in ids
                       if "swapped_out" in _kinds(rtr, sid)]
            assert swapped
            for sid in ids:
                assert _coverage(rtr, eng.stats, sid) >= 0.999
        finally:
            chaoslib.reset()


class TestHistoryTransport:
    def _bundle(self, setup):
        cfg, params = setup
        reqtrace.configure(enabled=True)
        eng = EngineCore(params, cfg, **ENG)
        eng.submit(np.arange(8, dtype=np.int32), 6)
        eng.service_round(decode=False)
        [slot] = eng.exportable_slots()
        return eng.export_migration(slot)

    def test_bundle_carries_history(self, setup):
        bundle = self._bundle(setup)
        assert bundle.segments is not None
        kinds = [s[0] for s in bundle.segments]
        assert kinds[0] == "queued" and kinds[-1] == "migrating"
        # exported copies are immutable-shaped tuples, JSON-able
        json.dumps(bundle.segments)

    def test_wire_roundtrip_preserves_segments(self, setup):
        bundle = self._bundle(setup)
        back = bundle_from_wire(bundle_to_wire(bundle))
        assert back.segments == tuple(
            tuple(s) for s in bundle.segments)

    def test_wire_null_means_donor_traced_nothing(self, setup):
        bundle = self._bundle(setup)
        wire = bundle_to_wire(bundle)
        wire["segments"] = None
        assert bundle_from_wire(wire).segments is None

    def test_legacy_wire_absent_key_decodes_to_untracked(self, setup):
        # the backward-compat contract (the PR 17 transport pattern):
        # a pre-round-18 artifact has NO segments key — the reader
        # must decode it to one untracked span, not None, so the
        # donor-side life is a measured number on the receiver
        bundle = self._bundle(setup)
        wire = bundle_to_wire(bundle)
        del wire["segments"]
        assert bundle_from_wire(wire).segments \
            == reqtrace.LEGACY_SEGMENTS

    def test_legacy_install_resolves_to_untracked_span(self):
        # a legacy bundle's whole donor life lands as one untracked
        # segment from t_submit to the install instant, then decode
        rtr = reqtrace.ReqTrace()
        rtr.install_history(9, reqtrace.LEGACY_SEGMENTS, t=4.0,
                            t_submit=1.0)
        tiled, untracked = reqtrace.finalize(rtr.segments(9), 1.0, 6.0)
        assert [s[0] for s in tiled] == ["untracked", "decode"]
        assert untracked == pytest.approx(3.0)

    def test_install_prefers_local_history(self):
        # in-process the recorder is shared: the live history carries
        # the router's seq annotation, which the bundle's exported
        # copy predates — install must keep the richer local one
        rtr = reqtrace.ReqTrace()
        rtr.begin_request(5, 0.0)
        carried = rtr.export_history(5, 1.0)
        rtr.annotate_open(5, seq=3)
        rtr.install_history(5, carried, t=2.0, t_submit=0.0)
        mig = [s for s in rtr.segments(5) if s[0] == "migrating"]
        assert mig[0][3] == {"seq": 3}


class TestPerfettoLane:
    def test_finished_history_mirrors_onto_request_lane(self, setup):
        # with a flight recorder active, finish mirrors the resolved
        # segments as cat="request" X slices on the request's own tid
        cfg, params = setup
        tracelib.configure(enabled=True)
        reqtrace.configure(enabled=True)
        try:
            eng = ContinuousBatcher(params, cfg, **ENG)
            sid = eng.submit(np.arange(5, dtype=np.int32), 4)
            eng.run()
            rec = tracelib.active()
            lane = [ev for ev in rec.events
                    if ev[0] == "X" and ev[1] == "request"]
            assert {ev[2] for ev in lane} >= {
                "queued", "prefill", "decode"}
            tids = {ev[4] for ev in lane}
            assert tids == {tracelib.TID_REQUEST + sid}
            assert all(ev[6]["seq_id"] == sid for ev in lane)
        finally:
            tracelib.configure(enabled=False)


class TestSnapshotAndExplain:
    def _served_snapshot(self, setup):
        cfg, params = setup
        reqtrace.configure(enabled=True)
        eng = ContinuousBatcher(params, cfg, **ENG)
        ids = [eng.submit(np.arange(5 + i, dtype=np.int32), 6,
                          priority=i % 2) for i in range(4)]
        eng.run()
        return reqtrace.active().snapshot(eng.stats)

    def test_snapshot_payload_and_coverage(self, setup):
        snap = self._served_snapshot(setup)
        assert snap["n"] == 4
        assert snap["coverage_frac"] >= 0.999
        json.dumps(snap)  # the kind=reqtrace record must be JSON-able
        entry = next(iter(snap["requests"].values()))
        assert {"priority", "t_submit", "t_first", "t_finish",
                "segments", "outcome"} <= set(entry)

    def test_digest_shares_sum_and_gate_scalars(self, setup):
        snap = self._served_snapshot(setup)
        dig = explainlib.digest([snap])
        assert dig["n"] == 4
        assert dig["coverage_frac"] >= 0.999
        assert 0.0 <= dig["ttft_p99_queue_share"] <= 1.0
        assert set(dig["classes"]) == {0, 1}
        for cls in dig["classes"].values():
            assert cls["n_band"] >= 1
            # window-weighted shares are a partition of attributed time
            assert sum(cls["band_shares"].values()) == pytest.approx(
                1.0, abs=1e-6)
        assert len(dig["worst"]) <= explainlib.WORST_N
        ttfts = [r["ttft_s"] for r in dig["worst"]]
        assert ttfts == sorted(ttfts, reverse=True)

    def test_format_names_the_tail_bucket(self, setup):
        snap = self._served_snapshot(setup)
        text = explainlib.format_explain(explainlib.digest([snap]))
        assert "request forensics" in text
        assert "p99-TTFT band" in text
        assert "queued" in text  # the dominant bucket is named

    def test_cli_exit_codes_and_digest_out(self, setup, tmp_path,
                                           capsys):
        from hpc_patterns_tpu.harness.runlog import RunLog

        snap = self._served_snapshot(setup)
        log = tmp_path / "run.jsonl"
        RunLog(str(log)).emit(kind="reqtrace", **snap)
        out = tmp_path / "dig.json"
        assert explainlib.main([str(log), "-o", str(out)]) == 0
        printed = capsys.readouterr().out
        assert "request forensics" in printed
        dig = json.loads(out.read_text())
        assert dig["n"] == 4
        # a log with no reqtrace records exits 2, loudly
        empty = tmp_path / "empty.jsonl"
        RunLog(str(empty)).emit(kind="metrics")
        assert explainlib.main([str(empty)]) == 2


class TestInterTokenDigest:
    """The decode-phase half of the digest: per-token availability
    stamps tile the same canonical segments over INTER-TOKEN windows,
    so 'tpot p99 missed' comes pre-attributed like the TTFT band
    does. Edge shapes the tiling must survive: shed-only streams (no
    tokens at all), single-token responses (no gap), and
    migration-install histories (segments without stamps — donor
    token instants are engine-local wall clock, so installs start
    empty)."""

    @staticmethod
    def _snap(entries):
        return {"n": len(entries), "coverage_frac": 1.0,
                "requests": {str(i): e
                             for i, e in enumerate(entries)}}

    def test_gap_tiling_attributes_the_stall(self):
        # stamps at 1.0/1.1/3.0: the long gap crosses a 1.8s
        # prefetch_wait span -> gap shares sum to 1.0 and the pooled
        # p99 band blames the stall mechanism
        e = {"priority": 0, "t_submit": 0.0, "t_first": 1.0,
             "t_finish": 3.0, "tokens": 3, "outcome": "ok",
             "segments": [["queued", 0.0, 1.0, None],
                          ["decode", 1.0, 1.1, None],
                          ["prefetch_wait", 1.1, 2.9, None],
                          ["decode", 2.9, 3.0, None]],
             "token_ts": [1.0, 1.1, 3.0]}
        dig = explainlib.digest([self._snap([e])])
        tp = dig["tpot"]
        assert tp["n_gaps"] == 2 and tp["n_band"] == 1
        assert sum(tp["band_shares"].values()) == pytest.approx(1.0)
        assert dig["tpot_p99_band_shares"]["prefetch_wait"] \
            == pytest.approx(1.8 / 1.9)
        assert dig["tpot_p99_stall_share"] \
            == pytest.approx(1.8 / 1.9)
        # the per-class section carries the same pool
        assert dig["classes"][0]["tpot"]["n_gaps"] == 2
        text = explainlib.format_explain(dig)
        assert "inter-token gaps" in text
        assert "prefetch_wait" in text

    def test_shed_only_stream_has_no_gaps_and_zero_stall_share(self):
        e = {"priority": 0, "t_submit": 0.0, "t_first": None,
             "t_finish": 1.0, "tokens": 0, "outcome": "shed",
             "segments": [["queued", 0.0, 0.5, None],
                          ["shed", 0.5, 0.5, None]],
             "token_ts": None}
        dig = explainlib.digest([self._snap([e])])
        assert dig["tpot"]["n_gaps"] == 0
        assert dig["tpot_p99_stall_share"] == 0.0
        assert dig["tpot_p99_band_shares"] == {}
        assert dig["tpot"]["gap"]["p99"] is None
        explainlib.format_explain(dig)  # renders without a tpot line

    def test_single_token_response_has_no_inter_token_window(self):
        e = {"priority": 0, "t_submit": 0.0, "t_first": 1.0,
             "t_finish": 1.0, "tokens": 1, "outcome": "ok",
             "segments": [["prefill", 0.0, 1.0, None]],
             "token_ts": [1.0]}
        dig = explainlib.digest([self._snap([e])])
        assert dig["tpot"]["n_gaps"] == 0
        assert dig["tpot_p99_stall_share"] == 0.0

    def test_migration_install_history_without_stamps_digests(self):
        # a migrated request's install carries full segments but an
        # empty stamp list (donor instants are engine-local): the
        # TTFT half still attributes, the TPOT half stays silent
        e = {"priority": 0, "t_submit": 0.0, "t_first": 0.5,
             "t_finish": 2.0, "tokens": 8, "outcome": "ok",
             "segments": [["queued", 0.0, 0.4, None],
                          ["prefill", 0.4, 0.5, None],
                          ["decode", 0.5, 1.0, None],
                          ["migrating", 1.0, 1.5, None],
                          ["decode", 1.5, 2.0, None]],
             "token_ts": None}
        dig = explainlib.digest([self._snap([e])])
        assert dig["tpot"]["n_gaps"] == 0
        assert dig["tpot_p99_stall_share"] == 0.0
        assert dig["ttft_p99_band_shares"]["queued"] \
            == pytest.approx(0.8)

    def test_engine_snapshot_carries_monotone_token_stamps(self, setup):
        # the producer half: a served stream's stats rows stamp one
        # instant per collected token, nondecreasing, first stamp at
        # t_first — and the snapshot serializes them
        cfg, params = setup
        reqtrace.configure(enabled=True)
        eng = ContinuousBatcher(params, cfg, **ENG)
        ids = [eng.submit(np.arange(5 + i, dtype=np.int32), 6)
               for i in range(3)]
        eng.run()
        snap = reqtrace.active().snapshot(eng.stats)
        for sid in ids:
            entry = snap["requests"][str(sid)]
            ts = entry["token_ts"]
            assert len(ts) == entry["tokens"]
            assert ts == sorted(ts)
            assert ts[0] == pytest.approx(entry["t_first"])
            assert ts[-1] <= entry["t_finish"] + 1e-6
        dig = explainlib.digest([snap])
        assert dig["tpot"]["n_gaps"] >= 3
        assert sum(dig["tpot"]["band_shares"].values()) \
            == pytest.approx(1.0)
