"""Known-bad: the rank-branched-collective deadlock, minimized.

The reference suite's silent failure mode: SPMD ranks disagreeing on
which collective comes next. Rank 0 enters the allreduce while every
other rank enters the ring shift — each side waits forever for peers
that went elsewhere, and the job hangs with no error (the mis-ordered
``MPI_Send/Recv`` deadlock, statically visible).

Lines carrying ``EXPECT: <rule>`` markers are the golden findings
tests/test_analysis.py asserts, line-exact.
"""

import jax
from jax import lax


def rank_branched_deadlock(comm, x):
    if jax.process_index() == 0:  # EXPECT: collective-divergence
        y = comm.allreduce(x)
    else:
        y = comm.sendrecv_ring(x)
    return y


def early_return_skips(comm, x):
    me = lax.axis_index("x")
    if me == 0:  # EXPECT: collective-divergence
        return x
    return comm.allreduce(x)


def loop_count_diverges(comm, x):
    r = jax.process_index()
    for _ in range(r):  # EXPECT: collective-divergence
        x = comm.sendrecv_ring(x)
    return x
