// Native sweep driver — the C++ face of the benchmark harness.
//
// The reference's batch runner is a bash script that executes each
// configuration, tees a log, and greps a SUCCESS/FAILURE summary
// (concurency/run.sh:4-18); its build harness registers binaries as
// CTest cases (src/CMakeLists.txt:39-50). This driver is both, in one
// native tool: it runs benchmark commands (each a framework app), then
// parses the shared JSONL run log (harness/runlog.py format) and exits
// 0 iff at least one result record exists and none failed — usable as
// the single test entry point from any CI, no Python wrapper needed.
// When --run commands are given the log is truncated first, so each
// sweep's verdict covers exactly that sweep's records.
//
// Usage:
//   hpcpat-sweep --log run.jsonl [--] CMD...   # run CMD (one per --run)
//   hpcpat-sweep --log run.jsonl               # parse/summarize only
// Each --run argument is executed via the shell, in order, before the
// log is parsed. Exit: 0 all SUCCESS, 1 any FAILURE or a command error.

#include <sys/wait.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <string>
#include <vector>

namespace {

// Minimal JSONL scan: a result record is a line containing
// "kind": "result"; its verdict is the value of "success". This parses
// exactly what runlog.py emits (flat JSON objects, one per line).
bool line_has(const std::string& line, const char* key, const char* value) {
  std::string pat = std::string("\"") + key + "\": " + value;
  if (line.find(pat) != std::string::npos) return true;
  pat = std::string("\"") + key + "\":" + value;  // no-space variant
  return line.find(pat) != std::string::npos;
}

bool line_has_str(const std::string& line, const char* key, const char* value) {
  std::string pat = std::string("\"") + key + "\": \"" + value + "\"";
  if (line.find(pat) != std::string::npos) return true;
  pat = std::string("\"") + key + "\":\"" + value + "\"";
  return line.find(pat) != std::string::npos;
}

}  // namespace

int main(int argc, char** argv) {
  std::string log_path;
  std::vector<std::string> commands;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "--log") == 0 && i + 1 < argc) {
      log_path = argv[++i];
    } else if (std::strcmp(argv[i], "--run") == 0 && i + 1 < argc) {
      commands.emplace_back(argv[++i]);
    } else if (std::strcmp(argv[i], "--help") == 0) {
      std::printf(
          "usage: %s --log FILE [--run CMD]...\n"
          "runs each CMD, then summarizes FILE (JSONL run log): exit 0 iff "
          "at least one result record exists and every one has "
          "\"success\": true\n",
          argv[0]);
      return 0;
    } else {
      std::fprintf(stderr, "unknown argument: %s (see --help)\n", argv[i]);
      return 2;
    }
  }
  if (log_path.empty()) {
    std::fprintf(stderr, "--log FILE is required\n");
    return 2;
  }

  bool ran_ok = true;
  if (!commands.empty()) {
    // Fresh log per sweep: apps opened with --log-append would otherwise
    // count stale records from a previous run, and apps that truncate
    // would silently drop earlier commands' FAILURE records.
    std::ofstream(log_path, std::ios::trunc);
  }
  for (const auto& cmd : commands) {
    std::printf("=== %s ===\n", cmd.c_str());
    std::fflush(stdout);
    int rc = std::system(cmd.c_str());
    if (rc != 0) {
      int code = WIFEXITED(rc) ? WEXITSTATUS(rc) : -1;
      std::printf("command exited with %d\n", code);
      ran_ok = false;  // still parse the log: the verdict lines matter
    }
  }

  std::ifstream in(log_path);
  if (!in) {
    std::fprintf(stderr, "cannot open log %s\n", log_path.c_str());
    return 2;
  }
  long ok = 0, bad = 0;
  std::string line;
  while (std::getline(in, line)) {
    if (!line_has_str(line, "kind", "result")) continue;
    if (line_has(line, "success", "true")) {
      ++ok;
    } else if (line_has(line, "success", "false")) {
      ++bad;
    }
  }
  // the grep-able contract of run.sh:17-18
  std::printf("SUCCESS count: %ld\n", ok);
  std::printf("FAILURE count: %ld\n", bad);
  return (bad == 0 && ran_ok && ok > 0) ? 0 : 1;
}
