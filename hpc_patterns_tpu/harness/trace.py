"""Flight recorder: per-event trace timeline with Chrome-trace export.

The metrics registry (harness/metrics.py) aggregates phase times into
fixed-bucket histograms — a snapshot can say the admission bubble is
12% of the run, but not WHICH decode chunk it landed on or whether a
recompile caused it. This module is the next observability rung: a
bounded ring-buffer recorder of individual timestamped events, ordered
in time, with compile and memory causes attached — the per-event
timeline that overlap attribution needs (PAPERS.md: stream-aware
message passing analyzes overlap from event timelines, not summary
statistics).

Event sources, all zero-cost when disabled:

- **spans** — every ``Metrics.span()`` begin/end feeds the recorder
  when one is installed (the existing instrumentation points become
  timeline tracks for free); nesting paths and attrs ride along.
- **device markers** — dispatch vs. completion instants from the
  serving engine's chunk loop (``ContinuousBatcher._dispatch_chunk`` /
  ``_resolve_pending``) and the eager ``Communicator`` collectives, so
  host bubbles are visually separable from device time: the window
  between a dispatch marker and its completion is drawn as a slice on
  a synthetic "device" track.
- **compile events** — a process-wide ``jax.monitoring`` duration
  listener (``/jax/core/compile/backend_compile_duration``) plus
  explicit :func:`compile_watch` / :func:`instrument_jit` hooks at the
  jit entry points (models/decode.py, models/serving.py,
  models/train.py) that attach the FUNCTION NAME and triggering arg
  shapes a bare backend event cannot know. ``serving.prefill_cache_
  size()`` consumes the same :func:`jit_cache_size` probe.
- **memory samples** — per-device live-buffer bytes via
  ``jax.live_arrays()`` at span boundaries (throttled), plus
  compiled-executable ``memory_analysis()`` peaks where the backend
  supports it (:func:`record_executable_memory`).

The ring buffer is bounded (``capacity`` events, oldest evicted), so a
long serving run records its most recent window instead of growing
without bound; the export pass re-balances B/E pairs across the
eviction edge so the JSON is always loadable.

Export is ``chrome://tracing`` JSON (Perfetto-loadable): spans as B/E
pairs on per-thread tracks, device windows and compiles as complete
(X) slices on their own tracks, memory as Counter events. Two routes:

- live: ``TraceRecorder.export(path)`` (serve_app ``--trace-out``);
- offline: the recorder's snapshot lands as one ``kind=trace`` RunLog
  record (apps/common.run_instrumented, under ``--trace --log``), and
  ``python -m hpc_patterns_tpu.harness.trace run.jsonl -o out.json``
  rebuilds the Chrome JSON from it; ``harness.report`` summarizes the
  same records;
- distributed: a traced child of apps/launch.py also writes the
  snapshot to the launcher-provided ``HPCPAT_TRACE_DIR``
  (:func:`write_rank_snapshot`; stamped with process identity, dual
  clock anchors, and barrier sync anchors), and harness/collect.py
  merges every rank's ring into ONE clock-aligned timeline with
  cross-rank skew/straggler rollups — rung 4 of the ladder.

Like metrics.py, this module is jax-free at import time: jax is only
touched inside enabled-path helpers (memory sampling, the monitoring
listener), so the disabled path costs one module-global None check.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

from hpc_patterns_tpu.harness import metrics as metricslib

# Synthetic track ids for events that are not host-thread work; real
# thread ids are far below this range on Linux (pids) and far above on
# macOS — collisions only relabel a track, never corrupt events.
# Device windows get SUBTRACKS (TID_DEVICE + track): Chrome-trace sync
# slices on one tid must nest properly, and overlapped admissions are
# concurrent with the decode chunk BY DESIGN — each admission slot
# renders on its own subtrack so overlapping windows stay valid.
TID_DEVICE = 1 << 20
TID_COMPILE = 1 << 21
TID_COUNTER = (1 << 21) + 1
# Request lifecycle lanes (round 18, harness/reqtrace.py): one
# subtrack PER REQUEST (TID_REQUEST + seq_id), each tiled wall-to-wall
# with that request's lifecycle segments — the Perfetto view of the
# coverage invariant, threaded by flow arrows into the migration/
# device windows at merge time (harness/collect.py).
TID_REQUEST = 1 << 22

# The single declared source of device-SUBTRACK bands (offsets added
# to TID_DEVICE): ``name -> (base, count)``, half-open width. Every
# module that owns a band unpacks it with :func:`track_band` instead
# of hand-picking integers — contractlint's ``track-band-collision``
# flags literal ``*_TRACK_BASE`` assignments and out-of-band
# ``track=`` literals, the same registry discipline pallaslint
# applies to collective ids. Bands: the decode chunk itself, the
# overlapped-admission slots (one per admit row), the KV-migration
# lanes (serving_plane/service.py), the warm spin-up lanes
# (serving_plane/autoscaler.py), and the host<->HBM residency lanes
# (memory/residency.py).
TRACK_BANDS: dict[str, tuple[int, int]] = {
    "decode": (0, 1),
    "admit": (1, 63),
    "migration": (64, 8),
    "spinup": (72, 8),
    "residency": (80, 8),
}


def track_band(name: str) -> tuple[int, int]:
    """``(base, count)`` for a declared subtrack band; the ONLY
    sanctioned way for a module to learn its band's offsets."""
    return TRACK_BANDS[name]


def _track_label(tid: int) -> str:
    if tid == TID_COMPILE:
        return "compile"
    if tid == TID_COUNTER:
        return "memory"
    if tid == TID_DEVICE:
        return "device (dispatch→completion)"
    if TID_DEVICE < tid < TID_COMPILE:
        track = tid - TID_DEVICE
        for name, (base, count) in TRACK_BANDS.items():
            if base <= track < base + count:
                # admit keeps its historic "slot" wording (slot N
                # rides subtrack N+1; track 0 is the decode chunk)
                if name == "admit":
                    return f"device (admit slot {track - base})"
                return f"device ({name} lane {track - base})"
        return f"device (subtrack {track})"
    if tid >= TID_REQUEST:
        return f"request {tid - TID_REQUEST}"
    return f"host thread {tid}"

DEFAULT_CAPACITY = 16384


class TraceRecorder:
    """Bounded ring-buffer event recorder.

    Events are compact tuples ``(ph, cat, name, ts, tid, dur, args)``:
    ``ph`` is the Chrome phase (B/E/i/X/C), ``cat`` the event kind
    (span/device/compile/counter), ``ts`` a ``time.perf_counter``
    stamp, ``dur`` only for X slices. ``t0_wall``/``t0_mono`` anchor
    the monotonic stamps to wall time so exports can be correlated
    with log timestamps.
    """

    def __init__(self, *, enabled: bool = True,
                 capacity: int = DEFAULT_CAPACITY,
                 mem_interval_s: float = 0.05):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self.n_events = 0  # total recorded, incl. evicted
        self.t0_wall = time.time()
        self.t0_mono = time.perf_counter()
        self.mem_interval_s = mem_interval_s
        # first sample one interval after construction, not at t=0
        self._last_mem_sample = self.t0_mono
        self._lock = threading.Lock()
        # cross-rank alignment anchors: monotonic stamps taken right
        # after a moment all ranks agree is (near-)simultaneous — a
        # barrier exit (apps/common.make_communicator records one).
        # They survive ring eviction like the rollup counters.
        self.sync_anchors: list[dict[str, Any]] = []
        # rollup counters that survive ring eviction (the snapshot's
        # summary must not shrink when old events fall off the ring)
        self.compile_count = 0
        self.compile_total_s = 0.0
        self.peak_live_bytes = 0

    # -- primitive ---------------------------------------------------------

    def _push(self, ph: str, cat: str, name: str, ts: float, tid: int,
              dur: float | None = None,
              args: dict[str, Any] | None = None) -> None:
        self.events.append((ph, cat, name, ts, tid, dur, args))
        self.n_events += 1

    # -- span feed (installed as metrics._trace_sink) ----------------------

    def span_begin(self, path: str, attrs: dict[str, Any],
                   ts: float | None = None) -> None:
        self._push("B", "span", path,
                   time.perf_counter() if ts is None else ts,
                   threading.get_ident(),
                   args=dict(attrs) if attrs else None)

    def span_end(self, path: str, ts: float | None = None) -> None:
        self._push("E", "span", path,
                   time.perf_counter() if ts is None else ts,
                   threading.get_ident())
        self.maybe_sample_memory()

    # -- device markers ----------------------------------------------------

    def mark_dispatch(self, name: str,
                      args: dict[str, Any] | None = None,
                      track: int = 0) -> float:
        """Instant marker: device work for ``name`` was enqueued NOW
        (async dispatch — the device may start later). Returns the
        stamp to hand to :meth:`mark_complete`. ``track`` selects a
        device SUBTRACK (``TID_DEVICE + track``): windows that may
        overlap in time — an admission prefill behind an in-flight
        decode chunk — must live on different subtracks, because
        Chrome-trace sync slices on one track must nest."""
        ts = time.perf_counter()
        self._push("i", "device", f"{name}.dispatch", ts,
                   TID_DEVICE + track, args=args)
        return ts

    def mark_complete(self, name: str, t_dispatch: float,
                      args: dict[str, Any] | None = None,
                      track: int = 0) -> None:
        """Completion observed (a readback or block_until_ready
        resolved): draw the dispatch→completion window as one slice on
        the device (sub)track. Host gaps BETWEEN these slices are
        bubbles. Pass the same ``track`` as the dispatch."""
        ts = time.perf_counter()
        self._push("X", "device", name, t_dispatch, TID_DEVICE + track,
                   dur=ts - t_dispatch, args=args)

    def mark_request_segment(self, seq_id: int, kind: str, t0: float,
                             t1: float,
                             args: dict[str, Any] | None = None
                             ) -> None:
        """One finished lifecycle segment on a request's own lane
        (``TID_REQUEST + seq_id``) — reqtrace mirrors a request's
        whole history here at finish, so the per-request tiling is a
        first-class Perfetto track next to the device windows it
        explains. Retrospective X slices: both stamps are ordinary
        host perf_counter instants already taken by the stamp sites
        (no clock read, no readback — this runs inside the serving
        loop's finish path)."""
        self._push("X", "request", kind, t0,
                   TID_REQUEST + int(seq_id), dur=t1 - t0,
                   args={**(args or {}), "seq_id": int(seq_id)})

    def mark_sync(self, name: str) -> float:
        """Record a cross-rank sync anchor: call this immediately after
        a global barrier returns. All ranks exit a barrier within a
        small window (bounded by its release propagation), so their
        anchors of the same name+index are treated as simultaneous by
        the cross-rank merge (harness/collect.py), tightening clock
        alignment beyond what wall-clock anchors give on hosts with
        skewed clocks. Returns the monotonic stamp."""
        ts = time.perf_counter()
        self.sync_anchors.append({"name": name, "mono": ts})
        return ts

    # -- compile events ----------------------------------------------------

    def compile_event(self, name: str, dur_s: float,
                      args: dict[str, Any] | None = None,
                      t_end: float | None = None,
                      count: bool = True) -> None:
        """One compilation: an X slice of ``dur_s`` on the compile
        track ending at ``t_end`` (now by default). ``args`` carries
        whatever the hook knows — function name, triggering arg shapes
        (:func:`compile_watch`) or the raw jax.monitoring event name.

        ``count=False`` records the slice WITHOUT bumping the
        ``compile.count/total_s`` rollups: one real compilation is
        seen twice — by the jax.monitoring backend listener (pure XLA
        time, the canonical counter) AND by the named compile_watch /
        instrument_jit hook (name + shapes, call wall time) — and the
        hooks pass count=False so the rollup counts each compile
        once."""
        t_end = time.perf_counter() if t_end is None else t_end
        self._push("X", "compile", name, t_end - dur_s, TID_COMPILE,
                   dur=dur_s, args=args)
        if count:
            self.compile_count += 1
            self.compile_total_s += dur_s

    # -- memory samples ----------------------------------------------------

    def counter(self, name: str, values: dict[str, float]) -> None:
        self._push("C", "counter", name, time.perf_counter(),
                   TID_COUNTER, args=dict(values))

    def sample_memory(self) -> dict[str, float] | None:
        """Per-device live-buffer bytes via ``jax.live_arrays()``,
        recorded as a Counter event. Multi-device arrays attribute
        ``nbytes / n_devices`` to each holder. Returns the sample (or
        None when jax is unavailable / not yet imported — sampling
        must never be the thing that first initializes a backend)."""
        if "jax" not in sys.modules:
            return None
        try:
            import jax

            per_dev: dict[str, float] = {}
            total = 0
            for arr in jax.live_arrays():
                nbytes = int(getattr(arr, "nbytes", 0))
                total += nbytes
                devs = tuple(arr.devices())
                if not devs:
                    continue
                share = nbytes / len(devs)
                for d in devs:
                    key = f"live_bytes.{d}"
                    per_dev[key] = per_dev.get(key, 0.0) + share
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            return None
        sample = {"live_bytes": float(total), **per_dev}
        self.counter("mem", sample)
        self.peak_live_bytes = max(self.peak_live_bytes, total)
        return sample

    def maybe_sample_memory(self) -> None:
        """Throttled :meth:`sample_memory` — called at span boundaries,
        so at most one ``live_arrays()`` walk per ``mem_interval_s``."""
        now = time.perf_counter()
        if now - self._last_mem_sample < self.mem_interval_s:
            return
        with self._lock:
            if now - self._last_mem_sample < self.mem_interval_s:
                return
            self._last_mem_sample = now
        self.sample_memory()

    # -- snapshot / export -------------------------------------------------

    def _balanced_events(self) -> list[tuple]:
        """Buffer contents with span B/E pairs re-balanced across the
        ring's eviction edge: an E whose B was evicted is dropped, a B
        still open at snapshot time gets a synthesized E at the last
        stamp — so every exported B has a matching E, always."""
        events = list(self.events)
        out: list[tuple] = []
        stacks: dict[int, list[str]] = {}
        max_ts = self.t0_mono
        for ev in events:
            ph, cat, name, ts, tid = ev[0], ev[1], ev[2], ev[3], ev[4]
            max_ts = max(max_ts, ts + (ev[5] or 0.0))
            if ph == "B":
                stacks.setdefault(tid, []).append(name)
            elif ph == "E":
                stack = stacks.get(tid)
                if not stack or stack[-1] != name:
                    continue  # orphan: its B fell off the ring
                stack.pop()
            out.append(ev)
        for tid, stack in stacks.items():
            for name in reversed(stack):
                out.append(("E", "span", name, max_ts, tid, None, None))
        return out

    def snapshot(self) -> dict[str, Any]:
        """JSON-able recorder state — the payload of the ``kind=trace``
        RunLog record AND of the per-rank handoff file
        (:func:`write_rank_snapshot`). ``events`` is the balanced ring
        contents in compact list form; the summary fields survive
        eviction. ``clock`` carries TWO monotonic↔wall anchor pairs
        (construction and snapshot time) so the cross-rank merge can
        estimate each rank's clock offset and bound its drift;
        ``process`` stamps whose timeline this is (launcher env
        protocol first, live jax runtime second — see
        ``topology.process_env_info``); ``collectives`` carries the
        rank's collective-schedule hash chain for the merge-time
        desync check."""
        events = self._balanced_events()
        by_cat: dict[str, int] = {}
        for ev in events:
            by_cat[ev[1]] = by_cat.get(ev[1], 0) + 1
        process_id, num_processes, slice_id = _process_info()
        # the collective schedule hash chain (analysis/runtime.py):
        # every eager Communicator collective and traced timing rep
        # fingerprinted as (op, seq, shape, dtype, axis). The merge
        # (harness/collect.py) cross-checks the chains rank-against-
        # rank — equal digests PROVE the SPMD schedules matched; on
        # mismatch the first divergent (rank, op, seq) is named.
        # analysis.runtime is import-light (stdlib only), so this
        # costs no jax import.
        try:
            from hpc_patterns_tpu.analysis import runtime as _runtimelib

            collectives = _runtimelib.collective_schedule().snapshot()
        except Exception:  # noqa: BLE001 — the stamp is best-effort
            collectives = None
        return {
            "clock": {"wall0": self.t0_wall, "mono0": self.t0_mono,
                      "wall1": time.time(),
                      "mono1": time.perf_counter()},
            "process": {"process_id": process_id,
                        "num_processes": num_processes,
                        "slice_id": slice_id},
            "sync": [dict(a) for a in self.sync_anchors],
            "capacity": self.capacity,
            "n_events": self.n_events,
            "n_dropped": max(0, self.n_events - len(self.events)),
            "by_cat": by_cat,
            "compile": {"count": self.compile_count,
                        "total_s": self.compile_total_s},
            "mem": {"peak_live_bytes": self.peak_live_bytes},
            "collectives": collectives,
            "events": [list(ev) for ev in events],
        }

    def to_chrome(self) -> dict[str, Any]:
        return chrome_from_snapshots([self.snapshot()])

    def export(self, path: str | Path) -> Path:
        """Write the Chrome-trace JSON (Perfetto: open → this file)."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with path.open("w") as f:
            json.dump(self.to_chrome(), f)
        return path


def _process_info() -> tuple[int, int, int]:
    """(process_id, num_processes, slice_id) via topology's env-first
    resolution; (0, 1, 0) when topology/jax are unavailable — a
    snapshot must never fail for lack of a distributed runtime."""
    try:
        from hpc_patterns_tpu import topology

        return topology.process_env_info()
    except Exception:  # noqa: BLE001 — telemetry stamp, best-effort
        return 0, 1, 0


def rank_snapshot_path(trace_dir: str | Path, process_id: int) -> Path:
    """The per-rank handoff file for ``process_id`` under the
    launcher-provided ``HPCPAT_TRACE_DIR`` — one JSON object per file,
    the ``kind=trace`` snapshot verbatim. Width-padded so a shell glob
    lists ranks in order."""
    return Path(trace_dir) / f"rank{process_id:05d}.trace.json"


def write_rank_snapshot(rec: TraceRecorder, trace_dir: str | Path,
                        snapshot: dict[str, Any] | None = None
                        ) -> Path | None:
    """Write ``rec``'s snapshot to its per-rank file under
    ``trace_dir`` (the ``HPCPAT_TRACE_DIR`` handoff: the launcher sets
    the env var, every traced child writes here at exit, the launcher
    collects and merges — harness/collect.py). Pass ``snapshot`` when
    one was already taken for another sink (the ``--log`` record), so
    the rank file and the log record carry the SAME events and clock
    anchors. Returns the path, or None when the write failed (a full
    disk must not turn a successful run into a failure; the launcher
    reports missing rank files)."""
    snap = dict(rec.snapshot() if snapshot is None else snapshot)
    snap["kind"] = "trace"
    path = rank_snapshot_path(trace_dir, snap["process"]["process_id"])
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        with path.open("w") as f:
            json.dump(snap, f)
    except OSError as e:
        print(f"WARNING: could not write per-rank trace {path}: {e}",
              file=sys.stderr)
        return None
    return path


def chrome_from_snapshots(snaps: list[dict[str, Any]],
                          pid: int = 1) -> dict[str, Any]:
    """Chrome-trace JSON from one or more ``kind=trace`` snapshots.

    Spans become B/E pairs on per-thread tracks, device windows and
    compiles X slices on their synthetic tracks, memory samples Counter
    events. Timestamps are microseconds since the FIRST snapshot's
    monotonic anchor; multiple snapshots from one process merge on a
    shared clock (their anchors differ only by configure time)."""
    if not snaps:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # anchor at the earliest event START (an X slice recorded at its
    # end can begin before the recorder's construction stamp — e.g. a
    # compile already in flight when tracing was enabled); Chrome
    # timestamps must be nonnegative
    mono0 = min(float(s["clock"]["mono0"]) for s in snaps)
    for s in snaps:
        for ev in s.get("events", []):
            mono0 = min(mono0, float(ev[3]))
    trace_events: list[dict[str, Any]] = []
    tids_seen: set[int] = set()
    for snap in snaps:
        for ev in snap.get("events", []):
            ph, cat, name, ts, tid, dur, args = ev
            tids_seen.add(int(tid))
            rec: dict[str, Any] = {
                "name": name, "cat": cat, "ph": ph,
                "ts": (float(ts) - mono0) * 1e6,
                "pid": pid, "tid": int(tid),
            }
            if ph == "X":
                rec["dur"] = (dur or 0.0) * 1e6
            if ph == "i":
                rec["s"] = "t"  # thread-scoped instant arrow
            if ph == "C":
                rec["args"] = {k: v for k, v in (args or {}).items()}
            elif args:
                rec["args"] = {k: str(v) for k, v in args.items()}
            trace_events.append(rec)
    meta = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": "hpc_patterns_tpu"}},
    ]
    for tid in sorted(tids_seen):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": _track_label(tid)}})
    return {"traceEvents": meta + trace_events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# process-wide recorder + the metrics-span sink hookup
# ---------------------------------------------------------------------------

_recorder: TraceRecorder | None = None


def get_tracer() -> TraceRecorder | None:
    return _recorder


def active() -> TraceRecorder | None:
    """The enabled recorder, or None — THE fast-path check every hook
    makes (one module-global read; the disabled path never allocates)."""
    rec = _recorder
    if rec is not None and rec.enabled:
        return rec
    return None


def configure(*, enabled: bool = False,
              capacity: int = DEFAULT_CAPACITY,
              mem_interval_s: float = 0.05) -> TraceRecorder:
    """Install a FRESH process-wide recorder (apps call this once per
    run via ``--trace``; run_instrumented mirrors metrics.configure).
    Enabling also installs the recorder as the metrics-span sink and
    registers the jax.monitoring compile listener; disabling detaches
    the sink so ``Metrics.span()`` returns to its no-op fast path."""
    global _recorder
    _recorder = TraceRecorder(enabled=enabled, capacity=capacity,
                              mem_interval_s=mem_interval_s)
    metricslib._trace_sink = _recorder if enabled else None
    # fresh recorder = fresh collective schedule chain: every rank of a
    # launch configures at app start, so the chains all start from the
    # same genesis and index the run's collectives identically
    try:
        from hpc_patterns_tpu.analysis import runtime as _runtimelib

        _runtimelib.reset_collective_schedule()
    except Exception:  # noqa: BLE001
        pass
    if enabled:
        install_monitoring_listener()
    return _recorder


# ---------------------------------------------------------------------------
# compile watchers
# ---------------------------------------------------------------------------

_monitoring_installed = False

# the one backend-compile event gated on for counting; the other
# /jax/core/compile/* phases (jaxpr trace, MLIR lowering) would triple-
# count a single compilation
_BACKEND_COMPILE_EVENT = "backend_compile"


def _monitoring_listener(event: str, duration: float, **kw) -> None:
    rec = active()
    if rec is None or _BACKEND_COMPILE_EVENT not in event:
        return
    rec.compile_event("xla.backend_compile", float(duration),
                      args={"event": event})


def install_monitoring_listener() -> bool:
    """Register the ``jax.monitoring`` duration listener exactly once
    per process. The listener itself checks :func:`active`, so leaving
    it registered when tracing is off costs one None check per compile
    — registration is deliberately never undone (jax's unregister API
    is private and the listener list is append-only in practice)."""
    global _monitoring_installed
    if _monitoring_installed:
        return True
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _monitoring_listener)
    except Exception:  # noqa: BLE001 — tracing is best-effort
        return False
    _monitoring_installed = True
    return True


def jit_cache_size(fn, *, strict: bool = False) -> int:
    """Compiled-variant count of a jitted callable. THE compile-count
    probe: compile_watch diffs it around calls, and
    ``serving.prefill_cache_size()`` is its longest-standing consumer
    (the bucket-ladder bound observable).

    Default (telemetry) mode returns 0 when the wrapper exposes no
    ``_cache_size`` — a missing probe must not crash a traced run.
    ``strict=True`` raises instead: callers whose CLAIM is the count
    (the bucket-ladder assertions gate on it, and 0 is exactly the
    value they would read as success) must fail loudly if a jax
    upgrade renames the private probe."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        inner = getattr(fn, "__wrapped__", None)
        probe = getattr(inner, "_cache_size", None)
    if probe is None:
        if strict:
            raise AttributeError(
                f"{fn!r} exposes no _cache_size probe (jax private "
                "API moved?) — the compile-count observable would "
                "silently read 0")
        return 0
    if strict:
        return int(probe())
    try:
        return int(probe())
    except Exception:  # noqa: BLE001
        return 0


_NULL = contextlib.nullcontext()


class _CompileWatch:
    """Context manager diffing a jitted fn's cache size around a call:
    growth means THIS call compiled, and the call's wall time is the
    compile-dominated cost the event records (the backend listener has
    the pure-XLA time; this hook contributes function name + shapes)."""

    __slots__ = ("rec", "name", "fn", "attrs", "n0", "t0")

    def __init__(self, rec: TraceRecorder, name: str, fn,
                 attrs: dict[str, Any]):
        self.rec, self.name, self.fn, self.attrs = rec, name, fn, attrs

    def __enter__(self):
        self.n0 = jit_cache_size(self.fn)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        grew = jit_cache_size(self.fn) - self.n0
        if grew > 0:
            # count=False: the backend listener already counted this
            # compilation; the hook's job is the name + shapes
            self.rec.compile_event(self.name, dt, count=False,
                                   args={**self.attrs,
                                         "new_variants": grew})
        return False


def compile_watch(name: str, fn, **attrs):
    """``with compile_watch("serving._prefill_one", _prefill_one,
    padded_len=32): _prefill_one(...)`` — records a compile event iff
    the call grew ``fn``'s jit cache. The disabled path returns a
    shared nullcontext (nothing allocated per call)."""
    rec = active()
    if rec is None:
        return _NULL
    return _CompileWatch(rec, name, fn, attrs)


def _shape_strs(args) -> list[str]:
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            out.append(f"{dtype}{list(shape)}")
    return out


def record_executable_memory(name: str, compiled) -> dict | None:
    """Compiled-executable memory peaks (``memory_analysis()``) as a
    Counter event, where the backend supports it (TPU reports real HBM
    peaks; CPU reports code/temp sizes; some backends raise — then
    this records nothing and returns None)."""
    rec = active()
    if rec is None:
        return None
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    vals = {}
    for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "alias_size_in_bytes",
                 "generated_code_size_in_bytes",
                 "peak_memory_in_bytes"):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and v is not None:
            vals[attr] = float(v)
    if not vals:
        return None
    rec.counter(f"exec_mem.{name}", vals)
    return vals


def instrument_jit(fn, name: str, *, exec_memory: bool = False):
    """Wrap a jitted callable so every call that grows its jit cache
    records a compile event (name, triggering arg shapes, wall time;
    ``count=False`` — the backend listener is the canonical counter).
    With no recorder active the wrapper is a single global read +
    passthrough call.

    ``exec_memory=True`` additionally captures the executable's
    ``memory_analysis()`` peaks on each fresh-compile call via an AOT
    ``lower().compile()``. That AOT pass is a FULL second backend
    compilation (measured: the jit call cache does not serve it), so
    it is opt-in and only sane for functions whose compile is cheap
    relative to the insight; big entry points (the train step) leave
    it off and use :func:`record_executable_memory` at an explicit AOT
    site instead."""

    def wrapped(*args, **kwargs):
        rec = active()
        if rec is None:
            return fn(*args, **kwargs)
        n0 = jit_cache_size(fn)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if jit_cache_size(fn) > n0:
            rec.compile_event(name, dt, count=False,
                              args={"shapes": _shape_strs(args)})
            if exec_memory:
                try:
                    record_executable_memory(
                        name, fn.lower(*args, **kwargs).compile())
                except Exception:  # noqa: BLE001 — donated args may
                    pass           # be consumed; peaks are extras
        return out

    wrapped.__wrapped__ = fn
    wrapped.__name__ = getattr(fn, "__name__", name)
    return wrapped


# ---------------------------------------------------------------------------
# CLI: kind=trace RunLog records -> Chrome-trace JSON
# ---------------------------------------------------------------------------

def load_trace_snapshots(paths) -> list[dict[str, Any]]:
    """Every ``kind=trace`` record across the given runlog JSONL files
    (unparseable lines skipped, same tolerance as harness.report).
    Each record is annotated with its ``_source`` path so the export
    can keep records from different files on different pid lanes."""
    snaps = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "trace":
                    rec.setdefault("_source", str(path))
                    snaps.append(rec)
    return snaps


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Export kind=trace runlog records as Chrome-trace "
                    "JSON (load in Perfetto / chrome://tracing)")
    p.add_argument("logs", nargs="+",
                   help="runlog JSONL file(s) from a --trace --log run")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: <first log>.trace.json)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        snaps = load_trace_snapshots(args.logs)
    except OSError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    if not snaps:
        print("ERROR: no kind=trace records in input (run apps with "
              "--trace --log to record them)", file=sys.stderr)
        return 2
    out = Path(args.out) if args.out else Path(
        args.logs[0]).with_suffix(".trace.json")
    # the merge path (harness/collect.py) assigns one pid lane per
    # source process/file with process_name metadata — records from
    # different runlog files no longer collapse onto a single lane
    from hpc_patterns_tpu.harness import collect as collectlib

    chrome = collectlib.merge(snaps)["chrome"]
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w") as f:
        json.dump(chrome, f)
    n_ev = len(chrome["traceEvents"])
    n_lanes = len({e["pid"] for e in chrome["traceEvents"]})
    n_comp = sum(s.get("compile", {}).get("count", 0) for s in snaps)
    dropped = sum(s.get("n_dropped", 0) for s in snaps)
    print(f"{out}: {n_ev} trace events from {len(snaps)} snapshot(s) "
          f"on {n_lanes} pid lane(s) ({n_comp} compiles, {dropped} "
          f"evicted by the ring) — open in Perfetto (ui.perfetto.dev) "
          f"or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
