"""Process-wide metrics registry + span tracing (SURVEY.md §5).

The reference's observability is a tee'd text log grepped for
SUCCESS/FAILURE (concurency/run.sh:15-18); RunLog upgraded that to
JSONL, but each subsystem invented its own ad-hoc records. This module
is the shared schema underneath them all:

- **counters** (monotonic totals), **gauges** (last-value with min/max
  tracking), and **histograms** with FIXED log-spaced buckets, so any
  percentile computed from a snapshot equals the one computed live —
  the snapshot IS the histogram (quantized to bucket resolution) and
  percentiles survive JSON round-trips through RunLog.
- **spans**: ``with span("measure.timed"): ...`` measures a wall-time
  phase, nests (a thread-local stack builds ``outer/inner`` paths),
  records into a ``span.<path>`` histogram, and — when profiling is on
  — mirrors into ``jax.profiler.TraceAnnotation`` so XProf traces and
  the JSONL snapshot attribute time to the same named phases.

Disabled by default with a no-op fast path: ``get_metrics()`` returns a
disabled registry whose instruments are a shared no-op singleton and
whose ``span()`` is a reusable ``nullcontext`` — callers can
instrument unconditionally and tier-1 timing numbers are untouched.
Apps enable it per run via ``--metrics`` (apps/common.run_instrumented
installs a fresh registry and appends one ``kind=metrics`` snapshot
record to the run log); ``python -m hpc_patterns_tpu.harness.report``
aggregates those records back into a per-phase summary table.

Deliberately jax-free at module level: the only jax touch is the lazy
TraceAnnotation import inside an enabled, mirroring span.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Any, Iterator

# Fixed bucket layout shared by every histogram (and by report.py's
# reconstruction): 4 log-spaced buckets per decade over 1e-9..1e3 —
# ns-scale kernel times through ks-scale sweeps at ~±33% resolution.
# Changing this invalidates checked-in snapshots; bump with care (the
# layout is embedded in every snapshot for forward compatibility).
LO_DECADE = -9
HI_DECADE = 3
PER_DECADE = 4
N_BUCKETS = (HI_DECADE - LO_DECADE) * PER_DECADE

BUCKET_LAYOUT = {
    "lo_decade": LO_DECADE,
    "hi_decade": HI_DECADE,
    "per_decade": PER_DECADE,
}


def bucket_index(value: float) -> int:
    """Bucket holding ``value``; out-of-range values clamp to the end
    buckets (their true extrema are preserved by min/max tracking)."""
    if value <= 0:
        return 0
    i = math.floor((math.log10(value) - LO_DECADE) * PER_DECADE)
    return min(max(i, 0), N_BUCKETS - 1)


def bucket_value(index: int) -> float:
    """Representative (geometric-midpoint) value of a bucket."""
    return 10.0 ** (LO_DECADE + (index + 0.5) / PER_DECADE)


class Histogram:
    """Sparse fixed-bucket histogram: counts per bucket plus exact
    count/sum/min/max. Everything needed to reproduce its percentiles
    is in :meth:`snapshot`, by construction."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        value = float(value)
        if not math.isfinite(value):
            # NaN has no bucket (floor(nan) raises) and inf would poison
            # sum; telemetry drops the sample rather than crash the run
            return
        i = bucket_index(value)
        self.counts[i] = self.counts.get(i, 0) + 1
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)

    def percentile(self, q: float) -> float:
        """Rank-based percentile at bucket resolution, clamped to the
        observed [min, max] so p0/p100 are exact. The last order
        statistic IS the tracked max — returning the bucket midpoint
        there undershot it whenever the max sat in the upper half of
        its log bucket (a real flake: a load-spiked rep set whose
        samples all share one bucket)."""
        if not self.count:
            return math.nan
        rank = max(1, math.ceil(q / 100.0 * self.count))
        if rank >= self.count:
            return self.max
        cum = 0
        for i in sorted(self.counts):
            cum += self.counts[i]
            if cum >= rank:
                return min(max(bucket_value(i), self.min), self.max)
        return self.max

    def merge(self, other: "Histogram") -> None:
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def snapshot(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            # JSON objects key by string; report.py converts back
            "counts": {str(i): c for i, c in sorted(self.counts.items())},
        }

    @classmethod
    def from_snapshot(cls, snap: dict[str, Any]) -> "Histogram":
        h = cls()
        h.counts = {int(i): int(c) for i, c in snap["counts"].items()}
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        h.min = math.inf if snap["min"] is None else float(snap["min"])
        h.max = -math.inf if snap["max"] is None else float(snap["max"])
        return h


def _finite_or_none(value: float) -> float | None:
    return value if math.isfinite(value) else None


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        self.value += n


class Gauge:
    """Last-value instrument that also tracks its min/max/n so a final
    snapshot still shows the excursion, not just the last sample."""

    __slots__ = ("last", "min", "max", "n")

    def __init__(self):
        self.last = math.nan
        self.min = math.inf
        self.max = -math.inf
        self.n = 0

    def set(self, value: float) -> None:
        value = float(value)
        self.last = value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.n += 1

    def snapshot(self) -> dict[str, Any]:
        # non-finite values (a diverged loss is NaN) become null: bare
        # NaN/Infinity tokens are invalid strict JSON and would make the
        # runlog line unparseable outside Python
        return {"last": _finite_or_none(self.last),
                "min": _finite_or_none(self.min),
                "max": _finite_or_none(self.max),
                "n": self.n}


class _Noop:
    """Shared do-nothing instrument: the disabled registry hands this
    out so instrumented code never branches on enablement itself."""

    __slots__ = ()

    def inc(self, n: int | float = 1) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


_NOOP = _Noop()
_NULL_SPAN = contextlib.nullcontext()

# Flight-recorder hookup (harness/trace.py installs itself here via
# trace.configure): when a sink is present, every span begin/end also
# lands as a timestamped event in the ring buffer — the histograms say
# HOW LONG a phase takes, the recorder says WHEN each instance ran.
# None (the default) keeps span() on the no-op fast path: the check is
# one module-global read, no import of trace.py, still jax-free.
_trace_sink = None


class Metrics:
    """One registry per process (installed by :func:`configure`).

    ``enabled=False`` is the no-op fast path: instruments are the
    shared no-op singleton, ``span()`` is a reusable nullcontext, and
    ``snapshot()`` is empty — zero records, zero timing overhead.
    ``mirror_traces`` makes spans annotate the active ``jax.profiler``
    trace even when recording is off (profiling without --metrics).
    """

    def __init__(self, *, enabled: bool = True,
                 mirror_traces: bool = False):
        self.enabled = enabled
        self.mirror_traces = mirror_traces
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- instruments -------------------------------------------------------

    def _get(self, table: dict, name: str, factory):
        inst = table.get(name)
        if inst is None:
            with self._lock:
                inst = table.setdefault(name, factory())
        return inst

    def counter(self, name: str) -> Counter | _Noop:
        if not self.enabled:
            return _NOOP
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge | _Noop:
        if not self.enabled:
            return _NOOP
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram | _Noop:
        if not self.enabled:
            return _NOOP
        return self._get(self._histograms, name, Histogram)

    # -- spans -------------------------------------------------------------

    def span(self, name: str, **attrs: Any):
        """Context manager timing a named phase. Nested spans build a
        ``/``-joined path per thread; the elapsed wall time lands in
        the ``span.<path>`` histogram. With ``mirror_traces``, the
        span body also runs under a ``jax.profiler.TraceAnnotation``
        of the same name, so XProf shows the identical phase tree.
        With a flight recorder installed (``--trace``), begin/end also
        land as ring-buffer events carrying the same path."""
        if not (self.enabled or self.mirror_traces
                or _trace_sink is not None):
            return _NULL_SPAN
        return self._span(name, attrs)

    @contextlib.contextmanager
    def _span(self, name: str, attrs: dict[str, Any]) -> Iterator[None]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(name)
        path = "/".join(stack)
        annotation = _NULL_SPAN
        if self.mirror_traces:
            try:
                from jax.profiler import TraceAnnotation

                annotation = TraceAnnotation(
                    path, **{k: str(v) for k, v in attrs.items()})
            except Exception:  # noqa: BLE001 — tracing is best-effort
                pass
        sink = _trace_sink
        t0 = time.perf_counter()
        if sink is not None:
            sink.span_begin(path, attrs, t0)
        try:
            with annotation:
                yield
        finally:
            t1 = time.perf_counter()
            stack.pop()
            if sink is not None:
                sink.span_end(path, t1)
            if self.enabled:
                self._get(self._histograms, f"span.{path}",
                          Histogram).observe(t1 - t0)

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-able registry state — the payload of the
        ``kind=metrics`` RunLog record. Empty sections are included so
        consumers never branch on key presence."""
        return {
            "counters": {k: v.value for k, v in
                         sorted(self._counters.items())},
            "gauges": {k: v.snapshot() for k, v in
                       sorted(self._gauges.items())},
            "histograms": {k: v.snapshot() for k, v in
                           sorted(self._histograms.items())},
            "bucket_layout": dict(BUCKET_LAYOUT),
        }


# the process-wide registry; disabled until an app (or test) configures
_registry = Metrics(enabled=False)


def get_metrics() -> Metrics:
    return _registry


def configure(*, enabled: bool = False,
              mirror_traces: bool = False) -> Metrics:
    """Install a FRESH process-wide registry (apps call this once per
    run, so repeated in-process main() invocations — the test suite's
    CTest analog — never leak metrics across runs)."""
    global _registry
    _registry = Metrics(enabled=enabled, mirror_traces=mirror_traces)
    return _registry


def span(name: str, **attrs: Any):
    """Module-level convenience: a span on the current registry."""
    return _registry.span(name, **attrs)
