"""Trainer app: the flagship transformer end-to-end on a mesh.

The framework's full-stack exercise — everything the other apps prove in
isolation, composed: mesh construction (topology), Megatron TP + dp/sp
batch sharding (models/sharding), ring attention over sp (parallel/),
the jitted+donated train step (models/train), min-of-reps timing
(harness), checkpoint/resume (utils/checkpoint).

Self-validating (§4 style): loss must be finite every step and decrease
over the run on the synthetic corpus; with --resume-check, the state is
checkpointed, restored, and one step from each is compared.

Reports steady-state step time and tokens/s (the model-level throughput
headline).
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness.cli import base_parser
from hpc_patterns_tpu.models import ATTENTION_IMPLS, TransformerConfig
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_batch,
    make_train_step,
    record_step_metrics,
)


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=0,
                   help="grouped-query attention KV heads (0 = MHA)")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--attention", default="full",
                   choices=list(ATTENTION_IMPLS))
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", default="split",
                   choices=["nothing", "attn", "dots", "dots_attn", "split"],
                   help="what remat saves (see TransformerConfig; "
                        "'split' = attention outside the remat region, "
                        "the MFU default; 'nothing' = max memory saving "
                        "for long context)")
    p.add_argument("--loss-chunk", type=int, default=0, metavar="C",
                   help="online-logsumexp cross-entropy over vocab "
                        "chunks of C (must divide --vocab): the "
                        "(B,T,V) f32 logits never materialize — the "
                        "long-context memory wall remover (0 = dense)")
    p.add_argument("--pos-embed", default="learned",
                   choices=["learned", "rope"],
                   help="positional scheme: learned table or rotary (RoPE)")
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--dcn-dp", action="store_true",
                   help="multi-slice placement: lay the dp axis ACROSS "
                        "TPU slices (DCN) and all other axes within one "
                        "slice (ICI) via topology.make_hybrid_mesh; "
                        "--dp must equal the slice count (-1 = auto, "
                        "which on a single slice degenerates to dp=1)")
    p.add_argument("--fsdp", type=int, default=1,
                   help="fully-sharded data parallelism (ZeRO-3): params/"
                        "grads/optimizer state shard over this many "
                        "ranks, batch shards over dp*fsdp")
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (1F1B schedule, "
                        "models/pp.py); layers must divide by it")
    p.add_argument("--microbatches", type=int, default=4,
                   help="microbatches per step for --pp (batch must "
                        "divide by microbatches*dp)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel axis (requires --n-experts)")
    p.add_argument("--n-experts", type=int, default=0,
                   help="MoE experts per layer (0 = dense MLP)")
    p.add_argument("--n-experts-top-k", type=int, default=1,
                   help="experts consulted per token (1 = Switch top-1; "
                        "k>=2 = normalized top-k gates, GShard style)")
    p.add_argument("--moe-dispatch", default="auto",
                   choices=["auto", "einsum", "scatter"],
                   help="routing dispatch: one-hot einsum (oracle form) "
                        "or stable-sort scatter (O(N+E*C) memory); auto "
                        "switches to scatter past ~16 MB of one-hots")
    p.add_argument("--mlp-impl", default="dense",
                   choices=["dense", "fused"],
                   help="dense-layer MLP: XLA einsums, or the Pallas "
                        "fused matmul-gelu-matmul kernel (the d_ff "
                        "activation never materializes in HBM)")
    p.add_argument("--drop-rate-every", type=int, default=10, metavar="N",
                   help="sample the MoE routing-drop telemetry every N "
                        "steps (0 = off). The diagnostic is a second "
                        "forward pass — at every step it would cost "
                        "~25-30%% wall clock, so it is sampled")
    p.add_argument("--prefetch", type=int, default=0, metavar="DEPTH",
                   help="stream fresh synthetic batches through the async "
                        "prefetch loader (0 = one static batch)")
    p.add_argument("--data", default=None, metavar="TOKENS.bin",
                   help="raw binary token file (uint16/uint32/int32, "
                        "--data-dtype) streamed via np.memmap instead of "
                        "synthetic batches; implies --prefetch 2 unless set")
    p.add_argument("--data-dtype", default="uint16",
                   choices=["uint16", "uint32", "int32"])
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--schedule", default="constant",
                   choices=["constant", "cosine"])
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--accum", type=int, default=1,
                   help="gradient-accumulation micro-steps per update "
                        "(batch must divide by it)")
    p.add_argument("--offload-opt", action="store_true",
                   help="park optimizer moments in host RAM "
                        "(pinned_host), streamed to HBM per step — "
                        "frees 2x the f32 param footprint of HBM "
                        "(TPU backend only)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--resume-check", action="store_true",
                   help="save+restore mid-run and verify identical losses")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens from a "
                        "prompt with the trained params (KV-cache decode, "
                        "models/decode.py) and validate them")
    return p


def _make_cli_optimizer(args, log):
    """Build the optimizer from --lr/--schedule/--warmup-steps (shared by
    the sharded and --pp paths). Returns None after logging the app's
    ERROR/FAILURE protocol on invalid schedule parameters."""
    from hpc_patterns_tpu.models.train import make_optimizer

    try:
        return make_optimizer(
            args.lr, schedule=args.schedule,
            warmup_steps=args.warmup_steps, total_steps=args.steps,
        )
    except ValueError as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return None


def _train_loop(args, log, cfg, mesh, params, opt_state, step_fn, *,
                name, result_extra):
    """The shared training loop + self-validation: prefetch (optional),
    timed steps, finite/decreasing-loss checks, --resume-check, verdict.
    Both the sharded-train path and the --pp 1F1B path run through here
    so the loss/verdict semantics cannot drift between them."""
    tokens = make_batch(jax.random.PRNGKey(1), cfg, args.batch, args.seq,
                        mesh)

    prefetch = args.prefetch or (2 if args.data else 0)
    if prefetch:
        from hpc_patterns_tpu.models.sharding import batch_sharding
        from hpc_patterns_tpu.utils.data import (
            PrefetchLoader,
            memmap_tokens,
            synthetic_tokens,
        )

        if mesh is not None:
            sharding = batch_sharding(mesh, cfg)
            place = lambda b: jax.device_put(b, sharding)
        else:
            place = jax.device_put
        if args.data:
            source = memmap_tokens(
                args.data, batch=args.batch, seq=args.seq,
                dtype=args.data_dtype, steps=args.steps, vocab=cfg.vocab,
            )
        else:
            source = synthetic_tokens(
                jax.random.PRNGKey(1), batch=args.batch, seq=args.seq,
                vocab=cfg.vocab, steps=args.steps,
            )
        batch_iter = iter(PrefetchLoader(source, depth=prefetch,
                                         place=place))
    else:
        batch_iter = None

    losses = []
    t_steps = []
    ckpt_path = None
    diverged = False
    drop_rates_fn = None
    if cfg.n_experts and args.pp <= 1 and args.drop_rate_every > 0:
        # routing-drop telemetry: built ONCE (a fresh jit wrapper per
        # step would re-trace the whole forward every step)
        from hpc_patterns_tpu.models.transformer import moe_drop_rates

        drop_rates_fn = jax.jit(partial(moe_drop_rates, cfg=cfg, mesh=mesh))
    for i in range(args.steps):
        t0 = time.perf_counter()
        batch = next(batch_iter) if batch_iter is not None else tokens
        loss, params, opt_state = step_fn(params, opt_state, batch)
        loss_val = float(loss)  # blocks: readback is the completion fence
        t_steps.append(time.perf_counter() - t0)
        losses.append(loss_val)
        record_step_metrics(i, loss_val, t_steps[-1],
                            args.batch * args.seq)
        extra = {}
        if drop_rates_fn is not None and i % args.drop_rate_every == 0:
            # capacity drops during training are otherwise invisible
            # (they surface only as quality loss): one diagnostic
            # forward on the sampled step's batch
            drops = drop_rates_fn(params, batch)
            extra["moe_drop_rate"] = round(float(drops.max()), 4)
        log.emit(kind="step", step=i, loss=loss_val, dt_s=t_steps[-1],
                 **extra)
        if loss_val != loss_val or abs(loss_val) == float("inf"):
            # failure detection: a diverged run must halt at the first
            # bad step with a diagnostic, not burn the remaining budget
            # training on garbage (the reference's fail-fast error()
            # style, allreduce-mpi-sycl.cpp:79-86, applied to training)
            log.print(f"ERROR: non-finite loss {loss_val} at step {i} — "
                      f"halting early ({args.steps - 1 - i} steps skipped)")
            diverged = True
            break

    finite = all(l == l and abs(l) != float("inf") for l in losses)
    # a 1-step run has nothing to compare, and with --prefetch each step
    # sees a fresh i.i.d. batch (loss noise can exceed a few steps of
    # progress) — finiteness is the check in those modes
    learned = args.steps < 2 or bool(prefetch) or losses[-1] < losses[0]

    if diverged and (args.resume_check or args.checkpoint_dir
                     or args.generate):
        # never persist or decode from a NaN state: a garbage checkpoint
        # stamped with a step count that never ran would poison later
        # restores, and the verdict below is already FAILURE
        log.print("note: checkpoint/resume/generate legs skipped "
                  "(diverged state)")

    resume_ok = True
    if diverged:
        pass
    elif args.resume_check:
        from hpc_patterns_tpu.utils.checkpoint import (
            restore_checkpoint,
            save_checkpoint,
        )
        import tempfile

        ckdir = args.checkpoint_dir or tempfile.mkdtemp(prefix="hpcpat_ckpt_")
        ckpt_path = save_checkpoint(ckdir, params, opt_state, step=args.steps)
        r_params, r_opt, r_step = restore_checkpoint(ckdir, params, opt_state)
        check_batch = tokens
        loss_a, *_ = step_fn(params, opt_state, check_batch)
        loss_b, *_ = step_fn(r_params, r_opt, check_batch)
        resume_ok = float(loss_a) == float(loss_b) and r_step == args.steps
        log.print(f"resume-check: saved {ckpt_path}, losses "
                  f"{float(loss_a):.6f} vs {float(loss_b):.6f}")
    elif args.checkpoint_dir:
        # --checkpoint-dir alone means "save the trained state" (the
        # README's train -> eval lifecycle), not only the resume test
        from hpc_patterns_tpu.utils.checkpoint import save_checkpoint

        ckpt_path = save_checkpoint(args.checkpoint_dir, params, opt_state,
                                    step=args.steps)
        log.print(f"saved {ckpt_path}")

    generate_ok = True
    if diverged:
        pass
    elif args.generate and name != "train":
        log.print("note: --generate skipped (pp params are stage-local; "
                  "decode serves the unpipelined flagship)")
    elif args.generate:
        # serving leg: greedy KV-cache decode from the trained params
        # (single-controller; sharded params are gathered to host first)
        import jax.numpy as jnp

        from hpc_patterns_tpu.models.decode import greedy_generate

        if jax.process_count() > 1:
            log.print("note: --generate skipped (multi-process run)")
        elif args.seq // 2 + args.generate > cfg.max_seq:
            log.print(f"note: --generate {args.generate} skipped "
                      f"(prompt {args.seq // 2} + N > max_seq {cfg.max_seq})")
        else:
            p_local = jax.device_get(params) if mesh is not None else params
            prompt = jax.device_get(tokens)[:, : args.seq // 2]
            toks = greedy_generate(p_local, jnp.asarray(prompt), cfg,
                                   args.generate)
            arr = jax.device_get(toks)
            generate_ok = (
                arr.shape == (prompt.shape[0], args.generate)
                and int(arr.min()) >= 0 and int(arr.max()) < cfg.vocab
            )
            log.print(f"generate: {arr.shape[0]}x{args.generate} tokens, "
                      f"sample {arr[0, :8].tolist()}")

    ok = finite and learned and resume_ok and generate_ok
    # steady state excludes the compile step
    steady = t_steps[1:] or t_steps
    step_s = min(steady)
    tokens_per_s = args.batch * args.seq / step_s
    log.emit(
        kind="result", name=name, success=ok,
        steps=args.steps, loss_first=losses[0], loss_last=losses[-1],
        step_time_s=step_s, tokens_per_s=tokens_per_s,
        mesh=dict(mesh.shape) if mesh else None,
        attention=args.attention, checkpoint=ckpt_path,
        **result_extra,
    )
    label = result_extra.get("label", args.attention)
    log.print(
        f"train[{label}] {args.steps} steps: loss "
        f"{losses[0]:.4f}->{losses[-1]:.4f}, {step_s * 1e3:.1f} ms/step, "
        f"{tokens_per_s:,.0f} tok/s"
    )
    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def _run_pp(args, log, cfg) -> int:
    """--pp path: 1F1B pipeline training (models/pp.py), optionally
    data-parallel (--dp, incl. --dcn-dp across slices), ZeRO-3 stage
    params (--fsdp), Megatron tp inside stages (--tp; dense MLP only),
    host-offloaded optimizer state (--offload-opt), and/or MoE (aux
    loss threaded through the schedule; no sp/ep axes inside stages)."""
    from hpc_patterns_tpu.models import pp as pplib

    if args.sp > 1 or args.ep > 1:
        log.print("ERROR: --pp composes with --dp/--tp/--fsdp/--dcn-dp/"
                  "--offload-opt and --n-experts only (no sp/ep axes "
                  "inside pipeline stages — MoE experts route densely "
                  "per stage)")
        log.print("FAILURE")
        return 1
    tp = args.tp if args.tp > 1 else 1
    if tp > 1:
        try:
            pplib.check_tp(cfg, tp)
        except ValueError as e:
            log.print(f"ERROR: --pp --tp: {e}")
            log.print("FAILURE")
            return 1
    if args.attention not in ("full", "flash"):
        log.print("ERROR: --pp needs a stage-local attention "
                  "(--attention full or flash)")
        log.print("FAILURE")
        return 1
    if args.microbatches < 1:
        log.print(f"ERROR: --microbatches must be >= 1, "
                  f"got {args.microbatches}")
        log.print("FAILURE")
        return 1
    if args.n_layers % args.pp:
        log.print(f"ERROR: --n-layers {args.n_layers} must divide by "
                  f"--pp {args.pp}")
        log.print("FAILURE")
        return 1

    devices = topology.get_devices(args.backend)
    fs = args.fsdp if args.fsdp > 1 else 1
    if args.dcn_dp:
        # dp ACROSS slices: the once-per-step gradient pmean is the
        # latency-tolerant collective; fsdp gathers and the per-tick
        # stage ppermutes stay slice-internal (pp innermost = fastest
        # ICI neighbors)
        groups = topology.group_by_slice(devices)
        n_slices = len(groups)
        dp = n_slices if args.dp == -1 else args.dp
        if dp != n_slices:
            log.print(f"ERROR: --dcn-dp places dp across slices: --dp "
                      f"{args.dp} != slice count {n_slices} (use -1 for "
                      "auto)")
            log.print("FAILURE")
            return 1
        ici = ({"fsdp": fs} if fs > 1 else {}) | {"pp": args.pp}
        if tp > 1:
            ici["tp"] = tp  # innermost: tp rides nearest ICI neighbors
        picked = [d for s in sorted(groups)
                  for d in groups[s][:fs * args.pp * tp]]
        try:
            mesh = topology.make_hybrid_mesh({"dp": dp}, ici, picked)
        except topology.TopologyError as e:
            log.print(f"ERROR: --dcn-dp: {e}")
            log.print("FAILURE")
            return 1
    else:
        dp = args.dp
        axes = {}
        if dp > 1:
            axes["dp"] = dp
        if fs > 1:
            axes["fsdp"] = fs
        axes["pp"] = args.pp
        if tp > 1:
            axes["tp"] = tp  # innermost: tp rides nearest ICI neighbors
        mesh = topology.make_mesh(
            axes, devices[:max(dp, 1) * fs * args.pp * tp])
    if args.batch % (args.microbatches * max(dp, 1) * fs):
        log.print(f"ERROR: --batch {args.batch} must divide by "
                  f"--microbatches*--dp*--fsdp = "
                  f"{args.microbatches * max(dp, 1) * fs}")
        log.print("FAILURE")
        return 1
    optimizer = _make_cli_optimizer(args, log)
    if optimizer is None:
        return 1
    axis_fsdp = "fsdp" if fs > 1 else None
    params, opt_state = pplib.init_pp_train_state(
        jax.random.PRNGKey(0), cfg, optimizer=optimizer,
        mesh=mesh if axis_fsdp else None, axis_fsdp=axis_fsdp,
    )
    offload_example = None
    if args.offload_opt:
        # same platform gating as the sharded-train path: host-memory
        # compute annotations are TPU-only
        if mesh.devices.flat[0].platform != "tpu":
            log.print("note: --offload-opt needs a TPU backend "
                      "(host-memory compute annotations); ignoring")
        else:
            from hpc_patterns_tpu.models.train import offload_opt_state

            hosted = offload_opt_state(opt_state)
            if hosted is opt_state:
                # probe-gated identity fallback: say so instead of
                # logging an offload that did not happen
                log.print("note: pinned_host unusable on this "
                          "backend; optimizer state left in place")
            else:
                opt_state = hosted
                offload_example = opt_state
                log.print("optimizer state offloaded to pinned_host")
    step_fn = pplib.make_pp_train_step(
        cfg, mesh, microbatches=args.microbatches,
        axis_dp="dp" if dp > 1 else None, axis_fsdp=axis_fsdp,
        axis_tp="tp" if tp > 1 else None,
        optimizer=optimizer, offload_opt_example=offload_example,
    )
    label = f"pp={args.pp} 1f1b"
    if tp > 1:
        label += f" tp={tp}"
    if fs > 1:
        label += f" fsdp={fs}"
    if args.dcn_dp:
        label += f" dcn-dp={dp}"
    return _train_loop(
        args, log, cfg, mesh, params, opt_state, step_fn, name="train_pp",
        result_extra={"microbatches": args.microbatches, "label": label},
    )


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    # join a launcher rendezvous when present (apps/launch.py ≙ mpirun):
    # the mesh below is then global and the train step is true
    # multi-process SPMD — the multi-host training path, minus hardware
    topology.init_distributed_from_env()
    if args.prefetch < 0:
        log.print(f"ERROR: --prefetch must be >= 0, got {args.prefetch}")
        log.print("FAILURE")
        return 1
    if args.steps < 1:
        log.print(f"ERROR: --steps must be >= 1, got {args.steps}")
        log.print("FAILURE")
        return 1
    if args.accum > 1 and args.pp > 1:
        log.print("ERROR: --accum composes with the sharded-train path; "
                  "--pp already micro-batches via --microbatches")
        log.print("FAILURE")
        return 1
    if args.remat_policy != "split" and not args.remat:
        log.print("ERROR: --remat-policy has no effect without --remat "
                  "(no checkpointing happens; all activations are saved)")
        log.print("FAILURE")
        return 1
    if args.accum > 1 and args.batch % args.accum:
        log.print(f"ERROR: --batch {args.batch} must divide by "
                  f"--accum {args.accum}")
        log.print("FAILURE")
        return 1
    if args.ep > 1 and not args.n_experts:
        log.print("ERROR: --ep requires --n-experts")
        log.print("FAILURE")
        return 1
    if args.n_experts and args.n_experts % max(args.ep, 1):
        log.print(f"ERROR: --n-experts {args.n_experts} must divide by "
                  f"--ep {args.ep}")
        log.print("FAILURE")
        return 1
    try:
        cfg = TransformerConfig(
            vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, d_ff=4 * args.d_model, max_seq=args.seq,
            attention=args.attention, remat=args.remat, n_experts=args.n_experts,
            n_experts_top_k=args.n_experts_top_k,
            moe_dispatch=args.moe_dispatch,
            n_kv_heads=args.n_kv_heads, pos_embed=args.pos_embed,
            fsdp=args.fsdp > 1, remat_policy=args.remat_policy,
            loss_chunk=args.loss_chunk,
            mlp_impl=args.mlp_impl,
        )
    except ValueError as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    if args.pp > 1:
        return _run_pp(args, log, cfg)
    if args.attention == "flash" and args.sp > 1:
        log.print("ERROR: attention='flash' needs the sequence unsharded "
                  "(--sp 1); use ring_flash for a sharded sequence")
        log.print("FAILURE")
        return 1
    mesh = None
    if args.dcn_dp:
        # multi-slice placement: dp ACROSS slices (the gradient psum is
        # the latency-tolerant, once-per-step collective), every other
        # axis inside one slice so tp/sp/fsdp collectives ride ICI.
        # Devices must be taken per slice, never as a flat prefix.
        devices = topology.get_devices(args.backend)
        groups = topology.group_by_slice(devices)
        n_slices = len(groups)
        dp = n_slices if args.dp == -1 else args.dp
        if dp != n_slices:
            log.print(f"ERROR: --dcn-dp places dp across slices: --dp "
                      f"{args.dp} != slice count {n_slices} (use -1 for "
                      "auto)")
            log.print("FAILURE")
            return 1
        ici = {"sp": args.sp, "tp": args.tp}
        if args.fsdp > 1:
            ici = {"fsdp": args.fsdp, **ici}
        if args.ep > 1:
            ici["ep"] = args.ep
        ici_size = args.sp * args.tp * args.ep * args.fsdp
        picked = [d for s in sorted(groups)
                  for d in groups[s][:ici_size]]
        try:
            mesh = topology.make_hybrid_mesh({"dp": dp}, ici, picked)
        except topology.TopologyError as e:
            log.print(f"ERROR: --dcn-dp: {e}")
            log.print("FAILURE")
            return 1
    else:
        n_mesh = args.dp * args.sp * args.tp * args.ep * args.fsdp
        # every impl except the two single-path ones needs a mesh
        use_mesh = n_mesh > 1 or args.attention not in ("full", "flash")
        if use_mesh:
            devices = topology.get_devices(args.backend)
            axes = {"dp": args.dp, "sp": args.sp, "tp": args.tp}
            if args.fsdp > 1:
                # fsdp between dp and sp: param all-gathers ride links
                # as close as possible without stealing tp/sp's fastest
                axes = {"dp": args.dp, "fsdp": args.fsdp, "sp": args.sp,
                        "tp": args.tp}
            if args.ep > 1:
                axes["ep"] = args.ep
            mesh = topology.make_mesh(axes, devices[:n_mesh])

    optimizer = _make_cli_optimizer(args, log)
    if optimizer is None:
        return 1
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg, mesh,
                                         optimizer=optimizer)
    offload_example = None
    if args.offload_opt:
        # the platform of the devices the state actually lives on (a
        # --backend cpu mesh on a TPU host must NOT offload)
        platform = (mesh.devices.flat[0].platform if mesh is not None
                    else jax.default_backend())
        if platform != "tpu":
            log.print("note: --offload-opt needs a TPU backend "
                      "(host-memory compute annotations); ignoring")
        else:
            from hpc_patterns_tpu.models.train import offload_opt_state

            hosted = offload_opt_state(opt_state)
            if hosted is opt_state:
                # probe-gated identity fallback: say so instead of
                # logging an offload that did not happen
                log.print("note: pinned_host unusable on this "
                          "backend; optimizer state left in place")
            else:
                opt_state = hosted
                offload_example = opt_state
                log.print("optimizer state offloaded to pinned_host")
    step_fn = make_train_step(cfg, mesh, optimizer=optimizer,
                              accum_steps=args.accum,
                              offload_opt_example=offload_example)
    return _train_loop(
        args, log, cfg, mesh, params, opt_state, step_fn, name="train",
        result_extra={},
    )


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
