"""Runlog aggregator: per-phase/per-metric summary over JSONL logs.

Replaces eyeballing raw JSONL (the structured upgrade of grepping
``run.log``, SURVEY.md §5): point it at any run log — one app run or a
whole sweep, one file or several — and it merges every ``kind=metrics``
snapshot (harness/metrics.py) into one table of counters, gauges, and
histogram percentiles, plus a result-record summary. Histogram
percentiles are recomputed from the snapshots' fixed log-spaced bucket
counts, so the table shows exactly what a live registry would
(quantized to bucket resolution — the round-trip guarantee).

Usage::

    python -m hpc_patterns_tpu.harness.report run.jsonl [more.jsonl ...]

Exit 0 when records were read (even with no metrics snapshots — the
result summary still prints); 2 on unreadable/empty input.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path
from typing import Any, Iterable

from hpc_patterns_tpu.harness.metrics import BUCKET_LAYOUT, Gauge, Histogram

# p99 joined in round 8: SLO accounting (harness/slo.py) judges tail
# latency, and a per-phase table without the tail hides exactly the
# requests that blow their targets. Quantized to bucket resolution
# like every column here (the exact-percentile view is slo.py's).
PERCENTILES = (50.0, 95.0, 99.0)


def load_records(paths: Iterable[str | Path]) -> list[dict[str, Any]]:
    """All JSON records across ``paths``, in file-then-line order.
    Unparseable lines are skipped (a crashed run can truncate its last
    line; the rest of the log is still worth aggregating)."""
    records = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return records


def aggregate(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Merge every ``kind=metrics`` snapshot: counters sum, gauges keep
    the last value (min/max/n across snapshots), histograms merge
    bucket counts. Returns the merged tables plus record-kind stats."""
    counters: dict[str, float] = {}
    gauges: dict[str, Gauge] = {}
    histograms: dict[str, Histogram] = {}
    kinds: dict[str, int] = {}
    traces: list[dict[str, Any]] = []
    merged_traces: list[dict[str, Any]] = []
    analyses: list[dict[str, Any]] = []
    reqtraces: list[dict[str, Any]] = []
    budgets: list[dict[str, Any]] = []
    n_ok = n_bad = n_snapshots = n_layout_skipped = 0
    for rec in records:
        kind = rec.get("kind", "?")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "result":
            if rec.get("success"):
                n_ok += 1
            else:
                n_bad += 1
        if kind == "analysis":
            # jaxlint verdict (python -m hpc_patterns_tpu.analysis
            # --log): surface the static-gate outcome next to the
            # runtime rollups
            analyses.append({
                "ok": rec.get("ok", False),
                "findings": rec.get("findings", 0),
                "suppressed": rec.get("suppressed", 0),
                "baselined": rec.get("baselined", 0),
                "files": rec.get("files", 0),
                "by_rule": rec.get("by_rule", {}),
                "vmem": rec.get("vmem"),
            })
        if kind == "trace_merged":
            # cross-rank merge verdict (harness/collect.py): the
            # launcher's skew/straggler rollup over all rank timelines
            merged_traces.append({
                "n_ranks": rec.get("n_ranks", 0),
                "n_matched": rec.get("n_matched", 0),
                "align": rec.get("align", {}),
                "skew": rec.get("skew", {}),
                "schedule": rec.get("schedule", {}),
                "stragglers": rec.get("stragglers", {}),
                "out": rec.get("out"),
                "rollup_out": rec.get("rollup_out"),
            })
        if kind == "reqtrace":
            # request-lifecycle snapshot (harness/reqtrace.py):
            # surface the run's attribution coverage here; the
            # per-class tail table is the explain CLI's job
            # (`python -m hpc_patterns_tpu.harness.explain`)
            reqtraces.append({
                "n": rec.get("n", 0),
                "coverage_frac": rec.get("coverage_frac"),
            })
        if kind == "slo_budget":
            # segment SLO budget breach (harness/budget.py): one row
            # per over-budget (class, axis, segment) — rendered as
            # the per-class breach table next to the percentile tables
            budgets.append({
                "priority": rec.get("priority", 0),
                "axis": rec.get("axis", "?"),
                "segment": rec.get("segment", "?"),
                "share": rec.get("share"),
                "allowance_s": rec.get("allowance_s"),
                "n": rec.get("n", 0),
                "breached": rec.get("breached", 0),
                "worst_s": rec.get("worst_s"),
            })
        if kind == "trace":
            # flight-recorder snapshot (harness/trace.py): summarize
            # the rollups here; the full timeline is the trace CLI's
            # job (`python -m hpc_patterns_tpu.harness.trace`)
            traces.append({
                "n_events": rec.get("n_events", 0),
                "n_dropped": rec.get("n_dropped", 0),
                "by_cat": rec.get("by_cat", {}),
                "compile": rec.get("compile", {}),
                "peak_live_bytes": rec.get("mem", {}).get(
                    "peak_live_bytes", 0),
            })
        if kind != "metrics":
            continue
        n_snapshots += 1
        for name, value in rec.get("counters", {}).items():
            counters[name] = counters.get(name, 0) + value
        for name, snap in rec.get("gauges", {}).items():
            # null means the live value was non-finite (diverged loss):
            # last renders as "-", min/max just don't update
            g = gauges.setdefault(name, Gauge())
            g.last = (math.nan if snap["last"] is None
                      else float(snap["last"]))
            if snap["min"] is not None:
                g.min = min(g.min, float(snap["min"]))
            if snap["max"] is not None:
                g.max = max(g.max, float(snap["max"]))
            g.n += int(snap["n"])
        # bucket indices only mean the same thing under the same layout:
        # a snapshot written under a different one cannot be merged —
        # its percentiles would silently shift by up to a decade
        layout = rec.get("bucket_layout")
        if layout is not None and layout != BUCKET_LAYOUT:
            n_layout_skipped += 1
            continue
        for name, snap in rec.get("histograms", {}).items():
            h = histograms.setdefault(name, Histogram())
            h.merge(Histogram.from_snapshot(snap))
    return {
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "kinds": kinds,
        "traces": traces,
        "merged_traces": merged_traces,
        "analyses": analyses,
        "reqtraces": reqtraces,
        "budgets": budgets,
        "n_snapshots": n_snapshots,
        "n_layout_skipped": n_layout_skipped,
        "results": (n_ok, n_bad),
    }


def _fmt(v: float) -> str:
    if not math.isfinite(v):
        return "-"
    return f"{v:.4g}"


def format_report(agg: dict[str, Any], source: str = "") -> str:
    """The human table. Span histograms (``span.<path>``) are the
    per-phase timing attribution; everything else is per-metric."""
    lines = []
    n_records = sum(agg["kinds"].values())
    ok, bad = agg["results"]
    kinds = ", ".join(f"{k}={n}" for k, n in sorted(agg["kinds"].items()))
    head = f"{n_records} records"
    if source:
        head += f" from {source}"
    head += f" ({kinds})"
    lines.append(head)
    if ok or bad:
        lines.append(f"results: {ok} SUCCESS / {bad} FAILURE")
    for a in agg.get("analyses", []):
        rules = ", ".join(f"{k}={n}"
                          for k, n in sorted(a["by_rule"].items()))
        lines.append(
            f"analysis: {'CLEAN' if a['ok'] else 'FINDINGS'} — "
            f"{a['findings']} finding(s)"
            + (f" [{rules}]" if rules else "")
            + f", {a['suppressed']} suppressed"
            + (f", {a['baselined']} baselined" if a["baselined"] else "")
            + f" across {a['files']} file(s) (jaxlint)")
        vm = a.get("vmem")
        if vm:
            # the pallaslint VMEM budget rollup (analysis/vmem.py):
            # the worst model-dim kernel named so a chip session's
            # lowering failure is never the first warning
            worst = max(vm.get("rows", []),
                        key=lambda r: (r.get("bytes", 0)
                                       / max(r.get("limit", 1), 1)),
                        default=None)
            line = (f"  vmem: {vm.get('kernels', 0)} kernel(s), "
                    f"{vm.get('over_limit', 0)} over model-dim budget")
            if worst is not None:
                line += (f"; worst {worst['kernel']} "
                         f"{worst['bytes'] / 1e6:.1f}/"
                         f"{worst['limit'] / 1e6:.0f} MB")
            lines.append(line)
    for t in agg.get("merged_traces", []):
        worst_name, worst = None, 0.0
        for name, s in t["skew"].items():
            if s.get("max_start_skew_s", 0.0) >= worst:
                worst_name, worst = name, s["max_start_skew_s"]
        strag = max(t["stragglers"].items(),
                    key=lambda kv: kv[1].get("last", 0),
                    default=(None, {}))
        line = (f"trace_merged: {t['n_ranks']} rank(s), "
                f"{t['n_matched']} collective(s) matched "
                f"(clock align: {t['align'].get('method', '?')})")
        if worst_name is not None:
            line += f", max start skew {worst * 1e3:.3f} ms ({worst_name})"
        if strag[0] is not None and strag[1].get("last"):
            line += (f", straggler rank {strag[0]} "
                     f"({strag[1]['last']}/{strag[1].get('of', 0)} last)")
        # the desync check (analysis/runtime.py chains, cross-checked
        # by collect._schedule_check): one glance says whether the
        # ranks PROVABLY ran the same collective program
        sched = t.get("schedule") or {}
        if sched.get("verdict") == "consistent":
            line += (f", schedules consistent "
                     f"({sched.get('n_collectives', 0)} collectives)")
        elif sched.get("verdict") == "divergent":
            fd = sched.get("first_divergence") or {}
            line += f", SCHEDULE DIVERGENCE at #{fd.get('index', '?')}"
        if t.get("out"):
            line += f" — timeline: {t['out']}"
        if t.get("rollup_out"):
            # the versioned rollup artifact (collect --rollup-out):
            # name it so the autofit leg knows what to consume
            line += f", rollup: {t['rollup_out']}"
        lines.append(line)
    for t in agg.get("reqtraces", []):
        cov = t.get("coverage_frac")
        lines.append(
            f"reqtrace: {t['n']} request(s), attribution coverage "
            + (f"{cov:.1%}" if cov is not None else "-")
            + " — attribute: python -m hpc_patterns_tpu.harness.explain")
    if agg.get("budgets"):
        # the per-class breach table (harness/budget.py): which
        # lifecycle segment alone blew the class's TTFT/TPOT target
        lines.append(f"slo budget breaches: {len(agg['budgets'])} "
                     "(class axis segment: worst/allowance, count)")
        lines.append(f"  {'class':<6} {'axis':<5} {'segment':<14} "
                     f"{'share':>6} {'allowance':>10} {'worst':>10} "
                     f"{'count':>8}")
        for b in sorted(agg["budgets"],
                        key=lambda b: (b["priority"], b["axis"],
                                       -(b.get("worst_s") or 0.0))):
            share = (f"{b['share']:.0%}"
                     if b.get("share") is not None else "-")
            allow = (f"{b['allowance_s'] * 1e3:.0f}ms"
                     if b.get("allowance_s") is not None else "-")
            worst = (f"{b['worst_s'] * 1e3:.0f}ms"
                     if b.get("worst_s") is not None else "-")
            lines.append(
                f"  {b['priority']:<6} {b['axis']:<5} "
                f"{b['segment']:<14} {share:>6} {allow:>10} "
                f"{worst:>10} {b['breached']:>4}/{b['n']:<3}")
    for t in agg.get("traces", []):
        cats = ", ".join(f"{k}={n}" for k, n in sorted(t["by_cat"].items()))
        comp = t.get("compile", {})
        mem = t.get("peak_live_bytes", 0)
        lines.append(
            f"trace: {t['n_events']} events ({cats}; "
            f"{t['n_dropped']} evicted), "
            f"{comp.get('count', 0)} compiles "
            f"totalling {_fmt(comp.get('total_s', 0.0))}s"
            + (f", peak live {mem / 1e6:.1f} MB" if mem else "")
            + " — export: python -m hpc_patterns_tpu.harness.trace")
    if not agg["n_snapshots"]:
        lines.append("no kind=metrics snapshots (run apps with "
                     "--metrics --log to record them)")
        return "\n".join(lines)
    lines.append(f"merged {agg['n_snapshots']} metrics snapshot(s)")
    if agg.get("n_layout_skipped"):
        lines.append(
            f"WARNING: histograms from {agg['n_layout_skipped']} "
            "snapshot(s) skipped — written under a different bucket "
            "layout (counters/gauges still merged)")

    if agg["counters"]:
        lines.append("")
        lines.append(f"{'counter':<44} {'total':>12}")
        for name, value in sorted(agg["counters"].items()):
            lines.append(f"{name:<44} {_fmt(value):>12}")

    if agg["gauges"]:
        lines.append("")
        lines.append(f"{'gauge':<44} {'last':>12} {'min':>12} "
                     f"{'max':>12} {'n':>6}")
        for name, g in sorted(agg["gauges"].items()):
            lines.append(f"{name:<44} {_fmt(g.last):>12} {_fmt(g.min):>12} "
                         f"{_fmt(g.max):>12} {g.n:>6}")

    if agg["histograms"]:
        lines.append("")
        cols = " ".join(f"{'p%g' % q:>12}" for q in PERCENTILES)
        lines.append(f"{'histogram':<44} {'count':>8} {cols} {'max':>12}")
        for name, h in sorted(agg["histograms"].items()):
            pcts = " ".join(f"{_fmt(h.percentile(q)):>12}"
                            for q in PERCENTILES)
            hmax = h.max if h.count else math.nan
            lines.append(f"{name:<44} {h.count:>8} {pcts} "
                         f"{_fmt(hmax):>12}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("logs", nargs="+", help="runlog JSONL file(s)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        records = load_records(args.logs)
    except OSError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    if not records:
        print("ERROR: no records in input", file=sys.stderr)
        return 2
    print(format_report(aggregate(records), source=", ".join(args.logs)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
