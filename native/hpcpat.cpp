// Native runtime support library — the C++ side of the framework.
//
// The reference is pure C++ (SURVEY.md §2: "every component below is
// native"); its runtime pieces that are NOT the device compute path —
// aligned allocation (allreduce-mpi-sycl.cpp:19-21,154-159: ALIGNMENT
// 128 vs 2MB sycl::aligned_alloc), buffer init/validation kernels
// (Initialize :33-41, validation :192-204), ring-neighbor scheduling
// (SendRecvRing :43-59), and the timing statistics each app hand-rolls —
// are reimplemented here as a C library the Python layer binds with
// ctypes (no pybind11 in this image). The TPU compute path stays
// JAX/XLA/Pallas; this is the native harness around it.
//
// Build: make -C native   ->  native/libhpcpat.so

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---- timing statistics engine (≙ the min-of-reps protocol every app
// hand-rolls, sycl_con.cpp:101-119) ------------------------------------

// out[0]=min, out[1]=max, out[2]=mean, out[3]=stddev (population)
void hp_stats(const double* xs, int64_t n, double* out) {
  if (n <= 0) {
    out[0] = out[1] = out[2] = out[3] = 0.0;
    return;
  }
  double mn = xs[0], mx = xs[0], sum = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    if (xs[i] < mn) mn = xs[i];
    if (xs[i] > mx) mx = xs[i];
    sum += xs[i];
  }
  double mean = sum / (double)n, var = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    double d = xs[i] - mean;
    var += d * d;
  }
  out[0] = mn;
  out[1] = mx;
  out[2] = mean;
  out[3] = std::sqrt(var / (double)n);
}

// identity pass through native memory; lets Python verify the binding
// end-to-end (timing._native_identity round-trips samples through this)
void hp_roundtrip(const double* in, double* out, int64_t n) {
  std::memcpy(out, in, (size_t)n * sizeof(double));
}

// ---- aligned host allocator (≙ sycl::aligned_alloc with ALIGNMENT,
// allreduce-mpi-sycl.cpp:19-21; 2MB pages in allreduce-usm-...:16-18) ---

void* hp_aligned_alloc(size_t nbytes, size_t alignment) {
  if (alignment == 0 || (alignment & (alignment - 1)) != 0) return nullptr;
  if (nbytes == 0) nbytes = alignment;
  // round size up to a multiple of alignment (posix requirement)
  size_t rounded = (nbytes + alignment - 1) / alignment * alignment;
  void* p = nullptr;
  if (posix_memalign(&p, alignment < sizeof(void*) ? sizeof(void*) : alignment,
                     rounded) != 0)
    return nullptr;
  return p;
}

void hp_free(void* p) { std::free(p); }

// ---- buffer init + analytic validation (≙ Initialize kernel
// allreduce-mpi-sycl.cpp:33-41 and the elementwise oracle check
// :192-204) -------------------------------------------------------------

void hp_fill(float* p, int64_t n, float value) {
  for (int64_t i = 0; i < n; ++i) p[i] = value;
}

void hp_iota(float* p, int64_t n, float base, float step) {
  for (int64_t i = 0; i < n; ++i) p[i] = base + step * (float)i;
}

// returns index of first element with |p[i] - expected| > tol, or -1
int64_t hp_validate(const float* p, int64_t n, float expected, float tol) {
  for (int64_t i = 0; i < n; ++i)
    if (std::fabs(p[i] - expected) > tol) return i;
  return -1;
}

// ---- ring schedule (≙ the neighbor math of SendRecvRing,
// allreduce-mpi-sycl.cpp:43-59: right=(rank+1)%size, left=(rank-1+size)%size,
// with even/odd ordering for deadlock freedom) --------------------------

// writes size (src,dst) pairs for one ring step of `shift`
void hp_ring_plan(int32_t size, int32_t shift, int32_t* src, int32_t* dst) {
  for (int32_t r = 0; r < size; ++r) {
    src[r] = r;
    int32_t d = (r + shift) % size;
    if (d < 0) d += size;
    dst[r] = d;
  }
}

// the even/odd two-phase ordering of the reference (:50-58), exposed so
// tests can assert the deadlock-freedom property (every rank appears in
// exactly one send and one recv per phase)
// phase 0: even ranks send; phase 1: odd ranks send. Returns count.
int32_t hp_ring_phase(int32_t size, int32_t phase, int32_t* senders) {
  int32_t c = 0;
  for (int32_t r = phase; r < size; r += 2) senders[c++] = r;
  return c;
}

}  // extern "C"
