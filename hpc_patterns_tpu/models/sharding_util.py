"""Mesh-spec helpers shared by the model and its sharding rules.

Separate from models/sharding.py (which depends on the model config) so
transformer.py can import these without a cycle.
"""

from __future__ import annotations

from jax.sharding import Mesh, PartitionSpec as P


def resolve_spec(spec: P, mesh: Mesh, allowed: set | None = None) -> P:
    """Drop spec axes the mesh doesn't have (→ replicated on that dim),
    so one rule table serves every mesh shape — a dp-only mesh simply
    replicates the tp/ep-sharded dims, the reference's fallback-to-
    whole-device philosophy (devices.hpp:33-38). Tuple entries (axis
    groups like ``(dp, ep)``) keep only their present members.

    ``allowed``: the axis names pruning is legitimate for (the model
    config's dp/sp/tp/ep set). An absent axis NOT in ``allowed`` is a
    misconfiguration (e.g. a mesh named {"data", "model"} with default
    cfg axis names) and raises instead of silently replicating.
    """

    def fix(ax):
        if isinstance(ax, tuple):
            kept = tuple(fix(a) for a in ax)
            kept = tuple(a for a in kept if a is not None)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        if ax is None or ax in mesh.axis_names:
            return ax
        if allowed is not None and ax not in allowed:
            raise ValueError(
                f"spec axis {ax!r} is neither in the mesh "
                f"{mesh.axis_names} nor a declared model axis "
                f"{sorted(allowed)} — axis-name mismatch?"
            )
        return None

    return P(*(fix(ax) for ax in spec))


def mesh_axis_size(mesh: Mesh, name: str) -> int:
    """Axis size, 1 when the mesh doesn't carry the axis (pruned away)."""
    return mesh.shape[name] if name in mesh.axis_names else 1
