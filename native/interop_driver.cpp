// Native XLA interop driver — C++ executing XLA computations on shared
// buffers, both directions proven with asserts (C10 completion).
//
// The reference's distinctive interop achievement is two RUNTIMES
// sharing one device context: an OpenMP-allocated buffer read by a SYCL
// kernel, and a SYCL-allocated buffer read by an OpenMP kernel, each
// validated elementwise (sycl_omp_ze_interopt/interop_omp_ze_sycl.cpp:
// 81-101). Here the two runtimes are THIS C++ program (which owns
// main(), the allocator, and every assert) and the XLA runtime (hosted
// in an embedded CPython — the binding layer, playing the role the OMP
// interop API plays in the reference: the vehicle for obtaining the
// other runtime's context, not the thing under test).
//
//   Leg 1 (native alloc -> XLA compute; ≙ :81-91): C++ aligned_alloc's
//     a 128-aligned buffer and fills it; XLA dlpack-imports it with
//     ZERO COPY (pointer identity asserted on both sides: the XLA
//     array's device pointer IS the C allocation) and reduces it; C++
//     asserts the reduction against its own double-precision oracle.
//     Alignment is load-bearing: XLA aliases only >=64-byte-aligned
//     imports (the reference's ALIGNMENT constant in TPU-stack form,
//     allreduce-mpi-sycl.cpp:19-21).
//
//   Leg 2 (XLA alloc -> native read, in place; ≙ :93-101): XLA
//     allocates a buffer; C++ reads the raw device memory DIRECTLY
//     (no export, no copy) and validates the fill; XLA then runs a
//     DONATED computation that writes its output into that same buffer
//     (input_output aliasing); C++ re-reads the SAME address and
//     validates the new values — native code watching XLA mutate
//     memory in place.
//
// Mailbox protocol: a C++-owned double[16] whose address is given to
// the embedded interpreter — even the control channel is shared memory.
//   [0] leg-1 zero-copy flag   [1] leg-1 XLA checksum
//   [2] leg-2 buffer address   [3] leg-2 stage flag
//   [4] leg-2 alias flag       [15] python-side fatal-error flag
//
// Usage: interop_driver [--elements N] [--pythonpath A:B:C]
// Exit 0 iff every assert on both sides holds (prints SUCCESS).

#include <Python.h>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

double* g_mail = nullptr;

bool run_py(const char* code) {
  if (PyRun_SimpleString(code) != 0) {
    std::fprintf(stderr, "interop_driver: python stage failed\n");
    return false;
  }
  if (g_mail && g_mail[15] != 0.0) {
    std::fprintf(stderr, "interop_driver: python-side assert failed\n");
    return false;
  }
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  long n = 1 << 16;
  std::string pythonpath;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "--elements") == 0 && i + 1 < argc) {
      n = std::atol(argv[++i]);
    } else if (std::strcmp(argv[i], "--pythonpath") == 0 && i + 1 < argc) {
      pythonpath = argv[++i];
    } else if (std::strcmp(argv[i], "--help") == 0) {
      std::printf("usage: interop_driver [--elements N] [--pythonpath A:B]\n");
      return 0;
    }
  }
  if (n <= 0) {
    std::fprintf(stderr, "interop_driver: bad --elements\n");
    return 2;
  }

  // the embedded XLA must be the host CPU runtime (same memory space as
  // this process — zero-copy is a same-address-space property), never
  // the remote TPU plugin
  setenv("JAX_PLATFORMS", "cpu", 1);
  setenv("JAX_ENABLE_X64", "1", 1);  // exact f64 checksum at any size
  unsetenv("PALLAS_AXON_POOL_IPS");
  if (!pythonpath.empty()) setenv("PYTHONPATH", pythonpath.c_str(), 1);

  // ---- native allocation (leg 1), before any Python exists
  // round the byte size up to a multiple of the alignment: C11 permits
  // aligned_alloc to fail otherwise (e.g. -n 1000 -> 4000 bytes)
  size_t bytes = ((n * sizeof(float) + 127) / 128) * 128;
  float* buf = static_cast<float*>(aligned_alloc(128, bytes));
  double mail[16] = {0};
  g_mail = mail;
  if (!buf) {
    std::fprintf(stderr, "interop_driver: aligned_alloc failed\n");
    return 2;
  }
  double want_sum = 0.0;
  for (long i = 0; i < n; ++i) {
    buf[i] = 0.5f * static_cast<float>(i % 1024);
    want_sum += buf[i];
  }

  Py_Initialize();
  char setup[2048];
  std::snprintf(setup, sizeof(setup),
                "import ctypes, struct, numpy as np\n"
                "import jax, jax.numpy as jnp\n"
                "N = %ld\n"
                "BUF = 0x%llx\n"
                "mail = (ctypes.c_double * 16).from_address(0x%llx)\n"
                "assert jax.devices()[0].platform == 'cpu'\n",
                n, static_cast<unsigned long long>(
                       reinterpret_cast<uintptr_t>(buf)),
                static_cast<unsigned long long>(
                    reinterpret_cast<uintptr_t>(mail)));
  if (!run_py(setup)) return 1;

  // ---- leg 1: XLA reads C++-owned memory, zero copy
  if (!run_py(
          "try:\n"
          "    x = np.ctypeslib.as_array((ctypes.c_float * N)"
          ".from_address(BUF))\n"
          "    arr = jax.dlpack.from_dlpack(x)\n"
          "    ptr = arr.addressable_shards[0].data"
          ".unsafe_buffer_pointer()\n"
          "    mail[0] = 1.0 if ptr == BUF else 0.0\n"
          "    mail[1] = float(jnp.sum(arr.astype(jnp.float64)))\n"
          "except Exception as e:\n"
          "    print('leg1 error:', e)\n"
          "    mail[15] = 1.0\n"))
    return 1;
  if (mail[0] != 1.0) {
    std::fprintf(stderr, "FAILURE: leg1 import copied (no aliasing)\n");
    return 1;
  }
  if (std::fabs(mail[1] - want_sum) > 1e-6 * std::fabs(want_sum)) {
    std::fprintf(stderr, "FAILURE: leg1 checksum %f != %f\n", mail[1],
                 want_sum);
    return 1;
  }
  std::printf("interop_driver leg1 OK: XLA read %ld natively-owned "
              "floats in place (sum %.1f)\n", n, mail[1]);

  // ---- leg 2 stage A: XLA allocates + fills; C++ reads it raw
  if (!run_py(
          "try:\n"
          "    a = jnp.full((N,), 2.0, jnp.float32)\n"
          "    jax.block_until_ready(a)\n"
          "    leg2_ptr = a.addressable_shards[0].data"
          ".unsafe_buffer_pointer()\n"
          // the address crosses the mailbox as its exact uint64 BIT
          // pattern (a double-rounded address >= 2^53 would lose low
          // bits and turn the native re-read into a wild dereference)
          "    mail[2] = struct.unpack('<d', struct.pack('<Q',"
          " leg2_ptr))[0]\n"
          "    mail[3] = 1.0\n"
          "except Exception as e:\n"
          "    print('leg2a error:', e)\n"
          "    mail[15] = 1.0\n"))
    return 1;
  uint64_t leg2_bits = 0;
  std::memcpy(&leg2_bits, &mail[2], sizeof(leg2_bits));
  const float* xla_mem =
      reinterpret_cast<const float*>(static_cast<uintptr_t>(leg2_bits));
  for (long i = 0; i < n; ++i) {
    if (xla_mem[i] != 2.0f) {
      std::fprintf(stderr, "FAILURE: leg2 pre-read [%ld]=%f != 2\n", i,
                   xla_mem[i]);
      return 1;
    }
  }

  // ---- leg 2 stage B: XLA writes IN PLACE (donation); C++ re-reads
  if (!run_py(
          "try:\n"
          "    out = jax.jit(lambda v: v * 3 + 1, donate_argnums=0)(a)\n"
          "    jax.block_until_ready(out)\n"
          "    optr = out.addressable_shards[0].data"
          ".unsafe_buffer_pointer()\n"
          "    mail[4] = 1.0 if optr == leg2_ptr else 0.0\n"
          "except Exception as e:\n"
          "    print('leg2b error:', e)\n"
          "    mail[15] = 1.0\n"))
    return 1;
  if (mail[4] != 1.0) {
    std::fprintf(stderr, "FAILURE: leg2 donation did not alias\n");
    return 1;
  }
  for (long i = 0; i < n; ++i) {
    if (xla_mem[i] != 7.0f) {
      std::fprintf(stderr, "FAILURE: leg2 post-read [%ld]=%f != 7\n", i,
                   xla_mem[i]);
      return 1;
    }
  }
  std::printf("interop_driver leg2 OK: XLA wrote %ld floats in place; "
              "native re-read validated\n", n);

  Py_Finalize();
  free(buf);
  std::printf("SUCCESS\n");
  return 0;
}
